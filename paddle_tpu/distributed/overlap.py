"""Overlap-aware collective scheduling for the sharded train step.

ROADMAP item 5: PR 7 made the wire ~4x narrower (block-scaled int8/fp8
collectives); this module makes it *disappear* behind compute — the
FlexLink direction (stripe one collective across heterogeneous links
concurrently) plus the sharded-update formulation of "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training"
(per-bucket reduce-scatter + delayed gather as the natural overlap
unit).

Three levers, all inside ONE explicit shard_map train step over the
comm axis (the quantized step of distributed/sharding.py, restructured
as scans over layer blocks riding PR 8's stacked-weights layout):

- **Bucketed gradient sync**: the backward pass runs as a reverse scan
  over layer blocks; each layer's grad leaves are partitioned into
  ~``PT_COMM_BUCKET_MB`` buckets (reverse-layer order — the order
  backward produces them) and each bucket rides ONE quantized
  reduce-scatter (``compression.quantized_bucket_reduce_scatter``)
  issued INSIDE the backward scan body, right after that layer's vjp —
  so a bucket's wire time hides under the remaining layers' backward
  compute instead of serializing after it. Per-bucket error-feedback
  state is sliced from ``opt_state["comm_ef"]`` layer by layer by the
  scan.
- **One-layer-ahead weight prefetch** (stage 3): the pre-forward param
  all-gather for layer l+1 is issued at the TOP of layer l's scan body
  (double-buffered carry — the carry holds the gathered weights of the
  layer being computed while the next layer's gather is in flight), so
  the gather leaves the layer critical path; the backward scan
  prefetches layer l-1 the same way.
- **Link striping**: bucket payloads above ``stripe_min`` split into a
  full-precision ICI stripe and a quantized DCN stripe launched
  concurrently (fraction per ``planner.stripe_plan`` — proportional to
  effective link bandwidth so both stripes finish together), recombined
  on arrival.

``overlap=False`` (or ``PT_COMM_OVERLAP=0``) keeps the IDENTICAL math —
same per-layer bucket codec, same error-feedback algebra, bit-identical
parameters (tools/comm_smoke.py asserts this) — but hoists every
collective out of the compute scans: gathers un-prefetched, bucket
reduce-scatters in a tail scan after the full backward. That is the A/B
isolating scheduling from arithmetic, and the baseline the
``train_overlap`` bench row measures against. Measured target: per-step
``comm/exposed_s`` (observability.comm — collective wall time no
concurrent compute span covers) driven toward zero at an unchanged loss
trajectory.
"""

import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.distributed.sharding import (
    GroupShardedSpecs, group_sharded_specs, init_group_sharded_state,
    attach_comm_ef, _ensure_axis, _quant_unsupported_reason, _shard_dims,
    _sharded_update_tail, _strip_axis)

__all__ = ["partition_buckets", "overlap_group_specs", "build_overlap_step",
           "overlap_parallel", "resolve_stripe", "mlp_block_model",
           "DEFAULT_BUCKET_MB"]

DEFAULT_BUCKET_MB = 4.0


def _env_bucket_mb(bucket_mb: Optional[float]) -> float:
    if bucket_mb is not None:
        # ptlint: disable=PT001 -- bucket_mb is a static Python knob
        return float(bucket_mb)
    return float(os.environ.get("PT_COMM_BUCKET_MB",
                                str(DEFAULT_BUCKET_MB)))


def _env_overlap() -> bool:
    return os.environ.get("PT_COMM_OVERLAP", "1").strip().lower() \
        not in ("0", "off", "false", "no")


# effective wire compression of the DCN stripe per format — feeds
# planner.stripe_plan's q so the auto fraction sizes the stripes to
# finish together for the wire that will ACTUALLY run (int8/fp8 block
# 256 ≈ 3.94x measured in PR 7; bf16 = 2x; fp32 = none)
_STRIPE_RATIO = {"int8": 3.94, "fp8": 3.94, "bf16": 2.0, None: 1.0}


def resolve_stripe(stripe, axis: str, mesh: Optional[Mesh] = None,
                   method: Optional[str] = None) -> Optional[float]:
    """One normalization point for the striping knob: an explicit arg
    wins (``None`` falls through to ``PT_COMM_STRIPE``); "0"/"off" →
    no striping; "1"/"on"/"auto" → :func:`planner.stripe_plan`'s
    bandwidth-proportional DCN fraction for this axis, sized with the
    RESOLVED wire format's compression ratio (an int8 stripe carries
    ~4x the logical bytes per wire byte, so it can absorb a larger
    payload share than an fp32 one); a number in (0, 1) forces that
    fraction."""
    if stripe is None:
        stripe = os.environ.get("PT_COMM_STRIPE", "0").strip().lower()
    if isinstance(stripe, str):
        if stripe in ("", "0", "off", "none", "false", "no"):
            return None
        if stripe in ("1", "on", "auto"):
            from paddle_tpu.distributed import planner
            degrees = dict(mesh.shape) if mesh is not None else {}
            n_hosts = int(os.environ.get("PT_NNODES", "1"))
            return planner.stripe_plan(
                degrees, n_hosts,
                quant_ratio=_STRIPE_RATIO.get(method, 1.0)).get(axis)
        stripe = float(stripe)
    f = float(stripe)
    if not 0.0 < f < 1.0:
        return None
    return f


def partition_buckets(leaves: Sequence[Tuple[str, int]],
                      bucket_mb: Optional[float] = None,
                      reverse: bool = True) -> List[List[str]]:
    """Partition named grad leaves into communication buckets.

    ``leaves``: ``[(name, nbytes)]`` in FORWARD production order.
    Returns a list of buckets (each a list of names) in REVERSE order —
    the order backward produces gradients — each closed before a leaf
    that would push it past ``bucket_mb`` MB. A leaf bigger than the
    whole budget therefore forms its own bucket rather than splitting:
    the bucket clamps to the leaf, the same policy as PR 7's quant block
    clamping to tiny leaves, in the other direction. Tiny leaves keep
    accumulating until the budget closes the bucket, so a run of biases
    shares one launch instead of paying per-leaf latency."""
    budget = max(1.0, _env_bucket_mb(bucket_mb) * 2.0 ** 20)
    order = list(reversed(list(leaves))) if reverse else list(leaves)
    buckets: List[List[str]] = []
    cur: List[str] = []
    cur_b = 0.0
    for name, nbytes in order:
        if cur and cur_b + nbytes > budget:
            buckets.append(cur)
            cur, cur_b = [], 0.0
        cur.append(name)
        cur_b += float(nbytes)
    if cur:
        buckets.append(cur)
    return buckets


def overlap_group_specs(params: Dict[str, jax.Array], mesh: Mesh,
                        stacked_keys: Sequence[str],
                        level: str = "p_g_os", axis: str = "fsdp",
                        rules: Optional[Callable[[str], P]] = None
                        ) -> GroupShardedSpecs:
    """:func:`sharding.group_sharded_specs` for the overlap step: stacked
    block leaves (leading layer dim, PR 8's scan layout) never shard dim
    0 — the forward/backward scans slice it — so their comm axis is
    re-derived over the trailing (per-layer) dims."""
    specs = group_sharded_specs(params, mesh, level=level, axis=axis,
                                rules=rules)
    axis_size = dict(mesh.shape)[axis]
    for k in stacked_keys:
        if k not in params:
            raise ValueError(f"stacked key {k!r} not in params")
        v = params[k]
        if v.ndim < 2:
            raise ValueError(f"stacked leaf {k!r} needs a leading layer "
                             f"dim plus at least one per-layer dim, got "
                             f"shape {v.shape}")
        base = rules(k) if rules is not None else P()
        per_layer = P(*tuple(base)[1:])
        if axis_size > 1:
            per_layer = _ensure_axis(per_layer, v.shape[1:], axis,
                                     axis_size)
        stacked_spec = P(None, *tuple(per_layer))
        specs.param[k] = (stacked_spec if level == "p_g_os"
                          else _strip_axis(stacked_spec, axis))
        specs.grad[k] = (stacked_spec if level in ("os_g", "p_g_os")
                         else _strip_axis(stacked_spec, axis))
        specs.opt_slot[k] = stacked_spec
    return specs


def build_overlap_step(embed_fn: Callable, block_fn: Callable,
                       loss_fn: Callable, optimizer,
                       specs: GroupShardedSpecs,
                       stacked_keys: Sequence[str], *,
                       comm_quant: Optional[str] = "auto",
                       comm_block: Optional[int] = None,
                       bucket_mb: Optional[float] = None,
                       overlap: Optional[bool] = None,
                       prefetch: Optional[bool] = None,
                       stripe=None, stripe_min: int = 1 << 16,
                       donate: bool = True):
    """The overlap-scheduled group-sharded train step (module docstring).

    The model arrives in block form — the structure the scheduler needs
    to interleave collectives with per-layer compute:

    - ``embed_fn(nonblock_params, *batch) -> x0``
    - ``block_fn(layer_params, x) -> x`` — one layer, where
      ``layer_params[k]`` is the FULL (gathered) per-layer slice of
      stacked leaf ``k``
    - ``loss_fn(nonblock_params, x_final, *batch) -> scalar`` —
      this replica's local loss (head + criterion)

    ``params`` passed to the returned step hold the stacked block leaves
    named by ``stacked_keys`` (leading layer dim, specs from
    :func:`overlap_group_specs`) plus any non-block leaves; the step
    signature and state layout match the PR 7 quantized step —
    ``step(params, opt_state, *batch) -> (params, opt_state, loss)``
    with the error-feedback residual in ``opt_state["comm_ef"]``
    (:func:`sharding.attach_comm_ef`). ``comm_quant`` ``None``/"fp32"
    runs the same schedule on an fp32 wire; "auto" consults
    ``compression.resolve_comm_quant``. ``overlap``/``bucket_mb``/
    ``stripe`` default to the PT_COMM_OVERLAP / PT_COMM_BUCKET_MB /
    PT_COMM_STRIPE env knobs.

    ``overlap`` moves the bucket reduce-scatters into the backward scan
    body; ``prefetch`` (default: follows ``overlap``) double-buffers the
    stage-3 weight gathers one layer ahead. The two are split because
    their parity classes differ: toggling ``overlap`` alone is
    BIT-IDENTICAL (the barriered per-layer compute and the bucket codec
    are the same subgraphs, only collective placement moves), while
    ``prefetch`` routes the gathered weights through the scan carry,
    whose buffer layout legitimately changes the matmuls' FMA order —
    parity there is float-ulp-level, pinned by the smoke's tolerance.

    With PT_NUMERICS_EVERY > 0 at build time the step additionally
    returns one packed ``observability.numerics`` vector: per-layer
    grad families harvested as extra backward-scan ys (read AFTER the
    ``layer_bwd`` barrier, so the pinned subgraphs are untouched),
    per-bucket quantization-error rows derived from the error-feedback
    algebra (``new_ef`` IS the wire error exactly), and the NaN
    provenance header. The ``train.grad_poison`` fault site corrupts
    one layer's grad slice inside the scan body for localization
    drills. The compiled step exposes ``.numerics_layout`` for
    :class:`numerics.Monitor`.
    """
    from paddle_tpu.distributed import collective as coll
    from paddle_tpu.distributed import compression
    from paddle_tpu.observability import numerics as _nm
    mesh, axis, level = specs.mesh, specs.axis, specs.level
    num_on = _nm.enabled()
    num_box = _nm.LayoutBox()
    stacked = tuple(stacked_keys)
    if not stacked:
        raise ValueError("build_overlap_step needs at least one stacked "
                         "block leaf (use build_group_sharded_step for "
                         "unstructured models)")
    method = comm_quant
    if method == "auto":
        method = compression.resolve_comm_quant(axis=axis, mesh=mesh)
    if method in ("none", "fp32"):
        method = None
    reason = _quant_unsupported_reason(optimizer, specs)
    if reason is not None:
        raise ValueError(f"overlap step: {reason}")
    mesh_shape = dict(mesh.shape)
    n_shard = mesh_shape[axis]
    sdim = _shard_dims(specs)
    for k in stacked:
        if k not in specs.param:
            raise ValueError(f"stacked key {k!r} not in specs")
        if sdim.get(k, 1) == 0:
            raise ValueError(
                f"stacked leaf {k!r}: the layer dim cannot carry the "
                f"comm axis (the scans slice it) — build the specs with "
                f"overlap_group_specs")
    if overlap is None:
        overlap = _env_overlap()
    overlap = bool(overlap)
    do_prefetch = overlap if prefetch is None else bool(prefetch)
    do_prefetch = do_prefetch and level == "p_g_os"
    stripe_f = resolve_stripe(stripe, axis, mesh, method=method)
    bucket_budget = _env_bucket_mb(bucket_mb)
    data_axis = "dp" if axis != "dp" and mesh_shape.get("dp", 1) > 1 \
        else None

    def _dmean(x):
        return lax.pmean(x, data_axis) if data_axis else x

    # stacked leaves the per-layer bucket reduce-scatter covers, bucketed
    # on their per-layer byte volume (static — shapes come from specs'
    # params at trace time); leaves the axis never reached fall to the
    # replicated pmean group at the tail like in the quantized step
    rs_blk = [k for k in stacked if k in sdim]
    raw_blk = [k for k in stacked if k not in sdim]
    gather_blk = [k for k in stacked
                  if k in sdim and level == "p_g_os"]
    quantized = method not in (None, "bf16")

    def per_rank(params, opt_state, *batch):
        idx = lax.axis_index(axis)
        opt_state = dict(opt_state)
        step_count = opt_state["step"]
        ef = jax.tree_util.tree_map(lambda x: x[0],
                                    opt_state.pop("comm_ef"))
        ok = jnp.bool_(True)
        blk = {k: params[k] for k in stacked}
        nb = {k: v for k, v in params.items() if k not in blk}
        L = blk[stacked[0]].shape[0]
        for k, v in blk.items():
            if v.shape[0] != L:
                raise ValueError(f"stacked leaf {k!r} has layer dim "
                                 f"{v.shape[0]}, expected {L}")
        buckets = partition_buckets(
            [(k, 4 * int(np.prod(blk[k].shape[1:]))) for k in rs_blk],
            bucket_budget, reverse=True)

        # ---- stage-3 gather of the non-block params (batched guard) ----
        nb_gather = [k for k in nb if level == "p_g_os" and k in sdim]
        wmax_nb = dict(zip(nb_gather, lax.pmax(jnp.stack(
            [jnp.max(jnp.abs(nb[k])) for k in nb_gather]), axis))) \
            if nb_gather and quantized else {}
        nb_full = {}
        for k, p in nb.items():
            if k in nb_gather:
                if method is None:
                    nb_full[k] = coll.all_gather(p, axis,
                                                 tiled_axis=sdim[k])
                else:
                    f, okk = compression.quantized_all_gather_dequant(
                        p, axis, method, comm_block, dim=sdim[k],
                        vmax_axis=wmax_nb.get(k))
                    ok = ok & okk
                    nb_full[k] = f
            else:
                nb_full[k] = p

        # per-layer block-weight guard envelopes, batched: ONE pmax for
        # every (leaf, layer) instead of a scalar collective per gather
        # inside the scans
        if gather_blk and quantized:
            wmax_blk = lax.pmax(jnp.stack(
                [jnp.max(jnp.abs(blk[k]),
                         axis=tuple(range(1, blk[k].ndim)))
                 for k in gather_blk]), axis).T          # (L, n_gather)
        else:
            wmax_blk = jnp.zeros((L, max(1, len(gather_blk))),
                                 jnp.float32)

        def gather_layer(shards_l, vmax_l):
            """Full per-layer weights from the per-layer shard slices.
            The output rides an optimization_barrier: the gather subgraph
            then compiles identically whether it sits in the compute scan
            (overlap on) or outside it (off) — XLA cannot fuse it into
            the surrounding layer math and perturb bit-parity — while the
            barrier is pure dataflow, so the async collective scheduler
            still hoists the exchange ahead of the compute it feeds."""
            full, okk = {}, jnp.bool_(True)
            for i, k in enumerate(gather_blk):
                d = sdim[k] - 1
                if method is None:
                    full[k] = coll.all_gather(shards_l[k], axis,
                                              tiled_axis=d)
                else:
                    f, o = compression.quantized_all_gather_dequant(
                        shards_l[k], axis, method, comm_block, dim=d,
                        vmax_axis=vmax_l[i] if quantized else None)
                    okk = okk & o
                    full[k] = f
            for k in stacked:
                if k not in gather_blk:
                    full[k] = shards_l[k]
            if full:
                full = lax.optimization_barrier(full)
            return full, okk

        def bucket_sync(dw, ef_l):
            """The per-layer bucketed reduce-scatter: one pmax for every
            bucket's guard envelope, then one bucket codec exchange per
            bucket. Returns ({k: shard}, {k: new_ef}, ok)."""
            outs_s, outs_e = {}, {}
            okk = jnp.bool_(True)
            if not buckets:
                return outs_s, outs_e, okk
            dmeaned = {k: _dmean(dw[k].astype(jnp.float32))
                       for k in rs_blk}
            bmax = lax.pmax(jnp.stack(
                [jnp.max(jnp.stack(
                    [jnp.max(jnp.abs(dmeaned[k] + ef_l[k])) for k in b]))
                 for b in buckets]), axis) if quantized else None
            for i, b in enumerate(buckets):
                sh, ne, o = compression.quantized_bucket_reduce_scatter(
                    {k: dmeaned[k] for k in b},
                    {k: ef_l[k] for k in b},
                    axis, method, comm_block,
                    dims={k: sdim[k] - 1 for k in b},
                    vmax_axis=bmax[i] if quantized else None,
                    stripe=stripe_f, stripe_min=stripe_min)
                outs_s.update(sh)
                outs_e.update(ne)
                okk = okk & o
            return outs_s, outs_e, okk

        n_qrow = max(1, len(buckets))
        num_ev = max(1, _nm.every())
        num_want = ((jnp.asarray(step_count) % num_ev) == 0) \
            if num_on else None

        def _layer_stats(dw, ef_l, new_e):
            """Numerics raws for ONE layer: the (F,5) grad-family rows
            over the stacked leaves plus one (n_bucket,3) quant-error
            row per grad bucket. At cadence >1 the whole side
            computation sits under a lax.cond on the step counter, so
            off-cadence steps pay nothing and emit zeros."""
            def live(_):
                dm = {k: _dmean(dw[k].astype(jnp.float32))
                      for k in stacked}
                fr = jnp.stack([_nm.leaf_raw(dm[k]) for k in stacked])
                if buckets:
                    qr = jnp.stack([_nm.quant_raw(
                        [dm[k] for k in b], [ef_l[k] for k in b],
                        [new_e[k] for k in b]) for b in buckets])
                else:
                    qr = jnp.zeros((n_qrow, 3), jnp.float32)
                return fr, qr

            if num_ev <= 1:
                return live(0)
            return lax.cond(
                num_want, live,
                lambda _: (jnp.zeros((len(stacked), len(_nm.COLS)),
                                     jnp.float32),
                           jnp.zeros((n_qrow, 3), jnp.float32)), 0)

        def layer_fwd(w, x):
            """One layer's forward between optimization_barriers: the
            compute subgraph is then identical whichever schedule
            surrounds it, keeping the overlap on/off A/B a
            scheduling-only change."""
            w, x = lax.optimization_barrier((w, x))
            return lax.optimization_barrier(block_fn(w, x))

        def layer_bwd(w, x_l, dx):
            """One layer's vjp between optimization_barriers (same
            contract as layer_fwd). Returns (dw, dx_in)."""
            w, x_l, dx = lax.optimization_barrier((w, x_l, dx))
            _, bvjp = jax.vjp(block_fn, w, x_l)
            return lax.optimization_barrier(bvjp(dx))

        # ---- forward: scan over layers, weights one gather ahead ------
        x0, embed_vjp = jax.vjp(lambda q: embed_fn(q, *batch), nb_full)
        if do_prefetch and gather_blk:
            # double-buffered carry: compute layer l with the weights the
            # PREVIOUS body (or the prologue) gathered, while this body
            # issues the gather for l+1 — the gather leaves the layer
            # critical path. The rolled xs make the LAST body gather
            # layer 0 again (its ok guard keeps it live); that wasted
            # wraparound gather (one per scan, 1/L of gather traffic —
            # likewise in backward) is the price of uniform scan bodies:
            # peeling the final iteration would compile the last layer
            # as a second body outside the scan, doubling body compiles
            # and splitting the schedule the jaxpr tests pin down.
            w0, ok0 = gather_layer({k: blk[k][0] for k in stacked},
                                   wmax_blk[0])
            ok = ok & ok0

            def fbody(carry, xsl):
                x, w, okk = carry
                sh_next, vm_next = xsl
                w_next, o = gather_layer(sh_next, vm_next)
                y = layer_fwd(w, x)
                return (y, w_next, okk & o), x

            (xN, _, ok), acts = lax.scan(
                fbody, (x0, w0, ok),
                ({k: jnp.roll(blk[k], -1, axis=0) for k in stacked},
                 jnp.roll(wmax_blk, -1, axis=0)))
        else:
            def fbody(carry, xsl):
                x, okk = carry
                sh_l, vm_l = xsl
                w, o = gather_layer(sh_l, vm_l)
                y = layer_fwd(w, x)
                return (y, okk & o), x

            (xN, ok), acts = lax.scan(
                fbody, (x0, ok),
                ({k: blk[k] for k in stacked}, wmax_blk))

        # ---- head loss + backward scan --------------------------------
        loss, head_vjp = jax.vjp(
            lambda q, xf: loss_fn(q, xf, *batch), nb_full, xN)
        dnb, dxN = head_vjp(jnp.ones_like(loss))

        def rev(t):
            return jnp.flip(t, 0)

        ef_rev = {k: rev(ef[k]) for k in rs_blk}
        if overlap and do_prefetch and gather_blk:
            # prologue gathers layer L-1; each body prefetches l-1 and
            # launches the layer's grad buckets right after its vjp
            wl, okl = gather_layer({k: blk[k][L - 1] for k in stacked},
                                   wmax_blk[L - 1])
            ok = ok & okl

            def bbody(carry, xsl):
                dx, w, okk = carry
                x_l, sh_prev, vm_prev, ef_l, l_i = xsl
                w_prev, o = gather_layer(sh_prev, vm_prev)
                dw, dx_in = layer_bwd(w, x_l, dx)
                dw = _nm.poison_layer_slice(dw, l_i, step_count)
                sh_g, new_e, o2 = bucket_sync(dw, ef_l)
                raw = {k: _dmean(dw[k].astype(jnp.float32))
                       for k in raw_blk}
                ys = (sh_g, new_e, raw)
                if num_on:
                    ys += (_layer_stats(dw, ef_l, new_e),)
                return (dx_in, w_prev, okk & o & o2), ys

            (dx0, _, ok), bys = lax.scan(
                bbody, (dxN, wl, ok),
                (rev(acts),
                 {k: jnp.roll(rev(blk[k]), -1, axis=0) for k in stacked},
                 jnp.roll(rev(wmax_blk), -1, axis=0), ef_rev,
                 rev(jnp.arange(L))))
        elif overlap:
            # in-body bucket sync without the double-buffered weight
            # carry: each body re-gathers its own layer, then launches
            # that layer's grad buckets right after the vjp
            def bbody(carry, xsl):
                dx, okk = carry
                x_l, sh_l, vm_l, ef_l, l_i = xsl
                w, o = gather_layer(sh_l, vm_l)
                dw, dx_in = layer_bwd(w, x_l, dx)
                dw = _nm.poison_layer_slice(dw, l_i, step_count)
                sh_g, new_e, o2 = bucket_sync(dw, ef_l)
                raw = {k: _dmean(dw[k].astype(jnp.float32))
                       for k in raw_blk}
                ys = (sh_g, new_e, raw)
                if num_on:
                    ys += (_layer_stats(dw, ef_l, new_e),)
                return (dx_in, okk & o & o2), ys

            (dx0, ok), bys = lax.scan(
                bbody, (dxN, ok),
                (rev(acts), {k: rev(blk[k]) for k in stacked},
                 rev(wmax_blk), ef_rev, rev(jnp.arange(L))))
        else:
            # tail-sync baseline: the SAME per-layer math with every
            # collective hoisted out of the compute scan — backward
            # first, then a separate scan runs the identical bucket
            # codec layer by layer (bit-identical parameters vs the
            # un-prefetched overlap schedule; only collective placement
            # differs)
            def bbody(carry, xsl):
                dx, okk = carry
                x_l, sh_l, vm_l, l_i = xsl
                w, o = gather_layer(sh_l, vm_l)
                dw, dx_in = layer_bwd(w, x_l, dx)
                dw = _nm.poison_layer_slice(dw, l_i, step_count)
                return (dx_in, okk & o), dw

            (dx0, ok), dw_rev = lax.scan(
                bbody, (dxN, ok),
                (rev(acts), {k: rev(blk[k]) for k in stacked},
                 rev(wmax_blk), rev(jnp.arange(L))))

            def tail(okk, xsl):
                dw_l, ef_l = xsl
                sh_g, new_e, o2 = bucket_sync(dw_l, ef_l)
                raw = {k: _dmean(dw_l[k].astype(jnp.float32))
                       for k in raw_blk}
                ys = (sh_g, new_e, raw)
                if num_on:
                    ys += (_layer_stats(dw_l, ef_l, new_e),)
                return okk & o2, ys

            ok, bys = lax.scan(tail, ok, (dw_rev, ef_rev))

        sh_rev, efo_rev, raw_rev = bys[0], bys[1], bys[2]
        num_blk = bys[3] if num_on else None
        sh_blk = {k: rev(v) for k, v in sh_rev.items()}
        new_ef_blk = {k: rev(v) for k, v in efo_rev.items()}
        raw_g = {k: rev(v) for k, v in raw_rev.items()}
        (dnb_e,) = embed_vjp(dx0)
        dnb = jax.tree_util.tree_map(lambda a, b: a + b, dnb, dnb_e)

        # ---- non-block grads: the tail bucket set ---------------------
        nb_rs = [k for k in nb if k in sdim]
        nb_buckets = partition_buckets(
            [(k, 4 * int(np.prod(nb[k].shape))) for k in nb_rs],
            bucket_budget, reverse=True)
        shard_g, new_ef = dict(sh_blk), dict(new_ef_blk)
        nb_q_src = []
        if nb_buckets:
            dmeaned = {k: _dmean(dnb[k].astype(jnp.float32))
                       for k in nb_rs}
            nmax = lax.pmax(jnp.stack(
                [jnp.max(jnp.stack(
                    [jnp.max(jnp.abs(dmeaned[k] + ef[k])) for k in b]))
                 for b in nb_buckets]), axis) if quantized else None
            for i, b in enumerate(nb_buckets):
                sh, ne, o = compression.quantized_bucket_reduce_scatter(
                    {k: dmeaned[k] for k in b}, {k: ef[k] for k in b},
                    axis, method, comm_block,
                    dims={k: sdim[k] for k in b},
                    vmax_axis=nmax[i] if quantized else None,
                    stripe=stripe_f, stripe_min=stripe_min)
                shard_g.update(sh)
                new_ef.update(ne)
                ok = ok & o
                if num_on:
                    # raw refs only — the quant_raw reductions run
                    # inside the cadence-gated pack below
                    nb_q_src.append(([dmeaned[k] for k in b],
                                     [ef[k] for k in b],
                                     [ne[k] for k in b]))
        for k in nb:
            if k not in sdim:
                shard_g[k] = _dmean(lax.pmean(
                    dnb[k].astype(jnp.float32), axis))
                new_ef[k] = ef[k]
        for k in raw_blk:
            shard_g[k] = lax.pmean(raw_g[k], axis)
            new_ef[k] = ef[k]

        # ---- sharded update (≙ the quantized step's owner update) -----
        shard_p = {}
        for k in params:
            if k in sdim and level == "os_g":
                d = params[k].shape[sdim[k]] // n_shard
                shard_p[k] = lax.dynamic_slice_in_dim(
                    params[k], idx * d, d, axis=sdim[k])
            else:
                shard_p[k] = params[k]
        out_p, out_s, out_loss = _sharded_update_tail(
            optimizer, opt_state, shard_p, shard_g, new_ef, ok, loss,
            level=level, axis=axis, sdim=sdim, dmean=_dmean)
        if not num_on:
            return out_p, out_s, out_loss

        def build():
            pk = _nm.Packer()
            fr = rev(num_blk[0])                          # (L, F, 5)
            for i, k in enumerate(stacked):
                pk.family(f"grad/{k}", fr[:, i, :],
                          int(np.prod(blk[k].shape[1:])) or 1)
            pool = [_dmean(dnb[k].astype(jnp.float32)) for k in nb]
            if pool:
                pk.family("grad/(rest)", _nm.pooled_raw(pool),
                          sum(int(np.prod(nb[k].shape)) for k in nb))
            # per-bucket quant rows: sum the per-layer raws over the
            # layer axis, then the exact cross-rank reduction
            pk.quant("blk", lax.psum(jnp.sum(num_blk[1], axis=0),
                                     axis))
            if nb_q_src:
                pk.quant("nb", lax.psum(jnp.stack(
                    [_nm.quant_raw(g, e, n) for g, e, n in nb_q_src]),
                    axis))
            packed = pk.pack(loss=out_loss, box=num_box)
            packed = lax.pmean(packed, axis)
            if data_axis:
                packed = lax.pmean(packed, data_axis)
            return packed

        packed = _nm.cond_every(step_count, num_ev, build)
        return out_p, out_s, out_loss, packed

    ef_spec = {k: P(axis) for k in specs.param}
    state_spec = {"step": P(), "slots": dict(specs.opt_slot),
                  "comm_ef": ef_spec}
    batch_spec = P(data_axis) if data_axis else P()

    out_tail = (P(), P()) if num_on else (P(),)

    def step(params, opt_state, *batch):
        smapped = shard_map(
            per_rank, mesh=mesh,
            in_specs=(dict(specs.param), state_spec)
            + (batch_spec,) * len(batch),
            out_specs=(dict(specs.param), state_spec) + out_tail,
            check_vma=False)
        return smapped(params, opt_state, *batch)

    kw = {"donate_argnums": (0, 1)} if donate else {}
    fn = jax.jit(step, **kw)
    fn.numerics_layout = num_box
    return fn


def overlap_parallel(params: Dict[str, jax.Array], embed_fn: Callable,
                     block_fn: Callable, loss_fn: Callable, optimizer,
                     mesh: Mesh, stacked_keys: Sequence[str],
                     level: str = "p_g_os", axis: str = "fsdp",
                     rules: Optional[Callable[[str], P]] = None,
                     comm_quant: Optional[str] = "auto", **step_kw):
    """One-call API for the overlap-scheduled step, mirroring
    :func:`sharding.group_sharded_parallel`: derives the stacked-aware
    specs, places the state, always attaches the error-feedback residual
    (zeros stay zeros on an fp32 wire, so the step signature never
    depends on the resolved format), and builds the step.

    Returns ``(sharded_params, sharded_opt_state, jitted_train_step)``.
    """
    specs = overlap_group_specs(params, mesh, stacked_keys, level=level,
                                axis=axis, rules=rules)
    full_params = params
    params, opt_state = init_group_sharded_state(params, optimizer, specs)
    opt_state = attach_comm_ef(full_params, opt_state, specs)
    step = build_overlap_step(embed_fn, block_fn, loss_fn, optimizer,
                              specs, stacked_keys, comm_quant=comm_quant,
                              **step_kw)
    return params, opt_state, step


def mlp_block_model(n_layers: int = 4, d: int = 16, hidden: int = 32,
                    k: int = 8, seed: int = 0):
    """Tiny residual stacked-MLP in the overlap step's block form — the
    shared harness the overlap tests / comm smoke / ``train_overlap``
    bench row drive the scheduler with (a real model supplies its own
    embed/block/loss triple the same way). Returns
    ``(params, stacked_keys, embed_fn, block_fn, loss_fn)``; the batch
    is ``(x (B, d), y (B, k))``."""
    rs = np.random.RandomState(seed)
    params = {
        "w_in": jnp.asarray(rs.randn(d, d) * 0.3, jnp.float32),
        "blocks.w1": jnp.asarray(rs.randn(n_layers, d, hidden) * 0.2,
                                 jnp.float32),
        "blocks.b1": jnp.zeros((n_layers, hidden), jnp.float32),
        "blocks.w2": jnp.asarray(rs.randn(n_layers, hidden, d) * 0.2,
                                 jnp.float32),
        "w_out": jnp.asarray(rs.randn(d, k) * 0.3, jnp.float32),
    }
    stacked = ("blocks.w1", "blocks.b1", "blocks.w2")

    def embed_fn(nb, x, y):
        return x @ nb["w_in"]

    def block_fn(w, h):
        return h + jnp.tanh(h @ w["blocks.w1"]
                            + w["blocks.b1"]) @ w["blocks.w2"]

    def loss_fn(nb, h, x, y):
        return jnp.mean((h @ nb["w_out"] - y) ** 2)

    return params, stacked, embed_fn, block_fn, loss_fn
