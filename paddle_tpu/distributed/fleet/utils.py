"""fleet.utils (ref: python/paddle/distributed/fleet/utils/fs.py —
LocalFS/HDFSClient file-system abstraction the PS/elastic stack uses for
checkpoints; fleet/utils/__init__.py recompute re-export).

LocalFS is complete; HDFSClient requires a hadoop client binary, which
this environment does not ship — constructing it raises with guidance
rather than failing on first use."""

import os
import shutil

from paddle_tpu.distributed.recompute import recompute  # noqa: F401

__all__ = ["LocalFS", "HDFSClient", "recompute"]


class LocalFS:
    """(≙ fs.py LocalFS) — posix-backed implementation of the FS API."""

    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.replace(src, dst)

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src):
            raise FileNotFoundError(src)
        if not overwrite and self.is_exist(dst):
            raise FileExistsError(dst)
        os.replace(src, dst)

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def touch(self, path, exist_ok=True):
        if not exist_ok and self.is_exist(path):
            raise FileExistsError(path)
        open(path, "a").close()

    def cat(self, path):
        with open(path, "rb") as f:
            return f.read()

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


class HDFSClient:
    """(≙ fs.py HDFSClient) — needs the hadoop CLI, absent here."""

    def __init__(self, hadoop_home=None, configs=None, *a, **kw):
        raise RuntimeError(
            "HDFSClient requires a hadoop client installation; this "
            "TPU image has none. Use LocalFS (same API) or mount the "
            "store through a fuse/local path.")
