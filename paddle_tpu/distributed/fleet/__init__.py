"""paddle.distributed.fleet — the hybrid-parallel user entry point
(ref: python/paddle/distributed/fleet/fleet.py — Fleet.init:166,
distributed_model, distributed_optimizer, worker_index:454/worker_num:472,
get_hybrid_communicate_group:427; base/distributed_strategy.py
DistributedStrategy:109 with hybrid_configs/amp/recompute/sharding).

TPU-native mapping: `fleet.init(strategy)` builds the named device mesh
from `strategy.hybrid_configs` (≙ _init_hybrid_parallel_env building
HybridCommunicateGroup); `distributed_model` shards parameters onto it
through the structural planner (≙ wrapping in PipelineParallel /
TensorParallel / ShardingParallel classes — here GSPMD owns the
communication so one sharded pytree replaces the four wrapper classes);
`distributed_optimizer` applies the strategy's amp/gradient-merge
switches. The protobuf serialization dissolves — the strategy is a plain
attribute object.
"""

from typing import Optional

__all__ = ["DistributedStrategy", "init", "distributed_model", "utils",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_index", "worker_num", "is_first_worker", "worker_endpoints",
           "barrier_worker", "stop_worker", "UserDefinedRoleMaker",
           "PaddleCloudRoleMaker"]


class _HybridConfigs(dict):
    """dict with attribute access; unknown degrees default to 1."""

    def __getattr__(self, k):
        return self.get(k)

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    """(≙ base/distributed_strategy.py:109). The switches that exist on
    this stack; reference-only GPU knobs (cudnn_*, nccl_*) are absent
    rather than silently accepted."""

    def __init__(self):
        self.hybrid_configs = _HybridConfigs(
            dp_degree=1, mp_degree=1, pp_degree=1, sharding_degree=1,
            sep_degree=1, ep_degree=1)
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 2.0 ** 15, "use_pure_fp16":
                            False}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {"stage": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.find_unused_parameters = False
        # "bf16" | "int8" | None — compress the dp gradient exchange with
        # error feedback (≙ meta_optimizers/dgc_optimizer.py; see
        # distributed/compression.py for when to use)
        self.grad_compression = None

    def __repr__(self):
        return (f"DistributedStrategy(hybrid={dict(self.hybrid_configs)}, "
                f"amp={self.amp}, recompute={self.recompute}, "
                f"sharding={self.sharding})")


_strategy: Optional[DistributedStrategy] = None
_topo = None


def init(role_maker=None, is_collective=True, strategy=None):
    """(≙ Fleet.init:166) — rendezvous (when launched multi-process) and
    build the hybrid mesh from strategy.hybrid_configs."""
    global _strategy, _topo
    import os
    from paddle_tpu.distributed import env as env_mod
    from paddle_tpu.distributed import mesh as mesh_lib
    _strategy = strategy or DistributedStrategy()
    if os.environ.get("PT_WORLD_SIZE", "1") != "1" \
            and not env_mod.is_initialized():
        env_mod.init_parallel_env()
    hc = _strategy.hybrid_configs
    import jax
    n = len(jax.devices())
    degrees = {"dp": hc.get("dp_degree", 1) or 1,
               "tp": hc.get("mp_degree", 1) or 1,
               "pp": hc.get("pp_degree", 1) or 1,
               "fsdp": hc.get("sharding_degree", 1) or 1,
               "sp": hc.get("sep_degree", 1) or 1,
               "ep": hc.get("ep_degree", 1) or 1}
    # reference semantics: dp_degree = -1 (or unset remainder) absorbs the
    # devices the explicit degrees don't cover — including absorbing down
    # to 1 when the explicit degrees already cover everything
    explicit = 1
    for k, v in degrees.items():
        if k != "dp":
            explicit *= v
    if degrees["dp"] == -1 or (degrees["dp"] == 1 and explicit != n):
        if n % explicit != 0:
            raise ValueError(f"device count {n} not divisible by "
                             f"non-dp degrees product {explicit}")
        degrees["dp"] = n // explicit
    _topo = mesh_lib.init_mesh(**degrees)
    return _topo


def _require_init():
    if _topo is None:
        raise RuntimeError("call fleet.init() first")
    return _topo


def get_hybrid_communicate_group():
    """(≙ get_hybrid_communicate_group:427) — the HybridTopology carries
    the same queries (get_model_parallel_world_size, ...)."""
    return _require_init()


def distributed_model(model):
    """(≙ Fleet.distributed_model) — shard parameters over the fleet mesh
    via the structural planner; GSPMD inserts the collectives the
    reference's wrapper classes issue manually."""
    topo = _require_init()
    from paddle_tpu.distributed.api import shard_module
    return shard_module(model, auto=True, mesh=topo.mesh)


class _FleetOptimizer:
    """(≙ Fleet.distributed_optimizer product) — the underlying optimizer
    with the strategy's amp/gradient-merge behaviors attached. Gradients
    are already mesh-reduced by GSPMD; what remains of the reference's
    wrapper is loss scaling and k-step gradient merge."""

    def __init__(self, inner, strategy):
        self._inner = inner
        self._strategy = strategy
        self.scaler = None
        if strategy.amp:
            from paddle_tpu.amp import GradScaler
            self.scaler = GradScaler(init_loss_scaling=strategy.amp_configs[
                "init_loss_scaling"])
        self._merge_k = (strategy.gradient_merge_configs["k_steps"]
                         if strategy.gradient_merge else 1)
        self._wstate = None

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def init(self, params):
        """Merge state lives IN the state pytree (jnp counter + buffer),
        not on the wrapper — Python-side counters would be baked in at
        trace time and silently freeze training under jit."""
        import jax
        import jax.numpy as jnp
        st = {"inner": self._inner.init(params)}
        if self._merge_k > 1:
            st["gm_buf"] = jax.tree_util.tree_map(jnp.zeros_like, params)
            st["gm_n"] = jnp.zeros((), jnp.int32)
        return st

    def step(self, grads):
        """Paddle-style bound step MUST route through this wrapper's
        update() — falling through to the inner step() would silently
        bypass gradient-merge/amp."""
        import jax
        import jax.numpy as jnp
        self._inner._ensure_bound()
        if self._wstate is None and self._merge_k > 1:
            self._wstate = {
                "gm_buf": jax.tree_util.tree_map(jnp.zeros_like,
                                                 self._inner._params),
                "gm_n": jnp.zeros((), jnp.int32)}
        # the inner optimizer's state is AUTHORITATIVE every step (a
        # set_state_dict checkpoint restore writes there); only the
        # merge slots persist on the wrapper
        st = {"inner": self._inner._state}
        if self._wstate is not None:
            st.update(self._wstate)
        new_p, new_st = self.update(grads, st, self._inner._params)
        self._inner._params = new_p
        self._inner._state = new_st["inner"]
        if self._merge_k > 1:
            self._wstate = {"gm_buf": new_st["gm_buf"],
                            "gm_n": new_st["gm_n"]}
        return new_p

    def state_dict(self):
        d = self._inner.state_dict()
        if self._wstate is not None:
            d["gradient_merge"] = dict(self._wstate)
        return d

    def set_state_dict(self, d):
        self._inner.set_state_dict(d)
        self._wstate = (dict(d["gradient_merge"])
                        if "gradient_merge" in d else None)

    def update(self, grads, state, params):
        import jax
        import jax.numpy as jnp
        tm = jax.tree_util.tree_map
        if "inner" not in state:  # tolerate a raw inner-state pytree
            state = {"inner": state}
        if self._merge_k > 1 and "gm_buf" not in state:
            state = dict(state)
            state["gm_buf"] = tm(jnp.zeros_like, params)
            state["gm_n"] = jnp.zeros((), jnp.int32)
        if self._merge_k <= 1:
            new_p, inner_s = self._inner.update(grads, state["inner"],
                                                params)
            out = dict(state)
            out["inner"] = inner_s
            return new_p, out
        # jit-safe k-step merge: compute the would-be update every call
        # and SELECT it on step boundaries (no Python branch on a tracer)
        k = self._merge_k
        buf = tm(lambda b, g: b + g, state["gm_buf"], grads)
        n = state["gm_n"] + 1
        do = (n % k) == 0
        eff = tm(lambda b: b / k, buf)
        upd_p, upd_s = self._inner.update(eff, state["inner"], params)
        new_p = tm(lambda a, b: jnp.where(do, a, b), upd_p, params)
        new_inner = tm(lambda a, b: jnp.where(do, a, b), upd_s,
                       state["inner"])
        new_buf = tm(lambda b: jnp.where(do, jnp.zeros_like(b), b), buf)
        return new_p, {"inner": new_inner, "gm_buf": new_buf, "gm_n": n}


def distributed_optimizer(optimizer, strategy=None):
    _require_init()
    return _FleetOptimizer(optimizer, strategy or _strategy
                           or DistributedStrategy())


def build_dp_train_step(loss_fn, optimizer, strategy=None):
    """Data-parallel train step honoring ``strategy.grad_compression``:
    the dp gradient exchange runs through the compressed channel with
    error feedback (distributed/compression.py) when set, plain GSPMD
    psum otherwise. Signature either way:
    ``step(params, opt_state, ef, batch) -> (params, opt_state, ef,
    loss)`` — build ``ef`` with ``compression.init_error_feedback`` when
    compression is on, pass ``()`` otherwise.

    ``loss_fn(params, batch) -> scalar`` per-replica; batch dim 0 splits
    over dp. ≙ dgc_optimizer.minimize wiring under fleet.
    """
    topo = _require_init()
    strat = strategy or _strategy or DistributedStrategy()
    # the _FleetOptimizer wrapper stays in the loop (its update() carries
    # the strategy's gradient-merge slots), and BOTH settings build the
    # same shard_map step — method=None is a plain fp32 pmean, so
    # toggling compression changes only the wire format
    from paddle_tpu.distributed.compression import build_compressed_dp_step
    return build_compressed_dp_step(loss_fn, optimizer, topo.mesh,
                                    strat.grad_compression)


# -- worker queries (≙ Fleet.worker_index:454 etc.) --------------------------

def worker_index():
    from paddle_tpu.distributed.env import get_rank
    return get_rank()


def worker_num():
    from paddle_tpu.distributed.env import get_world_size
    return get_world_size()


def is_first_worker():
    return worker_index() == 0


def worker_endpoints(to_string=False):
    import os
    eps = os.environ.get("PT_TRAINER_ENDPOINTS", "").split(",") \
        if os.environ.get("PT_TRAINER_ENDPOINTS") else []
    return ",".join(eps) if to_string else eps


def barrier_worker():
    from paddle_tpu.distributed.collective import barrier
    barrier()


def stop_worker():
    """(≙ Fleet.stop_worker) — collective mode has no PS workers to stop;
    provided for script parity."""


class UserDefinedRoleMaker:
    """(≙ fleet.base.role_maker.UserDefinedRoleMaker shim)."""

    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        self.is_collective = is_collective
        self.kwargs = kwargs


class PaddleCloudRoleMaker(UserDefinedRoleMaker):
    """(≙ role_maker.PaddleCloudRoleMaker) — roles come from PT_* env."""


from paddle_tpu.distributed.fleet import utils  # noqa: E402
