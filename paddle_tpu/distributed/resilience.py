"""Resilient distributed runtime: retry/backoff/deadline policies and a
collective watchdog.

Reference analog: the reference's production serving stack assumes
workers die and stores partition — fleet/elastic/manager.py restarts
trainer groups, the brpc layer retries RPCs with timeouts — but each
caller hand-rolls its own policy. Here there is ONE policy object
(`RetryPolicy`: exponential backoff + jitter + an absolute deadline), one
deadline primitive (`Deadline`), guarded wrappers for the store and
control-plane ops (`store_get`, `with_deadline`), and a
`CollectiveWatchdog` that converts "a rank hung inside a barrier" — the
classic undiagnosable distributed failure — into an exception naming the
stalled rank(s).

Watchdog design: the same counter-not-clock trick as `elastic.py`
heartbeats. Each rank bumps a per-rank progress counter in the TCPStore
when it *enters* guarded collective #k, then waits (bounded by the
deadline) for every peer's counter to reach k before running the real
collective. A peer that never arrives leaves its counter behind, so the
waiting ranks raise `CollectiveStallError` naming exactly the laggards —
instead of blocking forever inside an un-interruptible native collective.
Cross-host clock skew cannot fake a stall because only counter *progress*
is judged, against the local monotonic clock.

Every retry / timeout / stall increments a `resilience/*` counter in
`paddle_tpu.stats` (§5.5 observability surface; see docs/resilience.md).
"""

import dataclasses
import random as _random
import time
from typing import Callable, Optional, Tuple

__all__ = ["Deadline", "DeadlineExceeded", "CollectiveStallError",
           "RetryPolicy", "with_deadline", "store_get", "store_set",
           "CollectiveWatchdog", "DEFAULT_POLICY"]


class DeadlineExceeded(TimeoutError):
    """An operation (including all its retries) overran its absolute
    deadline. Subclasses TimeoutError so existing timeout handlers
    (p2p recv rollback, elastic liveness) treat it uniformly."""


class CollectiveStallError(RuntimeError):
    """A guarded collective was entered by this rank but one or more
    peers never arrived within the deadline. ``stalled_ranks`` names
    them; the message includes each laggard's last observed progress."""

    def __init__(self, message: str, stalled_ranks=()):
        super().__init__(message)
        self.stalled_ranks = tuple(stalled_ranks)


class Deadline:
    """Absolute time budget, measured on the local monotonic clock.

    ``seconds=None`` means unbounded (remaining() == None, never
    expired) so call sites can thread one object through both bounded
    and unbounded paths.
    """

    __slots__ = ("seconds", "_t0")

    def __init__(self, seconds: Optional[float]):
        self.seconds = None if seconds is None else float(seconds)
        self._t0 = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def remaining(self) -> Optional[float]:
        if self.seconds is None:
            return None
        return self.seconds - self.elapsed()

    @property
    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0

    def budget(self, want: float, floor: float = 0.001) -> float:
        """Clamp a per-attempt timeout to what's left of the deadline
        (never below ``floor`` — native calls reject non-positive
        timeouts)."""
        r = self.remaining()
        return max(floor, want if r is None else min(want, r))

    def check(self, op: str = "operation"):
        if self.expired:
            raise DeadlineExceeded(
                f"{op} exceeded its {self.seconds}s deadline "
                f"(elapsed {self.elapsed():.2f}s)")


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff + jitter + absolute deadline.

        policy = RetryPolicy(max_attempts=5, deadline=30.0)
        value = policy.run(lambda: store.get(key), op="rendezvous_get")

    The deadline bounds the WHOLE call including every backoff sleep —
    a caller holding a peer at a barrier must fail within a known
    budget, not after max_attempts of unbounded waits. Retries and
    deadline overruns surface as ``resilience/retries`` /
    ``resilience/deadline_exceeded`` (plus per-op variants) in
    `paddle_tpu.stats`.
    """

    max_attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25          # +- fraction of the computed delay
    deadline: Optional[float] = 30.0

    def delay_for(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based)."""
        d = min(self.base_delay * self.multiplier ** (attempt - 1),
                self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * _random.random() - 1.0)
        return max(0.0, d)

    def run(self, fn: Callable, *, op: str = "op",
            retry_on: Tuple = (TimeoutError, ConnectionError, OSError),
            deadline: Optional["Deadline"] = None):
        from paddle_tpu import stats
        dl = deadline or Deadline(self.deadline)
        attempt = 0
        while True:
            try:
                return fn()
            except DeadlineExceeded:
                raise           # an inner deadline is final, never retried
            except retry_on as e:
                attempt += 1
                stats.add("resilience/retries")
                stats.add(f"resilience/{op}/retries")
                if attempt >= self.max_attempts:
                    stats.add("resilience/retries_exhausted")
                    raise
                if dl.expired:
                    stats.add("resilience/deadline_exceeded")
                    raise DeadlineExceeded(
                        f"{op} failed after {attempt} attempts over "
                        f"{dl.elapsed():.2f}s (deadline {dl.seconds}s): "
                        f"{e!r}") from e
                time.sleep(dl.budget(self.delay_for(attempt), floor=0.0))


DEFAULT_POLICY = RetryPolicy()


def with_deadline(fn: Callable, seconds: Optional[float],
                  op: Optional[str] = None,
                  policy: Optional[RetryPolicy] = None,
                  retry_on: Tuple = (TimeoutError, ConnectionError,
                                     OSError)) -> Callable:
    """Wrap a zero-arg-compatible callable so every invocation runs
    under a fresh ``seconds`` deadline with retry/backoff on transient
    errors (``retry_on``, default TimeoutError / ConnectionError /
    OSError — widen it for libraries that wrap transport failures in
    RuntimeError, e.g. jax's XlaRuntimeError).

        guarded = with_deadline(lambda: jax.distributed.initialize(...),
                                seconds=120.0, op="collective_init")
        guarded()
    """
    name = op or getattr(fn, "__name__", "op")
    pol = policy or DEFAULT_POLICY

    def wrapped(*args, **kwargs):
        return pol.run(lambda: fn(*args, **kwargs), op=name,
                       retry_on=retry_on, deadline=Deadline(seconds))

    wrapped.__name__ = f"with_deadline[{name}]"
    return wrapped


def store_get(store, key: str, *, deadline: float = 30.0,
              policy: Optional[RetryPolicy] = None, op: str = "store_get"):
    """Deadline-guarded TCPStore get: each attempt's native timeout is
    the deadline *remainder* (so retries after transient connection
    errors cannot extend the total budget), and the whole call fails
    with `DeadlineExceeded` naming the key. Fault site: ``store.get``."""
    from paddle_tpu import stats
    from paddle_tpu.testing import faults

    pol = policy or DEFAULT_POLICY
    dl = Deadline(deadline)

    def attempt():
        faults.fire("store.get")
        dl.check(f"store.get({key!r})")
        return store.get(key, timeout=dl.budget(deadline))

    try:
        return pol.run(attempt, op=op, deadline=dl)
    except TimeoutError as e:
        if isinstance(e, DeadlineExceeded):
            raise
        stats.add("resilience/deadline_exceeded")
        raise DeadlineExceeded(
            f"store.get({key!r}) exceeded its {deadline}s deadline") from e


def store_set(store, key: str, value, *,
              policy: Optional[RetryPolicy] = None, op: str = "store_set"):
    """Retried TCPStore set (transient connection errors only — set has
    no wait semantics, so no deadline remainder to thread)."""
    pol = policy or DEFAULT_POLICY
    return pol.run(lambda: store.set(key, value), op=op)


class CollectiveWatchdog:
    """Progress-counter watchdog for host-level collectives.

        wd = CollectiveWatchdog(store, rank=r, world_size=n,
                                deadline=30.0)
        with wd.guard("allreduce"):        # raises CollectiveStallError
            ...run the real collective...  # if a peer never arrives

    Each ``guard`` entry bumps this rank's counter
    (``resilience/wd/{group}/{rank}``) in the store and then waits —
    bounded by ``deadline`` — for every peer's counter to reach the
    same height. Because the counter only moves when a rank reaches the
    guard, a hung/dead peer is distinguishable from a slow one by lack
    of progress, and the error names exactly the ranks that never
    arrived (with their last observed progress), turning an infinite
    hang into a diagnosable failure. Fault site: ``watchdog.enter``
    (delay a rank to simulate a straggler)."""

    def __init__(self, store, rank: int, world_size: int,
                 group: str = "default", deadline: float = 30.0,
                 poll: float = 0.05):
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.group = group
        self.deadline = float(deadline)
        self.poll = float(poll)

    def _key(self, rank: int) -> str:
        return f"resilience/wd/{self.group}/{rank}"

    def _progress(self, rank: int) -> int:
        from paddle_tpu.native import decode_counter
        try:
            return decode_counter(
                self.store.get(self._key(rank), timeout=self.poll))
        except (TimeoutError, ValueError):
            return 0            # not yet registered → no progress

    def progress(self) -> dict:
        """Last observed per-rank progress counters (diagnostics)."""
        return {r: self._progress(r) for r in range(self.world_size)}

    def guard(self, op: str = "collective"):
        wd = self

        class _Guard:
            def __enter__(self):
                from paddle_tpu import stats
                from paddle_tpu.testing import faults
                faults.fire("watchdog.enter")
                seq = wd.store.add(wd._key(wd.rank), 1)
                dl = Deadline(wd.deadline)
                behind = {}
                while True:
                    behind = {r: c for r, c in
                              ((r, wd._progress(r))
                               for r in range(wd.world_size))
                              if c < seq and r != wd.rank}
                    if not behind:
                        break
                    if dl.expired:
                        stats.add("resilience/watchdog_stalls")
                        ranks = sorted(behind)
                        raise CollectiveStallError(
                            f"collective {op!r} #{seq}: rank(s) {ranks} "
                            f"stalled — progress "
                            f"{ {r: behind[r] for r in ranks} } after "
                            f"{wd.deadline}s (this rank={wd.rank}, "
                            f"world={wd.world_size})",
                            stalled_ranks=ranks)
                    time.sleep(wd.poll)
                stats.add("resilience/watchdog_syncs")
                return self

            def __exit__(self, *exc):
                return False

        return _Guard()

    def barrier(self, op: str = "barrier"):
        """A guarded no-op collective: returns once every rank arrives,
        raises `CollectiveStallError` otherwise."""
        with self.guard(op):
            pass
