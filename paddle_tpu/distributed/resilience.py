"""Resilient distributed runtime: retry/backoff/deadline policies and a
collective watchdog.

Reference analog: the reference's production serving stack assumes
workers die and stores partition — fleet/elastic/manager.py restarts
trainer groups, the brpc layer retries RPCs with timeouts — but each
caller hand-rolls its own policy. Here there is ONE policy object
(`RetryPolicy`: exponential backoff + jitter + an absolute deadline), one
deadline primitive (`Deadline`), guarded wrappers for the store and
control-plane ops (`store_get`, `with_deadline`), and a
`CollectiveWatchdog` that converts "a rank hung inside a barrier" — the
classic undiagnosable distributed failure — into an exception naming the
stalled rank(s).

Watchdog design: the same counter-not-clock trick as `elastic.py`
heartbeats. Each rank bumps a per-rank progress counter in the TCPStore
when it *enters* guarded collective #k, then waits (bounded by the
deadline) for every peer's counter to reach k before running the real
collective. A peer that never arrives leaves its counter behind, so the
waiting ranks raise `CollectiveStallError` naming exactly the laggards —
instead of blocking forever inside an un-interruptible native collective.
Cross-host clock skew cannot fake a stall because only counter *progress*
is judged, against the local monotonic clock.

Every retry / timeout / stall increments a `resilience/*` counter in
`paddle_tpu.stats` (§5.5 observability surface; see docs/resilience.md).
"""

import dataclasses
import os
import queue as _queue
import random as _random
import threading
import time
from typing import Callable, Optional, Tuple

__all__ = ["Deadline", "DeadlineExceeded", "CollectiveStallError",
           "RetryPolicy", "with_deadline", "store_get", "store_set",
           "CollectiveWatchdog", "DEFAULT_POLICY",
           "GuardedStore", "StorePartitioned", "store_retry_s"]


class DeadlineExceeded(TimeoutError):
    """An operation (including all its retries) overran its absolute
    deadline. Subclasses TimeoutError so existing timeout handlers
    (p2p recv rollback, elastic liveness) treat it uniformly."""


class StorePartitioned(ConnectionError):
    """The control-plane store stopped answering for a whole retry
    budget (router death, network partition, frozen server). Callers
    in the serve loops treat this as "degrade, don't die": skip the
    beat, buffer the result, keep decoding (docs/fleet-ha.md)."""


class CollectiveStallError(RuntimeError):
    """A guarded collective was entered by this rank but one or more
    peers never arrived within the deadline. ``stalled_ranks`` names
    them; the message includes each laggard's last observed progress."""

    def __init__(self, message: str, stalled_ranks=()):
        super().__init__(message)
        self.stalled_ranks = tuple(stalled_ranks)


class Deadline:
    """Absolute time budget, measured on the local monotonic clock.

    ``seconds=None`` means unbounded (remaining() == None, never
    expired) so call sites can thread one object through both bounded
    and unbounded paths.
    """

    __slots__ = ("seconds", "_t0")

    def __init__(self, seconds: Optional[float]):
        self.seconds = None if seconds is None else float(seconds)
        self._t0 = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def remaining(self) -> Optional[float]:
        if self.seconds is None:
            return None
        return self.seconds - self.elapsed()

    @property
    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0

    def budget(self, want: float, floor: float = 0.001) -> float:
        """Clamp a per-attempt timeout to what's left of the deadline
        (never below ``floor`` — native calls reject non-positive
        timeouts)."""
        r = self.remaining()
        return max(floor, want if r is None else min(want, r))

    def check(self, op: str = "operation"):
        if self.expired:
            raise DeadlineExceeded(
                f"{op} exceeded its {self.seconds}s deadline "
                f"(elapsed {self.elapsed():.2f}s)")


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff + jitter + absolute deadline.

        policy = RetryPolicy(max_attempts=5, deadline=30.0)
        value = policy.run(lambda: store.get(key), op="rendezvous_get")

    The deadline bounds the WHOLE call including every backoff sleep —
    a caller holding a peer at a barrier must fail within a known
    budget, not after max_attempts of unbounded waits. Retries and
    deadline overruns surface as ``resilience/retries`` /
    ``resilience/deadline_exceeded`` (plus per-op variants) in
    `paddle_tpu.stats`.
    """

    max_attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25          # +- fraction of the computed delay
    deadline: Optional[float] = 30.0

    def delay_for(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based)."""
        d = min(self.base_delay * self.multiplier ** (attempt - 1),
                self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * _random.random() - 1.0)
        return max(0.0, d)

    def run(self, fn: Callable, *, op: str = "op",
            retry_on: Tuple = (TimeoutError, ConnectionError, OSError),
            deadline: Optional["Deadline"] = None):
        from paddle_tpu import stats
        dl = deadline or Deadline(self.deadline)
        attempt = 0
        while True:
            try:
                return fn()
            except DeadlineExceeded:
                raise           # an inner deadline is final, never retried
            except retry_on as e:
                attempt += 1
                stats.add("resilience/retries")
                stats.add(f"resilience/{op}/retries")
                if attempt >= self.max_attempts:
                    stats.add("resilience/retries_exhausted")
                    raise
                if dl.expired:
                    stats.add("resilience/deadline_exceeded")
                    raise DeadlineExceeded(
                        f"{op} failed after {attempt} attempts over "
                        f"{dl.elapsed():.2f}s (deadline {dl.seconds}s): "
                        f"{e!r}") from e
                time.sleep(dl.budget(self.delay_for(attempt), floor=0.0))


DEFAULT_POLICY = RetryPolicy()


def with_deadline(fn: Callable, seconds: Optional[float],
                  op: Optional[str] = None,
                  policy: Optional[RetryPolicy] = None,
                  retry_on: Tuple = (TimeoutError, ConnectionError,
                                     OSError)) -> Callable:
    """Wrap a zero-arg-compatible callable so every invocation runs
    under a fresh ``seconds`` deadline with retry/backoff on transient
    errors (``retry_on``, default TimeoutError / ConnectionError /
    OSError — widen it for libraries that wrap transport failures in
    RuntimeError, e.g. jax's XlaRuntimeError).

        guarded = with_deadline(lambda: jax.distributed.initialize(...),
                                seconds=120.0, op="collective_init")
        guarded()
    """
    name = op or getattr(fn, "__name__", "op")
    pol = policy or DEFAULT_POLICY

    def wrapped(*args, **kwargs):
        return pol.run(lambda: fn(*args, **kwargs), op=name,
                       retry_on=retry_on, deadline=Deadline(seconds))

    wrapped.__name__ = f"with_deadline[{name}]"
    return wrapped


def store_get(store, key: str, *, deadline: float = 30.0,
              policy: Optional[RetryPolicy] = None, op: str = "store_get"):
    """Deadline-guarded TCPStore get: each attempt's native timeout is
    the deadline *remainder* (so retries after transient connection
    errors cannot extend the total budget), and the whole call fails
    with `DeadlineExceeded` naming the key. Fault site: ``store.get``."""
    from paddle_tpu import stats
    from paddle_tpu.testing import faults

    pol = policy or DEFAULT_POLICY
    dl = Deadline(deadline)

    def attempt():
        faults.fire("store.get")
        dl.check(f"store.get({key!r})")
        return store.get(key, timeout=dl.budget(deadline))

    try:
        return pol.run(attempt, op=op, deadline=dl)
    except TimeoutError as e:
        if isinstance(e, DeadlineExceeded):
            raise
        stats.add("resilience/deadline_exceeded")
        raise DeadlineExceeded(
            f"store.get({key!r}) exceeded its {deadline}s deadline") from e


def store_set(store, key: str, value, *,
              policy: Optional[RetryPolicy] = None, op: str = "store_set"):
    """Retried TCPStore set (transient connection errors only — set has
    no wait semantics, so no deadline remainder to thread)."""
    pol = policy or DEFAULT_POLICY
    return pol.run(lambda: store.set(key, value), op=op)


class CollectiveWatchdog:
    """Progress-counter watchdog for host-level collectives.

        wd = CollectiveWatchdog(store, rank=r, world_size=n,
                                deadline=30.0)
        with wd.guard("allreduce"):        # raises CollectiveStallError
            ...run the real collective...  # if a peer never arrives

    Each ``guard`` entry bumps this rank's counter
    (``resilience/wd/{group}/{rank}``) in the store and then waits —
    bounded by ``deadline`` — for every peer's counter to reach the
    same height. Because the counter only moves when a rank reaches the
    guard, a hung/dead peer is distinguishable from a slow one by lack
    of progress, and the error names exactly the ranks that never
    arrived (with their last observed progress), turning an infinite
    hang into a diagnosable failure. Fault site: ``watchdog.enter``
    (delay a rank to simulate a straggler)."""

    def __init__(self, store, rank: int, world_size: int,
                 group: str = "default", deadline: float = 30.0,
                 poll: float = 0.05):
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.group = group
        self.deadline = float(deadline)
        self.poll = float(poll)

    def _key(self, rank: int) -> str:
        return f"resilience/wd/{self.group}/{rank}"

    def _progress(self, rank: int) -> int:
        from paddle_tpu.native import decode_counter
        try:
            return decode_counter(
                self.store.get(self._key(rank), timeout=self.poll))
        except (TimeoutError, ValueError):
            return 0            # not yet registered → no progress

    def progress(self) -> dict:
        """Last observed per-rank progress counters (diagnostics)."""
        return {r: self._progress(r) for r in range(self.world_size)}

    def guard(self, op: str = "collective"):
        wd = self

        class _Guard:
            def __enter__(self):
                from paddle_tpu import stats
                from paddle_tpu.testing import faults
                faults.fire("watchdog.enter")
                seq = wd.store.add(wd._key(wd.rank), 1)
                dl = Deadline(wd.deadline)
                behind = {}
                while True:
                    behind = {r: c for r, c in
                              ((r, wd._progress(r))
                               for r in range(wd.world_size))
                              if c < seq and r != wd.rank}
                    if not behind:
                        break
                    if dl.expired:
                        stats.add("resilience/watchdog_stalls")
                        ranks = sorted(behind)
                        raise CollectiveStallError(
                            f"collective {op!r} #{seq}: rank(s) {ranks} "
                            f"stalled — progress "
                            f"{ {r: behind[r] for r in ranks} } after "
                            f"{wd.deadline}s (this rank={wd.rank}, "
                            f"world={wd.world_size})",
                            stalled_ranks=ranks)
                    time.sleep(wd.poll)
                stats.add("resilience/watchdog_syncs")
                return self

            def __exit__(self, *exc):
                return False

        return _Guard()

    def barrier(self, op: str = "barrier"):
        """A guarded no-op collective: returns once every rank arrives,
        raises `CollectiveStallError` otherwise."""
        with self.guard(op):
            pass


def store_retry_s(default: float = 2.0) -> float:
    """Per-op retry budget (seconds) for `GuardedStore` — how long a
    single store operation keeps retrying transport errors before the
    caller sees `StorePartitioned` and degrades to partition mode."""
    try:
        return max(0.1, float(os.environ.get("PT_STORE_RETRY_S", default)))
    except ValueError:
        return default


class _OpStuck(Exception):
    """Internal: the store op thread did not answer within the wait —
    the server is frozen (SIGSTOP) or the network is black-holing.
    Deliberately NOT in any retry_on tuple: retrying would just queue
    more ops behind the stuck one."""


class _KeyAbsent(Exception):
    """Internal: wraps the native TimeoutError for a key that simply
    isn't there yet. Builtin TimeoutError is an OSError subclass (3.10+)
    so it would match the transport retry_on tuple — but key-absence is
    normal control flow all over the serving protocol and must pass
    through UNRETRIED, not burn the whole partition budget."""

    def __init__(self, err: BaseException):
        super().__init__(str(err))
        self.err = err


class GuardedStore:
    """The one shared deadline-guarded store helper (ISSUE 17 satellite:
    every serving/fleet store call site routes through here).

    Wraps a raw `native.TCPStore` client so that:

    - transient transport failures (ConnectionError / RuntimeError /
      OSError / BrokenPipeError) are retried with backoff, bounded by
      `PT_STORE_RETRY_S`; exhaustion raises `StorePartitioned`, which
      serve loops treat as "degrade, don't die";
    - `TimeoutError` from ``get``/``wait`` passes through UNRETRIED —
      across the codebase it is the normal "key absent yet" signal, not
      a failure;
    - every op executes on a background pump thread with a caller-side
      timed wait, so a *frozen* store server (SIGSTOP partition) cannot
      wedge a serve loop inside a native call that has no timeout
      (``add`` in particular) — the caller gets `StorePartitioned`
      while the thread parks on the dead socket;
    - the ``store.partition`` fault site is consulted once per attempt
      (actions: ``drop``/``raise``/``delay`` — a ``count=N`` rule
      partitions exactly N ops then heals);
    - bytes moved through the store are metered
      (``serve/store_bytes_in``/``_out``) so tests can assert the
      socket KV transport keeps the store byte curve ~flat;
    - `swap(new_raw)` atomically redirects to a different store
      endpoint (router failover): a fresh pump thread is spun up so a
      thread parked on the dead endpoint is simply abandoned.

    Attribute reads not defined here (``host``, ``port``) fall through
    to the raw store.
    """

    SITE = "store.partition"
    _MAX_BACKLOG = 64       # refuse new ops when this many are queued

    def __init__(self, raw, retry_s: Optional[float] = None,
                 policy: Optional[RetryPolicy] = None):
        if isinstance(raw, GuardedStore):      # idempotent wrap
            raw = raw.raw
        self.raw = raw
        self.retry_s = store_retry_s() if retry_s is None else float(retry_s)
        self.policy = policy or RetryPolicy(
            max_attempts=64, base_delay=0.02, max_delay=0.25,
            deadline=self.retry_s)
        self._lock = threading.Lock()
        self._gen = 0
        self._spawn_pump()

    # -- pump thread ----------------------------------------------------
    def _spawn_pump(self):
        self._queue = _queue.Queue()
        self._gen += 1
        t = threading.Thread(target=self._pump, args=(self._queue, self._gen),
                             name=f"guarded-store-{self._gen}", daemon=True)
        t.start()

    def _pump(self, q, gen):
        while True:
            item = q.get()
            if item is None:
                return
            fn, box, done = item
            try:
                box.append(("ok", fn()))
            except BaseException as e:          # noqa: BLE001 — relayed
                box.append(("err", e))
            done.set()
            if gen != self._gen:                # abandoned after swap()
                return

    _GRACE_S = 0.3          # post-deadline re-check window (see below)

    def _run_async(self, fn, wait: float):
        with self._lock:
            if self._queue.qsize() > self._MAX_BACKLOG:
                raise StorePartitioned(
                    f"store op backlog > {self._MAX_BACKLOG} "
                    f"(server unresponsive)")
            q, box, done = self._queue, [], threading.Event()
            q.put((fn, box, done))
        if not done.wait(wait):
            # The deadline is wall-clock, so a process-wide freeze
            # (SIGSTOP of a router that hosts its OWN store) ages the
            # op while neither the pump nor the server ran a single
            # instruction; on resume the op lands within milliseconds.
            # One short grace re-check separates "we were suspended"
            # from "the server is black-holing" — a real partition
            # just reaches its verdict _GRACE_S later.
            if not done.wait(self._GRACE_S):
                raise _OpStuck(f"store op unanswered after {wait:.2f}s")
        kind, val = box[0]
        if kind == "err":
            raise val
        return val

    # -- guarded op core ------------------------------------------------
    def _guarded(self, fn, op: str, wait: float):
        from paddle_tpu import stats
        from paddle_tpu.testing import faults

        def attempt():
            if faults.fire(self.SITE) == "drop":
                raise ConnectionError(
                    f"store partitioned (injected) at {op}")
            try:
                return self._run_async(fn, wait)
            except DeadlineExceeded:
                raise
            except TimeoutError as e:
                # key-absent (get/wait): builtin TimeoutError ⊂ OSError,
                # so without the wrapper it would be retried as a
                # transport error for the whole partition budget
                raise _KeyAbsent(e) from e

        try:
            return self.policy.run(
                attempt, op=op,
                retry_on=(ConnectionError, OSError, RuntimeError,
                          BrokenPipeError),
                deadline=Deadline(self.retry_s))
        except _KeyAbsent as e:
            raise e.err         # key-absent — normal control flow
        except DeadlineExceeded as e:       # retry budget burned by failures
            stats.add("resilience/store_partitions")
            raise StorePartitioned(f"store unreachable at {op}: {e}") from e
        except (_OpStuck, ConnectionError, OSError, RuntimeError) as e:
            stats.add("resilience/store_partitions")
            raise StorePartitioned(f"store unreachable at {op}: {e}") from e

    # -- TCPStore surface ----------------------------------------------
    def get(self, key: str, timeout: float = 30.0) -> bytes:
        from paddle_tpu import stats
        out = self._guarded(
            lambda: self.raw.get(key, timeout=timeout),
            f"store.get({key})", wait=timeout + max(1.0, self.retry_s))
        stats.add("serve/store_bytes_in", len(out))
        return out

    def set(self, key: str, value) -> None:
        from paddle_tpu import stats
        v = value.encode() if isinstance(value, str) else bytes(value)
        stats.add("serve/store_bytes_out", len(v))
        self._guarded(lambda: self.raw.set(key, v),
                      f"store.set({key})", wait=max(1.0, self.retry_s))

    def add(self, key: str, amount: int) -> int:
        # native ptts_add has NO timeout — the pump thread is what makes
        # this safe to call against a frozen server.
        return self._guarded(lambda: self.raw.add(key, amount),
                             f"store.add({key})", wait=max(1.0, self.retry_s))

    def probe(self, key: str, wait: float = 0.3):
        """Single-attempt liveness read of a counter key: ``add(key, 0)``
        returns the current counter without bumping it. NEVER retried —
        this is the router-liveness probe (`RouterLink`) and its whole
        job is to answer "is the store reachable RIGHT NOW" in bounded
        time; backoff belongs to the caller's state machine. Returns the
        counter int, or None on any failure (unreachable, stuck, fault
        injection)."""
        from paddle_tpu.testing import faults
        if faults.fire(self.SITE) == "drop":
            return None
        try:
            return self._run_async(lambda: self.raw.add(key, 0), wait)
        except BaseException:       # noqa: BLE001 — probe is best-effort
            return None

    def delete_key(self, key: str) -> bool:
        return self._guarded(lambda: self.raw.delete_key(key),
                             f"store.delete({key})",
                             wait=max(1.0, self.retry_s))

    def wait(self, keys, timeout: float = 30.0) -> None:
        self._guarded(lambda: self.raw.wait(keys, timeout=timeout),
                      "store.wait", wait=timeout + max(1.0, self.retry_s))

    def close(self) -> None:
        try:
            self._queue.put(None)
        except Exception:
            pass
        self.raw.close()

    # -- failover -------------------------------------------------------
    def swap(self, new_raw) -> None:
        """Redirect every future op to ``new_raw`` (a fresh TCPStore
        client on the new router generation's endpoint). The old pump
        thread — possibly parked on the dead endpoint — is abandoned."""
        from paddle_tpu import stats
        if isinstance(new_raw, GuardedStore):
            new_raw = new_raw.raw
        with self._lock:
            old = self.raw
            self.raw = new_raw
            self._spawn_pump()
        stats.add("resilience/store_swaps")
        try:
            old.close()
        except Exception:
            pass

    def __getattr__(self, name):
        if name == "raw":
            raise AttributeError(name)
        return getattr(self.raw, name)
