"""Cross-host pipeline-parallel runtime (FleetExecutor analog).

Reference analog: paddle/fluid/distributed/fleet_executor/ —
fleet_executor.h:35 (the per-rank runtime), carrier.cc (schedules task
nodes), interceptor.cc (tag-addressed mailboxes), message_bus.cc
(cross-host transport), and the 1F1B semantics of
fleet/meta_parallel/pipeline_parallel.py:117-198.

The in-mesh PP path (models/gpt.py build_pipelined_train_step) is a single
SPMD program — right for stages connected by ICI. This runtime is the DCN
story: each HOST owns one stage as its own jitted program; only stage-
boundary activations/cotangents cross hosts, as raw bytes over the native
P2P endpoint (native/src/p2p.cc). Inside a stage the program still shards
over the local mesh axes — composing cross-host PP over DCN with
tp/fsdp/dp over ICI, which is exactly how the reference splits NCCL
(intra) from brpc (inter).

Schedules: "fthenb" (GPipe) and "1f1b" (warmup = n_stages-stage-1, then
steady alternation — caps in-flight activations at the stage depth).
Deadlock-free by construction: receives block, sends never do.
"""

import io
from typing import Callable, List, Optional, Sequence

import numpy as np
import jax

__all__ = ["FleetExecutor", "rendezvous_endpoints"]

_FWD, _BWD = 1, 2


def _pack(arrays) -> bytes:
    """Serialize a tuple of arrays (np.savez — no pickle on the wire)."""
    if not isinstance(arrays, (tuple, list)):
        arrays = (arrays,)
    buf = io.BytesIO()
    np.savez(buf, *[np.asarray(a) for a in arrays])
    return buf.getvalue()


def _unpack(payload: bytes):
    with np.load(io.BytesIO(payload)) as z:
        arrays = [z[k] for k in z.files]
    return arrays[0] if len(arrays) == 1 else tuple(arrays)


def _tag(kind: int, step: int, mb: int) -> int:
    return (kind << 56) | ((step & 0xFFFFFFFF) << 24) | (mb & 0xFFFFFF)


def rendezvous_endpoints(store, stage_idx: int, n_stages: int,
                         host: str = "127.0.0.1", timeout: float = 60.0):
    """Create this rank's P2P endpoint and exchange addresses through the
    TCPStore (≙ message_bus init from the rank-to-addr table the master
    distributes). Returns (endpoint, peers) with peers[s] = (host, port)."""
    from paddle_tpu import native
    ep = native.P2PEndpoint()
    store.set(f"fe/addr/{stage_idx}", f"{host}:{ep.port}".encode())
    peers = []
    for s in range(n_stages):
        raw = store.get(f"fe/addr/{s}", timeout=timeout).decode()
        h, p = raw.rsplit(":", 1)
        peers.append((h, int(p)))
    return ep, peers


class FleetExecutor:
    """Runs ONE pipeline stage of a cross-host pipeline.

    Args:
      stage_fn: jit-compatible ``(params, x) -> y``; the LAST stage returns
        a scalar loss (it receives the final activations and owns the loss
        head). Compiled once per activation shape.
      stage_idx / n_stages: this rank's stage and the pipeline depth.
      endpoint: a ``native.P2PEndpoint`` (see ``rendezvous_endpoints``).
      peers: ``peers[s] = (host, port)`` for every stage.
      schedule: "1f1b" (default) or "fthenb".
    """

    def __init__(self, stage_fn: Callable, stage_idx: int, n_stages: int,
                 endpoint, peers: Sequence, schedule: str = "1f1b",
                 timeout: float = 120.0):
        if schedule not in ("1f1b", "fthenb"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.stage_fn = stage_fn
        self.stage_idx = stage_idx
        self.n_stages = n_stages
        self.endpoint = endpoint
        self.peers = peers
        self.schedule = schedule
        self.timeout = timeout
        self._step = 0
        self.is_first = stage_idx == 0
        self.is_last = stage_idx == n_stages - 1

    # -- transport ----------------------------------------------------------

    def _send(self, stage: int, kind: int, mb: int, value):
        host, port = self.peers[stage]
        self.endpoint.send(host, port, _tag(kind, self._step, mb),
                           _pack(jax.device_get(value)))

    def _recv(self, kind: int, mb: int):
        return _unpack(self.endpoint.recv(_tag(kind, self._step, mb),
                                          self.timeout))

    # -- public -------------------------------------------------------------

    def run(self, params, microbatches: Optional[List] = None,
            labels: Optional[List] = None, n_micro: Optional[int] = None):
        """One optimizer-step's worth of pipeline: ``n_micro`` forwards and
        backwards in the configured schedule. Stage 0 passes the list of
        microbatch inputs; the last stage passes ``labels`` (its stage_fn
        then takes ``(params, x, label)`` — the loss head owns the
        targets, matching the reference's data feed to both pipeline
        ends). Returns ``(grads, mean_loss)`` — grads for THIS stage's
        params (averaged over microbatches), loss on the last stage else
        None."""
        if self.is_first:
            n_micro = len(microbatches)
        if n_micro is None:
            raise ValueError("non-first stages must pass n_micro")

        saved = {}
        losses = []
        grad_acc = None

        def fwd(mb):
            x = microbatches[mb] if self.is_first \
                else jax.numpy.asarray(self._recv(_FWD, mb))
            if labels is not None:
                y, vjp_fn = jax.vjp(
                    lambda p, xx: self.stage_fn(p, xx, labels[mb]),
                    params, x)
            else:
                y, vjp_fn = jax.vjp(self.stage_fn, params, x)
            saved[mb] = vjp_fn
            if self.is_last:
                losses.append(float(y))
            else:
                self._send(self.stage_idx + 1, _FWD, mb, y)

        def bwd(mb):
            nonlocal grad_acc
            vjp_fn = saved.pop(mb)
            if self.is_last:
                cot = np.float32(1.0)
            else:
                got = self._recv(_BWD, mb)
                cot = jax.tree_util.tree_map(np.asarray, got) \
                    if isinstance(got, tuple) else np.asarray(got)
            (gp, gx) = vjp_fn(cot)
            grad_acc = gp if grad_acc is None else jax.tree_util.tree_map(
                lambda a, b: a + b, grad_acc, gp)
            if not self.is_first:
                self._send(self.stage_idx - 1, _BWD, mb, gx)

        if self.schedule == "fthenb":
            for mb in range(n_micro):
                fwd(mb)
            for mb in range(n_micro):
                bwd(mb)
        else:  # 1f1b
            warmup = min(n_micro, self.n_stages - self.stage_idx - 1)
            for mb in range(warmup):
                fwd(mb)
            next_f, next_b = warmup, 0
            while next_b < n_micro:
                if next_f < n_micro:
                    fwd(next_f)
                    next_f += 1
                bwd(next_b)
                next_b += 1

        self._step += 1
        grads = jax.tree_util.tree_map(lambda g: g / n_micro, grad_acc)
        loss = float(np.mean(losses)) if losses else None
        return grads, loss
