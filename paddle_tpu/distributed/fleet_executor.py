"""Cross-host pipeline-parallel runtime (FleetExecutor analog).

Reference analog: paddle/fluid/distributed/fleet_executor/ —
fleet_executor.h:35 (the per-rank runtime), carrier.cc (schedules task
nodes), interceptor.cc (tag-addressed mailboxes), message_bus.cc
(cross-host transport), and the schedules of
fleet/meta_parallel/pipeline_parallel.py:117-198 (1F1B) and :457
(PipelineParallelWithInterleave — virtual stages).

The in-mesh PP path (models/gpt.py build_pipelined_train_step) is a single
SPMD program — right for stages connected by ICI. This runtime is the DCN
story: each HOST owns one stage as its own jitted program; only stage-
boundary activations/cotangents cross hosts, as raw bytes over the native
P2P endpoint (native/src/p2p.cc). Inside a stage the program still shards
over the local mesh axes — composing cross-host PP over DCN with
tp/fsdp/dp over ICI, which is exactly how the reference splits NCCL
(intra) from brpc (inter).

Schedules: "fthenb" (GPipe) and "1f1b" (warmup = n_stages-stage-1, then
steady alternation — caps in-flight activations at the stage depth).
``n_virtual > 1`` runs the interleaved schedule: each rank owns V model
chunks (global stage v·S + r), microbatches are processed in S-sized
groups through all chunks before the next group, and the warmup depth is
Megatron's (S-rank-1)·2 + (V-1)·S — cutting the pipeline bubble by ~V.

Sends are handed to a background worker thread (device_get → pack →
socket) so the next microbatch's compute dispatches while the previous
boundary tensor is still in flight — the comm/compute overlap the
reference gets from its async interceptor queues. Per-worker FIFO keeps
message order deterministic. The send queue is bounded (a few in-flight
boundary tensors); a peer that stops draining its socket surfaces as a
queue-full/timeout error rather than a silent hang or unbounded host
memory. (The receiver's native mailbox is itself unbounded — a full
credit protocol is future work; the bound here caps the SENDER's
pyramid of live activations, which is where fthenb piles them up.)
"""

import io
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Union

import numpy as np
import jax

from paddle_tpu import stats

__all__ = ["FleetExecutor", "rendezvous_endpoints"]

_FWD, _BWD = 1, 2


def _pack(arrays) -> bytes:
    """Serialize a tuple of arrays (np.savez — no pickle on the wire)."""
    if not isinstance(arrays, (tuple, list)):
        arrays = (arrays,)
    buf = io.BytesIO()
    np.savez(buf, *[np.asarray(a) for a in arrays])
    return buf.getvalue()


def _unpack(payload: bytes):
    with np.load(io.BytesIO(payload)) as z:
        arrays = [z[k] for k in z.files]
    return arrays[0] if len(arrays) == 1 else tuple(arrays)


def _tag(kind: int, step: int, chunk: int, mb: int) -> int:
    return ((kind << 54) | ((step & 0x3FFFFFFF) << 24)
            | ((chunk & 0xFF) << 16) | (mb & 0xFFFF))


def rendezvous_endpoints(store, stage_idx: int, n_stages: int,
                         host: str = "127.0.0.1", timeout: float = 60.0):
    """Create this rank's P2P endpoint and exchange addresses through the
    TCPStore (≙ message_bus init from the rank-to-addr table the master
    distributes). Returns (endpoint, peers) with peers[s] = (host, port)."""
    from paddle_tpu import native
    ep = native.P2PEndpoint()
    store.set(f"fe/addr/{stage_idx}", f"{host}:{ep.port}".encode())
    peers = []
    for s in range(n_stages):
        raw = store.get(f"fe/addr/{s}", timeout=timeout).decode()
        h, p = raw.rsplit(":", 1)
        peers.append((h, int(p)))
    return ep, peers


class FleetExecutor:
    """Runs ONE pipeline rank of a cross-host pipeline.

    Args:
      stage_fn: jit-compatible ``(params, x) -> y`` — or, with
        ``n_virtual > 1``, a list of V such callables (chunk v implements
        global stage v·S + rank). The LAST global stage's callable returns
        a scalar loss and takes ``(params, x, label)`` (it owns the loss
        head, matching the reference's data feed to both pipeline ends).
      stage_idx / n_stages: this rank's stage and the pipeline depth.
      endpoint: a ``native.P2PEndpoint`` (see ``rendezvous_endpoints``).
      peers: ``peers[s] = (host, port)`` for every stage.
      schedule: "1f1b" (default) or "fthenb".
      n_virtual: model chunks per rank (interleaved schedule when > 1).
    """

    def __init__(self, stage_fn: Union[Callable, Sequence[Callable]],
                 stage_idx: int, n_stages: int,
                 endpoint, peers: Sequence, schedule: str = "1f1b",
                 timeout: float = 120.0, n_virtual: int = 1):
        if schedule not in ("1f1b", "fthenb"):
            raise ValueError(f"unknown schedule {schedule!r}")
        if n_virtual > 1:
            if not isinstance(stage_fn, (list, tuple)) \
                    or len(stage_fn) != n_virtual:
                raise ValueError("n_virtual>1 needs a list of n_virtual "
                                 "stage callables (one per model chunk)")
            self.chunk_fns = list(stage_fn)
        else:
            self.chunk_fns = [stage_fn] if callable(stage_fn) \
                else list(stage_fn)
        self.stage_idx = stage_idx
        self.n_stages = n_stages
        self.n_virtual = n_virtual
        self.endpoint = endpoint
        self.peers = peers
        self.schedule = schedule
        self.timeout = timeout
        self._step = 0
        self.is_first = stage_idx == 0
        self.is_last = stage_idx == n_stages - 1
        # async send worker: FIFO queue keeps per-connection ordering;
        # bounded so a stalled peer backpressures fwd() instead of letting
        # every in-flight boundary activation pile up in host memory
        self._sendq: "queue.Queue" = queue.Queue(maxsize=4)
        self._send_err: List[BaseException] = []
        self._sender = threading.Thread(target=self._send_loop, daemon=True)
        self._sender.start()

    # -- transport ----------------------------------------------------------

    def _send_loop(self):
        while True:
            item = self._sendq.get()
            if item is None:
                return
            stage, kind, chunk, mb, step, value = item
            try:
                host, port = self.peers[stage]
                payload = _pack(jax.device_get(value))
                self.endpoint.send(host, port, _tag(kind, step, chunk, mb),
                                   payload)
                # §5.5 observability (≙ platform/monitor.h STAT_ADD)
                stats.add("fleet_executor/send_msgs")
                stats.add("fleet_executor/send_bytes", len(payload))
            except BaseException as e:  # surfaced at the next flush
                self._send_err.append(e)
            finally:
                self._sendq.task_done()

    def _send(self, stage: int, kind: int, mb: int, value, chunk: int = 0):
        # bounded put: a wedged peer surfaces as queue.Full after the
        # executor timeout instead of a silent indefinite block
        self._sendq.put((stage, kind, chunk, mb, self._step, value),
                        timeout=self.timeout)

    def _flush_sends(self):
        self._sendq.join()
        self._raise_send_err()

    def _raise_send_err(self):
        if self._send_err:
            err = self._send_err[0]
            del self._send_err[:]
            raise err

    def _recv(self, kind: int, mb: int, chunk: int = 0):
        # a failed async send (peer died) would otherwise surface as an
        # unrelated recv timeout — check before blocking and on timeout
        self._raise_send_err()
        t0 = time.perf_counter()
        try:
            payload = self.endpoint.recv(
                _tag(kind, self._step, chunk, mb), self.timeout)
        except TimeoutError:
            self._raise_send_err()
            raise
        # per-microbatch boundary wait + volume (§5.5 observability)
        stats.default_registry().record_time(
            "fleet_executor/recv_wait", time.perf_counter() - t0)
        stats.add("fleet_executor/recv_msgs")
        stats.add("fleet_executor/recv_bytes", len(payload))
        return _unpack(payload)

    def close(self):
        self._sendq.put(None)
        self._sender.join(timeout=5)
        self._raise_send_err()

    # -- public -------------------------------------------------------------

    def run(self, params, microbatches: Optional[List] = None,
            labels: Optional[List] = None, n_micro: Optional[int] = None):
        """One optimizer-step's worth of pipeline: ``n_micro`` forwards and
        backwards in the configured schedule. Stage 0 passes the list of
        microbatch inputs; the last stage passes ``labels``. Returns
        ``(grads, mean_loss)`` — grads for THIS rank's params (averaged
        over microbatches; a list of per-chunk grads when n_virtual > 1),
        loss on the last stage else None."""
        if self.is_first:
            n_micro = len(microbatches)
        if n_micro is None:
            raise ValueError("non-first stages must pass n_micro")
        S, V, r = self.n_stages, self.n_virtual, self.stage_idx
        if V > 1 and n_micro % S != 0:
            raise ValueError(f"interleaved schedule needs n_micro divisible"
                             f" by n_stages ({n_micro} % {S} != 0)")

        saved = {}
        losses = []
        grad_acc: List = [None] * V
        last_chunk_is_loss = self.is_last  # chunk V-1 on the last rank

        def fwd(mb, v=0):
            stats.add("fleet_executor/microbatch_fwd")
            g = v * S + r
            if g == 0:
                x = microbatches[mb]
            else:
                x = jax.numpy.asarray(self._recv(_FWD, mb, chunk=v))
            if last_chunk_is_loss and v == V - 1 and labels is not None:
                y, vjp_fn = jax.vjp(
                    lambda p, xx: self.chunk_fns[v](p, xx, labels[mb]),
                    params[v] if V > 1 else params, x)
            else:
                y, vjp_fn = jax.vjp(self.chunk_fns[v],
                                    params[v] if V > 1 else params, x)
            saved[(v, mb)] = vjp_fn
            if last_chunk_is_loss and v == V - 1:
                losses.append(float(y))
            elif r < S - 1:
                self._send(r + 1, _FWD, mb, y, chunk=v)
            else:  # chunk boundary hop: rank S-1 chunk v → rank 0 chunk v+1
                self._send(0, _FWD, mb, y, chunk=v + 1)

        def bwd(mb, v=0):
            stats.add("fleet_executor/microbatch_bwd")
            vjp_fn = saved.pop((v, mb))
            if last_chunk_is_loss and v == V - 1:
                cot = np.float32(1.0)
            else:
                got = self._recv(_BWD, mb, chunk=v)
                cot = jax.tree_util.tree_map(np.asarray, got) \
                    if isinstance(got, tuple) else np.asarray(got)
            (gp, gx) = vjp_fn(cot)
            grad_acc[v] = gp if grad_acc[v] is None else \
                jax.tree_util.tree_map(lambda a, b: a + b, grad_acc[v], gp)
            if r > 0:
                self._send(r - 1, _BWD, mb, gx, chunk=v)
            elif v > 0:  # rank 0 chunk v → rank S-1 chunk v-1
                self._send(S - 1, _BWD, mb, gx, chunk=v - 1)
            # g == 0 discards gx (no producer upstream)

        if V == 1:
            if self.schedule == "fthenb":
                for mb in range(n_micro):
                    fwd(mb)
                for mb in range(n_micro):
                    bwd(mb)
            else:  # 1f1b
                warmup = min(n_micro, S - r - 1)
                for mb in range(warmup):
                    fwd(mb)
                next_f, next_b = warmup, 0
                while next_b < n_micro:
                    if next_f < n_micro:
                        fwd(next_f)
                        next_f += 1
                    bwd(next_b)
                    next_b += 1
        else:
            # interleaved unit order (≙ get_model_chunk_id,
            # pipeline_parallel.py:457): S-sized microbatch groups sweep
            # all V chunks before the next group enters
            group = S * V

            def funit(k):
                within = k % group
                return within // S, (k // group) * S + within % S

            def bunit(j):
                within = j % group
                return (V - 1 - within // S,
                        (j // group) * S + within % S)

            total = n_micro * V
            if self.schedule == "fthenb":
                for k in range(total):
                    v, mb = funit(k)
                    fwd(mb, v)
                for j in range(total):
                    v, mb = bunit(j)
                    bwd(mb, v)
            else:
                warmup = min(total, (S - r - 1) * 2 + (V - 1) * S)
                for k in range(warmup):
                    v, mb = funit(k)
                    fwd(mb, v)
                fk, bk = warmup, 0
                while bk < total:
                    if fk < total:
                        v, mb = funit(fk)
                        fwd(mb, v)
                        fk += 1
                    v, mb = bunit(bk)
                    bwd(mb, v)
                    bk += 1

        self._flush_sends()
        self._step += 1
        grads = [jax.tree_util.tree_map(lambda g: g / n_micro, ga)
                 for ga in grad_acc]
        if V == 1:
            grads = grads[0]
        loss = float(np.mean(losses)) if losses else None
        return grads, loss
