"""Elastic membership manager over the native TCPStore.

Reference analog: fleet/elastic/manager.py:128 ElasticManager — ranks
register in etcd with a TTL'd heartbeat; when a node joins/leaves, the
manager kills the local trainer group (SIGTERM, manager.py:66) and the
launcher relaunches with the new membership. Here the etcd plane is the
framework's own C++ TCPStore and the relaunch is
``launch.py --max_restarts`` / a user callback.
"""

import threading
import time
from typing import Callable, Optional

__all__ = ["ElasticManager", "ELASTIC_TTL"]

ELASTIC_TTL = 60  # seconds, ≙ manager.py:39


class ElasticManager:
    """Heartbeat + peer-liveness watcher.

    store: a connected paddle_tpu.native.TCPStore client.
    on_change(dead_ranks) fires (once per membership change) when a peer's
    heartbeat goes stale — typically: kill local workers and exit with
    ELASTIC_EXIT_CODE so the launcher relaunches.
    """

    def __init__(self, store, rank: int, world_size: int,
                 ttl: float = ELASTIC_TTL, interval: Optional[float] = None,
                 on_change: Optional[Callable] = None):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.ttl = ttl
        self.interval = interval if interval is not None else max(
            0.05, ttl / 3)
        self.on_change = on_change
        self._stop = threading.Event()
        self._threads = []
        self._reported = set()

    def _hb_key(self, rank):
        return f"elastic/hb/{rank}"

    def _heartbeat_loop(self):
        # monotonically increasing counter (store.add), NOT a wall-clock
        # timestamp: peers judge staleness by lack of counter *progress*
        # against their own local clock, so cross-host clock skew cannot
        # produce false dead-peer events (ADVICE r1).
        while not self._stop.is_set():
            self.store.add(self._hb_key(self.rank), 1)
            self._stop.wait(self.interval)

    def _counter(self, rank):
        try:
            raw = self.store.get(self._hb_key(rank), timeout=1.0)
        except (TimeoutError, ValueError):
            return None
        # store.add keeps counters as raw little-endian int64
        if len(raw) == 8:
            return int.from_bytes(raw, "little", signed=True)
        try:
            return int(raw)
        except ValueError:
            return None

    def _watch_loop(self):
        # wait for everyone to register once before judging liveness
        for r in range(self.world_size):
            if self._stop.is_set():
                return
            try:
                self.store.get(self._hb_key(r), timeout=self.ttl)
            except TimeoutError:
                pass
        # last observed (counter, local time of last progress) per rank
        seen = {}
        while not self._stop.is_set():
            now = time.monotonic()
            dead = []
            for r in range(self.world_size):
                if r == self.rank:
                    continue
                c = self._counter(r)
                prev = seen.get(r)
                if prev is None or (c is not None and c != prev[0]):
                    seen[r] = (c, now)
                    # heartbeat resumed → eligible for re-reporting if it
                    # dies again after a recovery (ADVICE r1)
                    if c is not None:
                        self._reported.discard(r)
                    continue
                if now - prev[1] > self.ttl:
                    dead.append(r)
            fresh = [r for r in dead if r not in self._reported]
            if fresh and self.on_change is not None:
                self._reported.update(fresh)
                self.on_change(sorted(fresh))
            self._stop.wait(self.interval)

    def start(self):
        for fn in (self._heartbeat_loop, self._watch_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads = []
