"""Elastic membership manager over the native TCPStore.

Reference analog: fleet/elastic/manager.py:128 ElasticManager — ranks
register in etcd with a TTL'd heartbeat; when a node joins/leaves, the
manager kills the local trainer group (SIGTERM, manager.py:66) and the
launcher relaunches with the new membership. Here the etcd plane is the
framework's own C++ TCPStore and the relaunch is
``launch.py --max_restarts`` / a user callback.
"""

import threading
import time
from typing import Callable, Optional

__all__ = ["ElasticManager", "ElasticRegistry", "ELASTIC_TTL"]

ELASTIC_TTL = 60  # seconds, ≙ manager.py:39


class ElasticManager:
    """Heartbeat + peer-liveness watcher.

    store: a connected paddle_tpu.native.TCPStore client.
    on_change(dead_ranks) fires (once per membership change) when a peer's
    heartbeat goes stale — typically: kill local workers and exit with
    ELASTIC_EXIT_CODE so the launcher relaunches.
    """

    def __init__(self, store, rank: int, world_size: int,
                 ttl: float = ELASTIC_TTL, interval: Optional[float] = None,
                 on_change: Optional[Callable] = None):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.ttl = ttl
        self.interval = interval if interval is not None else max(
            0.05, ttl / 3)
        self.on_change = on_change
        self._stop = threading.Event()
        self._threads = []
        self._reported = set()

    def _hb_key(self, rank):
        return f"elastic/hb/{rank}"

    def _heartbeat_loop(self):
        # monotonically increasing counter (store.add), NOT a wall-clock
        # timestamp: peers judge staleness by lack of counter *progress*
        # against their own local clock, so cross-host clock skew cannot
        # produce false dead-peer events (ADVICE r1).
        while not self._stop.is_set():
            self.store.add(self._hb_key(self.rank), 1)
            self._stop.wait(self.interval)

    def _counter(self, rank):
        try:
            raw = self.store.get(self._hb_key(rank), timeout=1.0)
        except (TimeoutError, ValueError):
            return None
        from paddle_tpu.native import decode_counter
        try:
            return decode_counter(raw)
        except ValueError:
            return None

    def _watch_loop(self):
        # wait for everyone to register once before judging liveness
        for r in range(self.world_size):
            if self._stop.is_set():
                return
            try:
                self.store.get(self._hb_key(r), timeout=self.ttl)
            except TimeoutError:
                pass
        # progress-judged liveness core shared with ReplicaDirectory
        # (distributed/liveness.py): counters progress, local clock
        # judges
        from paddle_tpu.distributed.liveness import ProgressJudge
        judge = ProgressJudge()
        while not self._stop.is_set():
            now = time.monotonic()
            dead = []
            for r in range(self.world_size):
                if r == self.rank:
                    continue
                c = self._counter(r)
                if judge.update(r, c, now=now):
                    # heartbeat resumed → eligible for re-reporting if it
                    # dies again after a recovery (ADVICE r1)
                    if c is not None:
                        self._reported.discard(r)
                    continue
                if judge.stalled_for(r, now=now) > self.ttl:
                    dead.append(r)
            fresh = [r for r in dead if r not in self._reported]
            if fresh and self.on_change is not None:
                from paddle_tpu import stats
                stats.add("elastic/peers_lost", len(fresh))  # §5.5
                self._reported.update(fresh)
                self.on_change(sorted(fresh))
            self._stop.wait(self.interval)

    def start(self):
        for fn in (self._heartbeat_loop, self._watch_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads = []


class ElasticRegistry:
    """Master-side membership / rank-table service over the native TCPStore
    (≙ launch/controllers/master.py:66 HTTPMaster / :178 ETCDMaster, which
    the reference backs with HTTP or etcd; here the framework's own C++
    store is the registry plane).

    Protocol (all keys under ``elastic/``):
      - each node-launcher publishes ``nodes/{version}/{node_rank}`` =
        its alive local-worker count for membership round ``version``;
      - the master (node 0's launcher) collects announcements for the
        round, assigns contiguous global-rank ranges, and publishes
        ``table/{version}`` = "node:start:n,..." plus bumps ``version``;
      - every node-launcher polls ``wait_table(version)`` and (re)launches
        its local group with the assigned ranks and the new world size.

    A membership change (worker/node death) is simply a new round at
    version+1 with fewer announced workers: the cluster re-forms at N−1
    instead of restarting at N (VERDICT r2 item 5).
    """

    def __init__(self, store, node_rank: int, is_master: bool = False):
        self.store = store
        self.node_rank = node_rank
        self.is_master = is_master

    def publish(self, version: int, n_workers: int):
        self.store.set(f"elastic/nodes/{version}/{self.node_rank}",
                       str(n_workers))

    def form_table(self, version: int, nnodes: int, timeout: float = 30.0,
                   grace: float = 1.0, nnodes_min: int = 1):
        """Master only: gather this round's announcements and publish the
        rank table. Waits up to ``timeout`` for ``nnodes_min`` nodes
        (the elastic range's hard lower bound, ≙ --np MIN:MAX), then
        ``grace`` seconds for stragglers beyond the minimum; nodes that
        miss the window are dropped from the membership (that IS the
        elastic semantics)."""
        assert self.is_master
        members = {}
        deadline = time.monotonic() + timeout
        while len(members) < nnodes_min and time.monotonic() < deadline:
            members = self._poll_round(version, nnodes, per_key_timeout=1.0)
            if len(members) < nnodes_min:
                time.sleep(0.1)
        if len(members) < nnodes_min:
            raise TimeoutError(
                f"round {version}: only {len(members)} of the required "
                f"{nnodes_min} nodes announced within {timeout}s")
        grace_end = time.monotonic() + grace
        while len(members) < nnodes and time.monotonic() < grace_end:
            time.sleep(0.1)
            members = self._poll_round(version, nnodes, per_key_timeout=0.2)
        start = 0
        parts = []
        for node in sorted(members):
            n = members[node]
            parts.append(f"{node}:{start}:{n}")
            start += n
        self.store.set(f"elastic/table/{version}", ",".join(parts))
        self.store.set("elastic/version", str(version))
        return self.get_table(version)

    def _poll_round(self, version, nnodes, per_key_timeout):
        members = {}
        for node in range(nnodes):
            try:
                raw = self.store.get(f"elastic/nodes/{version}/{node}",
                                     timeout=per_key_timeout)
                n = int(raw)
                if n > 0:
                    members[node] = n
            except (TimeoutError, ValueError):
                continue
        return members

    def wait_table(self, version: int, timeout: float = 60.0):
        raw = self.store.get(f"elastic/table/{version}", timeout=timeout)
        table = {}
        for part in raw.decode().split(","):
            node, start, n = part.split(":")
            table[int(node)] = (int(start), int(n))
        world = sum(n for _, n in table.values())
        return table, world

    def get_table(self, version: int):
        return self.wait_table(version, timeout=5.0)
