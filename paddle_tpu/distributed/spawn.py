"""paddle.distributed.spawn analog — in-Python multiprocess launch.

Reference analog: python/paddle/distributed/spawn.py:482 ``spawn(func,
args, nprocs, ...)`` — the multiprocessing alternative to the launch CLI
for users who want to start workers from a script instead of a shell.

TPU note: one process per HOST drives all local chips (SURVEY §5.8), so
``nprocs`` here means host-process count — useful for CPU-mesh testing
and for driving per-process data workers, not for splitting one host's
chips (that's what the device mesh is for).
"""

import multiprocessing as mp
import os
import sys
import traceback

__all__ = ["spawn", "ProcessContext"]


def _worker(fn, args, env, rank, err_dir):
    for k, v in env.items():
        os.environ[k] = v
    try:
        fn(*args)
    except SystemExit as e:
        if e.code in (0, None):
            raise  # intentional clean exit is not a failure
        with open(os.path.join(err_dir, f"err_{rank}"), "w") as f:
            f.write(traceback.format_exc())
        sys.exit(1)
    except BaseException:
        with open(os.path.join(err_dir, f"err_{rank}"), "w") as f:
            f.write(traceback.format_exc())
        sys.exit(1)


class ProcessContext:
    """Join handle over spawned workers (≙ the context returned by the
    reference's spawn with join=False)."""

    def __init__(self, procs, err_dir):
        self.processes = procs
        self._err_dir = err_dir

    def join(self, timeout=None):
        """Wait for every worker; raises RuntimeError with the failing
        rank's traceback if any exited non-zero. Returns False (like
        torch.multiprocessing) when a timeout expires with workers still
        running."""
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        for p in self.processes:
            p.join(None if deadline is None
                   else max(0.0, deadline - _time.monotonic()))
        if any(p.exitcode is None for p in self.processes):
            return False
        for rank, p in enumerate(self.processes):
            if p.exitcode:
                path = os.path.join(self._err_dir, f"err_{rank}")
                detail = ""
                if os.path.exists(path):
                    with open(path) as f:
                        detail = f.read()
                self.terminate()
                raise RuntimeError(
                    f"spawn worker {rank} exited with code {p.exitcode}\n"
                    f"{detail}")
        return True

    def terminate(self):
        for p in self.processes:
            if p.is_alive():
                p.terminate()


_KNOWN_OPTIONS = {"gpus", "xpus", "ips", "backend"}  # accepted for API
# parity with the reference spawn (device selection is the mesh's job on
# TPU); anything else is a typo and raises


def spawn(func, args=(), nprocs=1, join=True, daemon=False,
          master_port=23471, start_method="spawn", **options):
    """Start ``nprocs`` processes running ``func(*args)`` with the same
    PT_* env contract the launch CLI writes (ref spawn.py:482; workers
    read it through ``init_parallel_env``).

    join=True blocks and re-raises worker failures; join=False returns a
    :class:`ProcessContext`.
    """
    unknown = set(options) - _KNOWN_OPTIONS
    if unknown:
        raise TypeError(f"spawn got unknown options {sorted(unknown)}")
    import tempfile
    ctx = mp.get_context(start_method)
    err_dir = tempfile.mkdtemp(prefix="pt_spawn_")
    procs = []
    for rank in range(nprocs):
        env = {
            "PT_COORDINATOR": f"127.0.0.1:{master_port}",
            "PT_NUM_PROCESSES": str(nprocs),
            "PT_PROCESS_ID": str(rank),
            "PT_LOCAL_RANK": str(rank),
            "PT_NNODES": "1",
        }
        p = ctx.Process(target=_worker, args=(func, args, env, rank,
                                              err_dir), daemon=daemon)
        p.start()
        procs.append(p)
    pc = ProcessContext(procs, err_dir)
    if join:
        pc.join()
        return None
    return pc
