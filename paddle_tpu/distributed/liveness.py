"""Progress-judged liveness: the ONE counter-vs-local-clock core.

Both liveness planes in the framework use the same idiom (ADVICE r1):
a peer publishes a monotonically increasing counter (``store.add``),
and an observer judges it dead when the counter stops *progressing*
against the OBSERVER's own monotonic clock. Wall clocks never cross
the wire, so cross-host clock skew cannot fabricate a death. Until
this module the idiom lived twice — ``elastic.ElasticManager`` (TTL'd
training-peer watch) and ``membership.ReplicaDirectory`` (serving-
replica liveness) each kept their own ``{key: (counter, t_progress)}``
bookkeeping. :class:`ProgressJudge` is that bookkeeping, once; both
classes delegate to it and keep their public surfaces unchanged.
"""

import time
from typing import Dict, Optional, Tuple

__all__ = ["ProgressJudge"]


class ProgressJudge:
    """Observer-local progress state: key -> (last counter, local
    monotonic time that counter last ADVANCED).

    The contract, shared by every caller:

    - :meth:`update` folds one observation in and reports whether the
      counter progressed. The FIRST observation of a key always counts
      as progress (the key just became visible); afterwards only a
      changed non-None counter does. A ``None`` counter (transient
      store-read failure) never counts as progress but also never
      *resets* the progress clock — only elapsed time without observed
      progress kills a peer.
    - :meth:`stalled_for` is how long the key has gone without
      progress on THIS observer's clock; the caller compares it to its
      own TTL / dead-after horizon.
    """

    def __init__(self):
        self._seen: Dict[object, Tuple[Optional[int], float]] = {}

    def has(self, key) -> bool:
        """True once the key has been observed at least once."""
        return key in self._seen

    def update(self, key, counter: Optional[int],
               now: Optional[float] = None) -> bool:
        """Fold one counter observation; True iff it PROGRESSED."""
        now = time.monotonic() if now is None else now
        prev = self._seen.get(key)
        if prev is None or (counter is not None and counter != prev[0]):
            self._seen[key] = (counter, now)
            return True
        return False

    def stalled_for(self, key,
                    now: Optional[float] = None) -> Optional[float]:
        """Seconds since the key last progressed (None = never seen)."""
        prev = self._seen.get(key)
        if prev is None:
            return None
        return (time.monotonic() if now is None else now) - prev[1]

    def alive(self, key, ttl: float,
              now: Optional[float] = None) -> bool:
        """True while the key's last progress is within ``ttl``."""
        stalled = self.stalled_for(key, now=now)
        return stalled is not None and stalled <= ttl

    def forget(self, key):
        self._seen.pop(key, None)
