"""Live in-HBM array redistribution: elastic events in O(collective),
not O(checkpoint) (ISSUE 16 tentpole, training half; docs/elastic.md).

PR 14's ElasticTrainer pays a full disk round-trip per reshape — save,
re-plan, ``load_resharded``. But when the surviving devices still hold
the state, the redistribution is pure data movement: every (old
mesh/layout → new mesh/layout) pair over the PR 8 SpecLayout
vocabulary lowers to a schedule of all-to-all / all-gather / slice
transfers that XLA executes device-to-device when :func:`redistribute`
re-commits each live array to its target ``NamedSharding``
(``jax.device_put`` compiles to the collective on TPU; on the CPU test
meshes it is the same resharding engine minus the ICI). The
stacked ↔ per-layer block-layout conversion rides along as pure
reshapes (``stack`` / layer slicing), so the pass moves state between
ANY two topologies ``init_train_state`` can produce — the same
envelope ``load_resharded`` covers, bit-exactly, without touching
disk.

Contract with the checkpoint path (kept, never replaced):

- **fallback**: any leaf the planner can't prove (missing source,
  shape/layer gap), any injected fault at the ``redistribute.schedule``
  site, and any post-transfer digest mismatch raises
  :class:`RedistributeError` — the caller (``fleet/elastic_train.py``)
  degrades to save → ``restore_resharded``, counted under
  ``fleet/reshard_fallbacks``;
- **oracle**: tests drive the same (mesh, layout) chain through both
  paths and assert bit-identical leaves — the checkpoint path is the
  ground truth the in-HBM path must match.

``PT_RESHARD_VERIFY=1`` (default) digests every source leaf before the
move and its target after, so in-transit corruption (the chaos gate's
``bitflip``) degrades to the fallback loudly instead of training on
silently corrupted state.
"""

import hashlib
import os
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.checkpoint import (_canon_per_layer,
                                               _sharding_of, name_leaves)

__all__ = ["RedistributeError", "Transfer", "plan_redistribute",
           "redistribute", "reshard_verify"]


class RedistributeError(RuntimeError):
    """The planner can't prove this redistribution (or verification
    caught a corrupted transfer) — degrade to the checkpoint path."""


def reshard_verify() -> bool:
    """``PT_RESHARD_VERIFY`` (default 1): digest every leaf before and
    after the move; a mismatch raises instead of returning corrupted
    state. 0 trades the host round-trip for speed on trusted fabrics."""
    return os.environ.get("PT_RESHARD_VERIFY", "1") != "0"


@dataclass
class Transfer:
    """One planned leaf move: ``op`` is the collective class the
    (src sharding → dst sharding) pair lowers to, ``layout`` the block
    conversion riding along (``direct`` / ``stack`` / ``unstack``)."""
    name: str
    op: str          # local | replicate | all-gather | slice | all-to-all
    layout: str      # direct | stack | unstack
    shape: tuple
    src: str
    dst: str


def _spec_desc(sharding) -> str:
    if sharding is None:
        return "uncommitted"
    spec = getattr(sharding, "spec", None)
    return str(spec) if spec is not None else type(sharding).__name__


def _is_sharded(sharding) -> bool:
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return False
    return any(p is not None for p in spec)


def _classify(src_sh, dst_sh) -> str:
    """The collective class a (src → dst) sharding pair lowers to —
    the schedule row the tests and the flight recorder see."""
    if _spec_desc(src_sh) == _spec_desc(dst_sh) and src_sh is not None \
            and dst_sh is not None and \
            getattr(src_sh, "device_set", 0) == getattr(dst_sh,
                                                        "device_set", 1):
        return "local"
    src_p, dst_p = _is_sharded(src_sh), _is_sharded(dst_sh)
    if not src_p and not dst_p:
        return "replicate"
    if src_p and not dst_p:
        return "all-gather"
    if not src_p and dst_p:
        return "slice"
    return "all-to-all"


def _gather_sources(src_state):
    """(direct leaves, per-layer groups keyed by stacked name)."""
    src = name_leaves(src_state)
    layers: Dict[str, Dict[int, str]] = {}
    for n, v in src.items():
        if not hasattr(v, "shape"):
            continue
        c = _canon_per_layer(n)
        if c is not None:
            layers.setdefault(c[0], {})[c[1]] = n
    return src, layers


def _resolve(name, leaf, src, src_layers):
    """The source value + layout conversion for one target leaf, or a
    RedistributeError naming the gap (the fallback trigger)."""
    shape = tuple(leaf.shape)
    direct = src.get(name)
    if hasattr(direct, "shape"):
        if tuple(direct.shape) != shape:
            raise RedistributeError(
                f"{name}: source shape {tuple(direct.shape)} != target "
                f"shape {shape}")
        return direct, "direct"
    if name in src_layers:
        # target stacked, source per-layer: stack the layer leaves
        per = src_layers[name]
        L = shape[0]
        missing = [l for l in range(L) if l not in per]
        if missing:
            raise RedistributeError(
                f"{name}: per-layer source lacks layers {missing} "
                f"(have {sorted(per)})")
        blk = src[per[0]]
        if tuple(blk.shape) != shape[1:]:
            raise RedistributeError(
                f"{name}: per-layer source shape {tuple(blk.shape)} "
                f"does not stack to {shape}")
        return jnp.stack([src[per[l]] for l in range(L)]), "stack"
    c = _canon_per_layer(name)
    stacked = src.get(c[0]) if c else None
    if hasattr(stacked, "shape"):
        # target per-layer, source stacked: slice one layer out
        if tuple(stacked.shape)[1:] != shape:
            raise RedistributeError(
                f"{name}: stacked source {tuple(stacked.shape)} does "
                f"not slice to {shape}")
        if not 0 <= c[1] < stacked.shape[0]:
            raise RedistributeError(
                f"{name}: stacked source lacks layer {c[1]}")
        return stacked[c[1]], "unstack"
    raise RedistributeError(
        f"no source for target leaf {name!r} (neither direct, "
        f"per-layer, nor stacked)")


def plan_redistribute(src_state, dst_template,
                      mesh=None) -> List[Transfer]:
    """Lower the (src state → dst template) pair into its transfer
    schedule WITHOUT moving anything — the provable-plan gate and the
    tests' schedule-shape oracle. Raises :class:`RedistributeError`
    when any target leaf has no provable source."""
    src, src_layers = _gather_sources(src_state)
    out: List[Transfer] = []
    for name, leaf in name_leaves(dst_template).items():
        if not hasattr(leaf, "shape"):
            continue
        value, layout = _resolve(name, leaf, src, src_layers)
        src_sh = _sharding_of(value, None)
        dst_sh = _sharding_of(leaf, mesh)
        out.append(Transfer(name=name,
                            op=_classify(src_sh, dst_sh),
                            layout=layout, shape=tuple(leaf.shape),
                            src=_spec_desc(src_sh),
                            dst=_spec_desc(dst_sh)))
    return out


def _digest(host: np.ndarray) -> str:
    return hashlib.sha256(
        np.ascontiguousarray(host).tobytes()).hexdigest()


def redistribute(src_state, dst_template, mesh=None,
                 verify: Optional[bool] = None):
    """Move ``src_state``'s live arrays onto ``dst_template``'s mesh,
    shardings, and block layout — returns a new state pytree shaped
    like ``dst_template``, bit-identical to what ``load_resharded``
    would have produced from a checkpoint of ``src_state``.

    ``mesh`` applies the ``restore_like`` normalization: template
    leaves whose sharding does not span the whole mesh land
    mesh-replicated (jit-created optimizer scalars).

    Raises :class:`RedistributeError` (unprovable plan, digest
    mismatch) or whatever the ``redistribute.schedule`` fault plan
    injects — callers degrade to the checkpoint path on ANY failure;
    a partial move never escapes (the source state stays intact).
    """
    from paddle_tpu.observability import flight
    from paddle_tpu.testing import faults
    if verify is None:
        verify = reshard_verify()
    # the documented reshard fault site: index 0 is the plan itself
    # (raise/kill here = schedule never proved); indices 1.. are the
    # per-leaf transfers in plan order (bitflip at index k corrupts
    # leaf k-1 in transit — verification must veto it)
    faults.fire("redistribute.schedule")
    src, src_layers = _gather_sources(src_state)
    leaves, treedef = jax.tree_util.tree_flatten(dst_template)
    names = list(name_leaves(dst_template))
    if len(names) != len(leaves):
        raise RedistributeError(
            "template names/leaves mismatch: the template carries "
            "non-pytree leaves the walker saw")
    ops: Dict[str, int] = {}
    out = []
    for name, leaf in zip(names, leaves):
        if not hasattr(leaf, "shape"):
            sv = src.get(name)
            out.append(leaf if sv is None else sv)
            continue
        value, layout = _resolve(name, leaf, src, src_layers)
        dst_sh = _sharding_of(leaf, mesh)
        op = _classify(_sharding_of(value, None), dst_sh)
        ops[op] = ops.get(op, 0) + 1
        want = None
        if verify or faults.enabled():
            # ptlint: disable=PT001 -- deliberate device→host copy:
            # the pre-move digest of the verification contract (and
            # the chaos gate's in-transit corruption point)
            host = np.asarray(value)
            want = _digest(host)
            if faults.enabled():
                buf = faults.transform("redistribute.schedule",
                                       host.tobytes())
                value = np.frombuffer(buf, host.dtype).reshape(
                    host.shape)
        np_dtype = (leaf.dtype if isinstance(leaf.dtype, np.dtype)
                    else np.dtype(str(leaf.dtype)))
        if value.dtype != np_dtype:
            value = value.astype(np_dtype)
        if dst_sh is None:
            moved = jnp.asarray(value)
        else:
            moved = jax.device_put(value, dst_sh)
        if verify:
            # ptlint: disable=PT001 -- the post-move digest: in-transit
            # corruption degrades to the checkpoint fallback, loudly
            got = _digest(np.asarray(moved))
            if got != want:
                raise RedistributeError(
                    f"{name}: post-transfer digest mismatch "
                    f"({got[:12]} != {want[:12]}) — in-transit "
                    f"corruption, falling back to the checkpoint path")
        out.append(moved)
    flight.record("fleet", "reshard", phase="schedule",
                  leaves=sum(ops.values()), ops=ops,
                  verified=bool(verify))
    return jax.tree_util.tree_unflatten(treedef, out)
