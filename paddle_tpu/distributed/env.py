"""Process-level distributed environment.

Reference analog: the launch env contract (PADDLE_TRAINER_ID,
PADDLE_TRAINER_ENDPOINTS set by python/paddle/distributed/launch/main.py:18)
plus TCPStore rendezvous (paddle/fluid/distributed/store/tcp_store.cc).

TPU-native: ``jax.distributed.initialize`` is the coordination service (it
replaces TCPStore + gen_comm_id entirely); one *process* per host drives all
local chips, and in-program communication is XLA collectives — so "rank"
here is the host-process index, not a per-chip rank.
"""

import os

import jax

_initialized = False


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None, setup_deadline=None):
    """ref: paddle.distributed.init_parallel_env (distributed/parallel.py:98).

    Single-process (the common TPU case — all local chips visible): no-op.
    Multi-host: wires jax.distributed.initialize from args or the
    PT_COORDINATOR/PT_NUM_PROCESSES/PT_PROCESS_ID env contract set by
    ``paddle_tpu.distributed.launch``.

    Collective setup runs deadline-guarded (`resilience.with_deadline`):
    a coordinator that never comes up fails within ``setup_deadline``
    seconds (env PT_INIT_DEADLINE, default 120) with retries/backoff on
    transient connection errors, instead of blocking a relaunch forever.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "PT_COORDINATOR")
    if coordinator_address:
        from paddle_tpu.distributed import resilience
        from paddle_tpu.testing import faults

        num_processes = num_processes or int(os.environ["PT_NUM_PROCESSES"])
        process_id = process_id if process_id is not None else int(
            os.environ["PT_PROCESS_ID"])
        # observability rank tagging (trace pid lanes, stats.export rank)
        # reads PT_PROCESS_ID — publish it for callers that passed
        # process_id explicitly instead of via the launch env contract
        os.environ.setdefault("PT_PROCESS_ID", str(process_id))
        if setup_deadline is None:
            setup_deadline = float(os.environ.get("PT_INIT_DEADLINE", 120))

        def _connect():
            faults.fire("collective.init")
            # initialization_timeout bounds the blocking connect INSIDE
            # jax (default 300s) — without it the outer deadline could
            # only be checked between attempts
            try:
                jax.distributed.initialize(
                    coordinator_address, num_processes, process_id,
                    initialization_timeout=max(1, int(setup_deadline)))
            except Exception:
                # a failed attempt leaves jax's global client/service
                # assigned, which would turn every retry into
                # "initialize should only be called once" — reset so the
                # retry really reconnects
                try:
                    jax.distributed.shutdown()
                except Exception:
                    pass
                raise

        # RuntimeError included: jax wraps grpc UNAVAILABLE in
        # XlaRuntimeError (a RuntimeError), and _connect's shutdown
        # cleanup makes a re-initialize legal
        resilience.with_deadline(
            _connect, seconds=setup_deadline, op="collective_init",
            retry_on=(TimeoutError, ConnectionError, OSError,
                      RuntimeError))()
    _initialized = True


def get_rank():
    """Host-process index (ref: paddle.distributed.get_rank)."""
    return jax.process_index()


def get_world_size():
    """Number of host processes (ref: paddle.distributed.get_world_size
    counts chips; here chips-per-process × process_count = chip world)."""
    return jax.process_count()


def get_chip_count():
    return jax.device_count()


def is_initialized():
    return _initialized


class ParallelEnv:
    """ref: paddle.distributed.ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()
