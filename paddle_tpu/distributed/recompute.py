"""Recompute (activation checkpointing) API.

Reference analog: fleet/recompute/recompute.py (RecomputeFunction:224,
recompute():386 — a PyLayer that re-runs the forward under tracked RNG
during backward) and recompute_hybrid.py:69 (_HPRecomputeFunction — the
MP-aware variant with optional CPU offload of checkpointed activations).

On TPU the mechanism is jax.checkpoint: the compiler re-runs the forward
inside the transposed program, RNG correctness falls out of explicit PRNG
keys (no RNGStatesTracker state machine needed), and *what* is saved is a
first-class policy instead of PyLayer bookkeeping:

- recompute(fn, *args)                       ≙ fleet.utils.recompute
- recompute(..., policy="dots_saveable")     ≙ selective-save; policies map
  onto jax.checkpoint_policies (the saved_tensors_hooks analog)
- recompute(..., offload=True)               ≙ recompute_hybrid offload —
  jax's offloadable policies move residuals to host memory
- recompute_sequential(fns, x, segments=k)   ≙ fleet.utils
  .recompute_sequential: split a layer stack into k segments, checkpoint
  each boundary
- checkpoint_name(x, "name") + save_only_these_names ≙ per-tensor
  selective save lists
"""

import functools
from typing import Callable, Optional, Sequence, Union

import jax
from jax import ad_checkpoint

__all__ = ["recompute", "recompute_sequential", "checkpoint_name",
           "POLICIES"]

checkpoint_name = ad_checkpoint.checkpoint_name

# name → jax checkpoint policy (jax.checkpoint_policies.*); the reference's
# single recompute mode corresponds to "nothing_saveable"
POLICIES = {
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}


def _resolve_policy(policy, offload: bool):
    if policy is None:
        if offload:
            # ≙ recompute_hybrid CPU offload: save residuals to host memory
            return jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=[],
                names_which_can_be_offloaded=[],
                offload_src="device", offload_dst="pinned_host")
        return None  # jax default: save nothing across the boundary
    if callable(policy):
        return policy
    if isinstance(policy, str):
        if policy in POLICIES:
            return POLICIES[policy]
        raise ValueError(
            f"unknown recompute policy {policy!r}; one of {list(POLICIES)} "
            f"or a jax.checkpoint_policies callable")
    if isinstance(policy, (list, tuple)):
        # selective save list of checkpoint_name strings
        # (≙ saved_tensors_hooks keeping only chosen activations)
        return jax.checkpoint_policies.save_only_these_names(*policy)
    raise TypeError(f"bad policy: {policy!r}")


def recompute(function: Callable, *args,
              policy=None, offload: bool = False,
              prevent_cse: bool = True, static_argnums=(),
              preserve_rng_state: bool = True, use_reentrant: bool = True,
              **kwargs):
    """Run ``function(*args)`` now; rematerialize its intermediates during
    backward instead of saving them (≙ fleet.utils.recompute,
    recompute.py:386).

    policy: None (save nothing), a POLICIES name, a list of
    checkpoint_name strings to save, or any jax.checkpoint_policies
    callable. preserve_rng_state/use_reentrant are accepted for reference
    API parity (both are inherent to tracing: PRNG keys are explicit
    operands, and there is no autograd tape to re-enter).
    """
    fn = jax.checkpoint(function, policy=_resolve_policy(policy, offload),
                        prevent_cse=prevent_cse,
                        static_argnums=static_argnums)
    return fn(*args, **kwargs)


def recompute_wrapper(function: Callable = None, **ckpt_kwargs):
    """Decorator form: ``@recompute_wrapper(policy=...)``."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*a, **k):
            return recompute(fn, *a, **ckpt_kwargs, **k)
        return wrapped
    return deco(function) if function is not None else deco


def recompute_sequential(functions: Sequence[Callable], x,
                         segments: int = 1, policy=None, **kwargs):
    """Apply a layer list in ``segments`` checkpointed chunks
    (≙ fleet.utils.recompute_sequential): only segment-boundary
    activations survive to the backward pass, intermediates within a
    segment re-run."""
    fns = list(functions)
    n = len(fns)
    segments = max(1, min(segments, n))
    bounds = [round(i * n / segments) for i in range(segments + 1)]

    for lo, hi in zip(bounds[:-1], bounds[1:]):
        if lo == hi:
            continue

        def seg(h, _fns=fns[lo:hi]):
            for f in _fns:
                h = f(h)
            return h

        x = recompute(seg, x, policy=policy, **kwargs)
    return x
