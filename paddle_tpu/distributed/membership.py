"""Serving-replica membership over the native TCPStore.

The serving router (``paddle_tpu/serving/router.py``) needs a lighter
contract than elastic training membership (``elastic.py``): replicas
never form a collective — they only need to be *discoverable* (the
router learns who exists), *describable* (slots, pid, endpoint), and
*judgeable* (alive or dead, so queued work can be redistributed).

The store has no key-listing op, so announcements go through a counter
index: ``announce`` bumps ``<ns>/n`` and writes ``<ns>/idx/<i>`` →
replica id, plus ``<ns>/meta/<rid>`` with the JSON metadata. Liveness
is the shared progress-judged core (``distributed/liveness.py``, the
same one ``elastic.ElasticManager`` watches training peers with):
heartbeats are monotonically increasing counters (``store.add``), and
a peer is dead when its counter stops *progressing* against the
OBSERVER's local clock — wall clocks never cross the wire, so clock
skew cannot fabricate a death.

Replicas additionally carry a LIFECYCLE STATE (``<ns>/state/<rid>``)
for the fleet controller's drain protocol (docs/elastic.md): ``up``
(default — routable), ``draining`` (the router stops placing new
work; the replica finishes or hands back its in-flight requests),
``drained`` (the replica finished its drain and is about to exit).
"""

import json
import time
from typing import Dict, Optional

from paddle_tpu.distributed.liveness import ProgressJudge

__all__ = ["ReplicaDirectory"]


class ReplicaDirectory:
    """Announce/discover/judge serving replicas on a shared TCPStore.

    One instance per process; the router polls :meth:`members` +
    :meth:`alive`, each replica calls :meth:`announce` once and
    :meth:`heartbeat` from its serve loop.
    """

    def __init__(self, store, namespace: str = "serve"):
        self.store = store
        self.ns = namespace
        # observer-local liveness state: the shared progress-judged
        # core (one bookkeeping implementation for elastic + serving)
        self._judge = ProgressJudge()

    # -- replica side -------------------------------------------------------

    def announce(self, rid: str, meta: Optional[dict] = None,
                 retry: Optional["RetryPolicy"] = None,
                 deadline: float = 15.0):
        """Register ``rid`` (idempotent for re-announce: metadata is
        overwritten, the index gains at most one extra pointer).

        At fleet spawn a worker can reach this before the router's
        store has finished binding, so the whole registration runs
        under a `resilience.RetryPolicy` — a slow bind costs backoff,
        not a dead-on-arrival worker (which the controller would then
        heal-loop on). ``state`` is re-seeded ``up`` only when absent,
        so a re-announce after a router failover cannot resurrect a
        draining replica into the routable pool.

        ``meta`` carries the replica's STATIC description — the router
        reads it once per membership refresh. The serving fields the
        disaggregated router places by: ``role`` (``prefill`` /
        ``decode`` / ``both``), ``page`` (KV page size), ``max_bucket``
        (largest prefill bucket — the router's bucket-fit screen),
        ``slots``."""
        from paddle_tpu.distributed import resilience

        def register():
            self.store.set(f"{self.ns}/meta/{rid}",
                           json.dumps(meta or {}))
            # seed the lifecycle state so state() hits on the first
            # read — a missing key costs the full store get-with-wait
            # timeout — but never clobber an existing (draining) state
            try:
                self.store.get(f"{self.ns}/state/{rid}", timeout=0.02)
            except (TimeoutError, ValueError):
                self.store.set(f"{self.ns}/state/{rid}", "up")
            i = self.store.add(f"{self.ns}/n", 1)
            self.store.set(f"{self.ns}/idx/{i}", rid)
            self.heartbeat(rid)

        pol = retry or resilience.RetryPolicy(
            max_attempts=16, base_delay=0.05, max_delay=1.0,
            deadline=deadline)
        pol.run(register, op="membership.announce",
                retry_on=(ConnectionError, OSError, RuntimeError,
                          resilience.StorePartitioned),
                deadline=resilience.Deadline(deadline))

    def heartbeat(self, rid: str, load: Optional[dict] = None,
                  stats: Optional[dict] = None) -> int:
        """Bump the liveness counter; when ``load`` is given, refresh
        the replica's gauge-style load fields FIRST (so an observer
        that sees the new counter sees load at least that fresh).
        Routing state therefore costs the router ONE store read per
        replica per poll (:meth:`load`) — no per-request round trips.
        The disaggregated router's fields: ``queued`` (admission queue
        depth), ``free_slots``, ``free_pages``, ``kv_bytes``
        (outstanding KV bytes across live slots).

        ``stats`` attaches a full ``paddle_tpu.stats.export()``
        snapshot the same way — the fleet telemetry plane
        (``observability/fleet.FleetStats``) merges the latest export
        per replica into the fleet-level /statsz, at the cost of one
        more store write per refresh beat (never per request)."""
        if load is not None:
            self.store.set(f"{self.ns}/load/{rid}", json.dumps(load))
        if stats is not None:
            self.store.set(f"{self.ns}/stats/{rid}", json.dumps(stats))
        return self.store.add(f"{self.ns}/hb/{rid}", 1)

    # -- observer side ------------------------------------------------------

    def members(self) -> Dict[str, dict]:
        """Every replica ever announced (dead ones included — liveness
        is :meth:`alive`'s call), rid -> metadata."""
        from paddle_tpu import native
        try:
            n = native.decode_counter(
                self.store.get(f"{self.ns}/n", timeout=0.05))
        except (TimeoutError, ValueError):
            return {}
        out: Dict[str, dict] = {}
        for i in range(1, n + 1):
            try:
                rid = self.store.get(f"{self.ns}/idx/{i}",
                                     timeout=0.2).decode()
                out[rid] = json.loads(
                    self.store.get(f"{self.ns}/meta/{rid}", timeout=0.2))
            except (TimeoutError, ValueError):
                continue
        return out

    def load(self, rid: str) -> Optional[dict]:
        """The replica's last heartbeat-refreshed load gauges (one
        store read), or None when it has never published any."""
        try:
            return json.loads(
                self.store.get(f"{self.ns}/load/{rid}", timeout=0.05))
        except (TimeoutError, ValueError):
            return None

    def stats_export(self, rid: str) -> Optional[dict]:
        """The replica's last heartbeat-attached ``stats.export()``
        snapshot (one store read), or None when it never attached
        one."""
        try:
            return json.loads(
                self.store.get(f"{self.ns}/stats/{rid}", timeout=0.05))
        except (TimeoutError, ValueError):
            return None

    def _counter(self, rid: str) -> Optional[int]:
        from paddle_tpu import native
        try:
            return native.decode_counter(
                self.store.get(f"{self.ns}/hb/{rid}", timeout=0.2))
        except (TimeoutError, ValueError):
            return None

    def alive(self, rid: str, dead_after: float = 2.0) -> bool:
        """True while ``rid``'s heartbeat counter keeps advancing;
        False once it stalls for ``dead_after`` seconds of THIS
        process's monotonic clock. A transient store-read failure never
        flips a previously-progressing replica dead by itself — only
        ``dead_after`` seconds without observed progress does."""
        now = time.monotonic()
        c = self._counter(rid)
        if c is None and not self._judge.has(rid):
            return False            # never seen a heartbeat at all
        if self._judge.update(rid, c, now=now):
            return True
        return self._judge.stalled_for(rid, now=now) <= dead_after

    # -- lifecycle state (drain protocol) -----------------------------------

    def set_state(self, rid: str, state: str):
        """Publish ``rid``'s lifecycle state: ``up`` (routable,
        implicit default), ``draining`` (controller asked it to retire
        — the router stops placing on it), ``drained`` (the replica
        finished every in-flight request and is exiting)."""
        if state not in ("up", "draining", "drained"):
            raise ValueError(f"replica state must be up|draining|"
                             f"drained, got {state!r}")
        self.store.set(f"{self.ns}/state/{rid}", state)

    def state(self, rid: str) -> str:
        """``rid``'s last published lifecycle state (announce seeds
        ``up``, so a registered replica's read always hits; ``up``
        is also the fallback for a never-registered rid)."""
        try:
            return self.store.get(f"{self.ns}/state/{rid}",
                                  timeout=0.02).decode()
        except (TimeoutError, ValueError):
            return "up"
