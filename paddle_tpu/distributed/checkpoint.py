"""Distributed (sharded) checkpointing with resharding-on-load.

Reference analog (SURVEY §5.4): sharding optimizers' rank-local
state_dicts + auto_parallel dist_saver.py / converter.py (per-rank
programs+params with dist attrs, resharded on load), and the op-version
registry (framework/op_version_registry.h:397) → the format_version field.

Format (one directory per checkpoint):
    meta.json             format_version, per-array {shape, dtype, shards}
    skeleton.pkl          pytree structure with ARRAY_n placeholders
    data/ARRAY_n.s{k}.npy one file per saved shard (its global index range
                          recorded in meta) — only ONE copy of each distinct
                          shard is written (replicated arrays write once)

Resharding on load: the loader assembles each *needed* slice from whichever
saved shard files overlap it via jax.make_array_from_callback, so a
checkpoint written on mesh A (e.g. fsdp=8) restores onto mesh B (e.g.
dp=2×fsdp=4, or a single chip) reading each byte once. The reference can
only restart on the same topology unless the auto-parallel converter
rewrites states (SURVEY §7.3 hard-part 5); here resharding is native.
"""

import dataclasses
import json
import os
import pickle
from typing import Any, Callable, Dict, Optional, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["save_state", "load_state", "AutoCheckpoint"]

FORMAT_VERSION = 1
_MIN_READABLE_VERSION = 1


class _Py:
    """Skeleton marker for non-array leaves (opaque to tree flattening —
    a bare tuple marker would be descended into as a pytree)."""

    def __init__(self, v):
        self.v = v


def _flatten(state):
    leaves, treedef = jax.tree_util.tree_flatten(state)
    return leaves, treedef


def _shard_ranges(arr: jax.Array):
    """Distinct addressable shards as (index-ranges, numpy data)."""
    seen = {}
    for sh in arr.addressable_shards:
        key = tuple((s.start or 0, s.stop if s.stop is not None else dim)
                    for s, dim in zip(sh.index, arr.shape))
        if key not in seen:
            seen[key] = np.asarray(sh.data)
    return seen


def save_state(state, path: str):
    """Save any pytree of jax/numpy arrays (+ json-able scalars). Each
    distinct device shard is written once; replicated arrays write one
    copy. Works on any mesh, including a single device."""
    os.makedirs(os.path.join(path, "data"), exist_ok=True)
    leaves, treedef = _flatten(state)
    meta = {"format_version": FORMAT_VERSION, "arrays": {}}
    skeleton = []
    for i, leaf in enumerate(leaves):
        name = f"ARRAY_{i}"
        if isinstance(leaf, jax.Array) and not isinstance(leaf, jax.core.Tracer):
            shards = _shard_ranges(leaf)
            entry = {"shape": list(leaf.shape), "dtype": str(leaf.dtype),
                     "shards": []}
            for k, (ranges, data) in enumerate(shards.items()):
                fn = f"{name}.s{k}.npy"
                np.save(os.path.join(path, "data", fn),
                        data, allow_pickle=False)
                entry["shards"].append({"file": fn,
                                        "range": [list(r) for r in ranges]})
            meta["arrays"][name] = entry
            skeleton.append(name)
        elif isinstance(leaf, np.ndarray):
            fn = f"{name}.s0.npy"
            np.save(os.path.join(path, "data", fn), leaf,
                    allow_pickle=False)
            meta["arrays"][name] = {
                "shape": list(leaf.shape), "dtype": str(leaf.dtype),
                "shards": [{"file": fn,
                            "range": [[0, d] for d in leaf.shape]}]}
            skeleton.append(name)
        else:
            skeleton.append(_Py(leaf))
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    with open(os.path.join(path, "skeleton.pkl"), "wb") as f:
        pickle.dump(jax.tree_util.tree_unflatten(treedef, skeleton), f)


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _read_slice(path, entry, index, shape, dtype):
    """Assemble the requested global slice from overlapping saved shards."""
    starts = [s.start or 0 for s in index]
    stops = [s.stop if s.stop is not None else dim
             for s, dim in zip(index, shape)]
    out = np.empty([b - a for a, b in zip(starts, stops)], dtype)
    for sh in entry["shards"]:
        r = sh["range"]
        inter = [(max(a, ra), min(b, rb))
                 for (a, b), (ra, rb) in zip(zip(starts, stops), r)]
        if any(a >= b for a, b in inter):
            continue
        data = np.load(os.path.join(path, "data", sh["file"]),
                       mmap_mode="r")
        if data.dtype.kind == "V":  # ml_dtypes (bf16/fp8) round-trip raw
            data = data.view(dtype)
        src = tuple(slice(a - ra, b - ra)
                    for (a, b), (ra, rb) in zip(inter, r))
        dst = tuple(slice(a - s, b - s)
                    for (a, b), s in zip(inter, starts))
        out[dst] = data[src]
    return out


def load_state(path: str,
               shardings: Optional[Union[Dict[str, Any],
                                         Callable[[str], Any]]] = None,
               template=None):
    """Load a checkpoint directory.

    shardings: None → jnp arrays on the default device;
    a pytree matching the saved structure (leaves NamedSharding / None), or
    a callable mapping the flattened leaf position name ("ARRAY_i") — use
    `template` instead for name-free placement: a pytree of shardings with
    the same structure as the saved state.
    """
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    ver = meta.get("format_version", 0)
    if not (_MIN_READABLE_VERSION <= ver <= FORMAT_VERSION):
        raise ValueError(
            f"checkpoint format_version {ver} unsupported "
            f"(readable: {_MIN_READABLE_VERSION}..{FORMAT_VERSION})")
    with open(os.path.join(path, "skeleton.pkl"), "rb") as f:
        skeleton = pickle.load(f)

    is_sh_leaf = lambda x: x is None or isinstance(x, NamedSharding)
    t_leaves = None
    if template is not None:
        t_leaves = jax.tree_util.tree_leaves(template, is_leaf=is_sh_leaf)
    s_leaves = None
    if shardings is not None and not callable(shardings):
        s_leaves = jax.tree_util.tree_leaves(shardings, is_leaf=is_sh_leaf)

    leaves, treedef = jax.tree_util.tree_flatten(skeleton)
    out = []
    for li, leaf in enumerate(leaves):
        if isinstance(leaf, _Py):
            out.append(leaf.v)
            continue
        name = leaf
        entry = meta["arrays"][name]
        shape = tuple(entry["shape"])
        np_dtype = _np_dtype(entry["dtype"])
        # indexed by overall leaf position: the shardings/template pytree
        # mirrors the SAVED structure, so its non-array positions (None
        # placeholders) keep array positions aligned
        sharding = None
        if callable(shardings):
            sharding = shardings(name)
        elif s_leaves is not None:
            sharding = s_leaves[li]
        elif t_leaves is not None:
            sharding = t_leaves[li]
        if sharding is None:
            arr = jnp.asarray(_read_slice(
                path, entry, tuple(slice(0, d) for d in shape), shape,
                np_dtype))
        else:
            def cb(index, entry=entry, shape=shape, np_dtype=np_dtype):
                return _read_slice(path, entry, index, shape, np_dtype)

            arr = jax.make_array_from_callback(shape, sharding, cb)
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


@dataclasses.dataclass
class AutoCheckpoint:
    """Epoch-range auto checkpoint ≙ the reference's TrainEpochRange
    (fluid/incubate/checkpoint/auto_checkpoint.py:284): snapshot state each
    epoch under a job directory, transparently resume after preemption.

        ck = AutoCheckpoint("/ckpts", job_id="gpt-run-1", keep=2)
        state = ck.restore() or init_state()
        for epoch in ck.epochs(start=ck.next_epoch, end=100):
            state = train_one_epoch(state)
            ck.save(state, epoch)
    """
    root: str
    job_id: str
    keep: int = 2

    def __post_init__(self):
        self.dir = os.path.join(self.root, self.job_id)
        os.makedirs(self.dir, exist_ok=True)

    def _epochs_on_disk(self):
        eps = []
        for d in os.listdir(self.dir):
            if d.startswith("epoch_") and os.path.exists(
                    os.path.join(self.dir, d, "meta.json")):
                eps.append(int(d.split("_")[1]))
        return sorted(eps)

    @property
    def next_epoch(self) -> int:
        eps = self._epochs_on_disk()
        return (eps[-1] + 1) if eps else 0

    def restore(self, shardings=None, template=None):
        """Latest epoch's state, or None if nothing saved yet."""
        eps = self._epochs_on_disk()
        if not eps:
            return None
        return load_state(os.path.join(self.dir, f"epoch_{eps[-1]}"),
                          shardings=shardings, template=template)

    def save(self, state, epoch: int):
        tmp = os.path.join(self.dir, f".tmp_epoch_{epoch}")
        final = os.path.join(self.dir, f"epoch_{epoch}")
        save_state(state, tmp)
        if os.path.exists(final):
            import shutil
            shutil.rmtree(final)
        os.rename(tmp, final)
        for e in self._epochs_on_disk()[:-self.keep]:
            import shutil
            shutil.rmtree(os.path.join(self.dir, f"epoch_{e}"))

    def epochs(self, start: int, end: int):
        return range(start, end)
