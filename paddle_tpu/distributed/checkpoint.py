"""Distributed (sharded) checkpointing with resharding-on-load.

Reference analog (SURVEY §5.4): sharding optimizers' rank-local
state_dicts + auto_parallel dist_saver.py / converter.py (per-rank
programs+params with dist attrs, resharded on load), and the op-version
registry (framework/op_version_registry.h:397) → the format_version field.

Format (one directory per checkpoint):
    meta.json             format_version, per-array {shape, dtype, shards}
    skeleton.pkl          pytree structure with ARRAY_n placeholders
    data/ARRAY_n.s{k}.npy one file per saved shard (its global index range
                          recorded in meta) — only ONE copy of each distinct
                          shard is written (replicated arrays write once)

Resharding on load: the loader assembles each *needed* slice from whichever
saved shard files overlap it via jax.make_array_from_callback, so a
checkpoint written on mesh A (e.g. fsdp=8) restores onto mesh B (e.g.
dp=2×fsdp=4, or a single chip) reading each byte once. The reference can
only restart on the same topology unless the auto-parallel converter
rewrites states (SURVEY §7.3 hard-part 5); here resharding is native.
"""

import dataclasses
import json
import os
import pickle
from typing import Any, Callable, Dict, Optional, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["save_state", "load_state", "AutoCheckpoint"]

FORMAT_VERSION = 1
_MIN_READABLE_VERSION = 1


class _Py:
    """Skeleton marker for non-array leaves (opaque to tree flattening —
    a bare tuple marker would be descended into as a pytree)."""

    def __init__(self, v):
        self.v = v


def _flatten(state):
    leaves, treedef = jax.tree_util.tree_flatten(state)
    return leaves, treedef


def _range_key(index, shape):
    return tuple((s.start or 0, s.stop if s.stop is not None else dim)
                 for s, dim in zip(index, shape))


def _range_tag(key) -> str:
    """Deterministic shard filename fragment from its global index range —
    identical on every process, so multi-host writers never collide on a
    name for *different* data and agree on the name for the same shard."""
    return "x".join(f"{a}-{b}" for a, b in key)


def _global_shard_layout(arr: jax.Array):
    """All distinct shard ranges of the GLOBAL array (not just addressable
    ones), computable identically on every process from the sharding."""
    try:
        idx_map = arr.sharding.devices_indices_map(arr.shape)
        return sorted({_range_key(ix, arr.shape)
                       for ix in idx_map.values()})
    except Exception:
        # addressable-only fallback is complete ONLY when this process sees
        # every device; on multi-host it would write a meta.json missing
        # other hosts' ranges → an unrestorable checkpoint. Fail loudly.
        if jax.process_count() > 1:
            raise
        return sorted({_range_key(sh.index, arr.shape)
                       for sh in arr.addressable_shards})


def _owned_shards(arr: jax.Array):
    """Addressable shards this process must write: exactly the replica-0
    copy of each range (each distinct range has one replica-0 holder
    globally, so across processes every range is written exactly once)."""
    out = {}
    for sh in arr.addressable_shards:
        if sh.replica_id != 0:
            continue
        key = _range_key(sh.index, arr.shape)
        if key not in out:
            out[key] = np.asarray(sh.data)
    return out


def save_state(state, path: str):
    """Save any pytree of jax/numpy arrays (+ json-able scalars).

    Multi-host safe (ADVICE r1): every distinct shard range is written
    exactly once globally — by the process holding its replica-0 copy —
    under a range-derived filename identical on all processes; meta.json
    and skeleton.pkl (whose content is process-independent) are written by
    process 0 only, and a cross-host barrier closes the save so the
    checkpoint is complete when any process returns."""
    try:
        _save_state_local(state, path)
    finally:
        # every process must reach the barrier even if its local write
        # failed — otherwise peers hang forever; the local exception still
        # propagates (and the launcher tears the job down)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"ckpt_save:{path}")


def _save_state_local(state, path: str):
    os.makedirs(os.path.join(path, "data"), exist_ok=True)
    proc0 = jax.process_index() == 0
    leaves, treedef = _flatten(state)
    meta = {"format_version": FORMAT_VERSION, "arrays": {}}
    skeleton = []
    for i, leaf in enumerate(leaves):
        name = f"ARRAY_{i}"
        if isinstance(leaf, jax.Array) and not isinstance(leaf, jax.core.Tracer):
            layout = _global_shard_layout(leaf)
            entry = {"shape": list(leaf.shape), "dtype": str(leaf.dtype),
                     "shards": [{"file": f"{name}.{_range_tag(k)}.npy",
                                 "range": [list(r) for r in k]}
                                for k in layout]}
            for key, data in _owned_shards(leaf).items():
                np.save(os.path.join(path, "data",
                                     f"{name}.{_range_tag(key)}.npy"),
                        data, allow_pickle=False)
            meta["arrays"][name] = entry
            skeleton.append(name)
        elif isinstance(leaf, np.ndarray):
            fn = f"{name}.s0.npy"
            if proc0:
                np.save(os.path.join(path, "data", fn), leaf,
                        allow_pickle=False)
            meta["arrays"][name] = {
                "shape": list(leaf.shape), "dtype": str(leaf.dtype),
                "shards": [{"file": fn,
                            "range": [[0, d] for d in leaf.shape]}]}
            skeleton.append(name)
        else:
            skeleton.append(_Py(leaf))
    if proc0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
        with open(os.path.join(path, "skeleton.pkl"), "wb") as f:
            pickle.dump(jax.tree_util.tree_unflatten(treedef, skeleton), f)


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _read_slice(path, entry, index, shape, dtype):
    """Assemble the requested global slice from overlapping saved shards.

    Verifies the saved shards fully cover the requested slice (ADVICE r1:
    a missing/partial shard file must raise, never restore np.empty
    garbage)."""
    starts = [s.start or 0 for s in index]
    stops = [s.stop if s.stop is not None else dim
             for s, dim in zip(index, shape)]
    out = np.empty([b - a for a, b in zip(starts, stops)], dtype)
    boxes = []  # intersection boxes copied into out (coverage accounting)
    for sh in entry["shards"]:
        r = sh["range"]
        inter = [(max(a, ra), min(b, rb))
                 for (a, b), (ra, rb) in zip(zip(starts, stops), r)]
        if any(a >= b for a, b in inter):
            continue
        f = os.path.join(path, "data", sh["file"])
        if not os.path.exists(f):
            raise ValueError(
                f"checkpoint shard missing: {sh['file']} (range {r}) — "
                f"incomplete save?")
        data = np.load(f, mmap_mode="r")
        if data.dtype.kind == "V":  # ml_dtypes (bf16/fp8) round-trip raw
            data = data.view(dtype)
        src = tuple(slice(a - ra, b - ra)
                    for (a, b), (ra, rb) in zip(inter, r))
        dst = tuple(slice(a - s, b - s)
                    for (a, b), s in zip(inter, starts))
        out[dst] = data[src]
        if tuple(inter) not in boxes:
            boxes.append(tuple(inter))
    if not _boxes_cover(boxes, list(zip(starts, stops))):
        raise ValueError(
            f"saved shards do not cover requested slice "
            f"{list(zip(starts, stops))} of array shape {list(shape)} — "
            f"checkpoint incomplete")
    return out


def _boxes_cover(boxes, target) -> bool:
    """Exact axis-aligned-box coverage check without per-element masks
    (ADVICE r1 follow-up: a bool mask of a 1B-element slice costs 1GB).
    GSPMD shard grids give pairwise-disjoint boxes, so a volume sum decides
    coverage; on (pathological) partial overlap, fall back to coordinate
    compression over the distinct boundaries (#shards^ndim cells, tiny)."""
    if not target or all(a >= b for a, b in target):
        return True  # zero-size slice
    total = 1
    for a, b in target:
        total *= max(0, b - a)
    if total == 0:
        return True
    vol = 0
    for bx in boxes:
        v = 1
        for a, b in bx:
            v *= b - a
        vol += v
    if vol < total:  # even counting overlaps twice there isn't enough
        return False
    if len(boxes) > 512:
        # save_state only writes disjoint GSPMD grids; skip the O(S^2)
        # overlap scan on pod-scale layouts where it would dominate load
        return True
    overlap = False
    for i, bx in enumerate(boxes):
        for by in boxes[i + 1:]:
            if all(max(a1, a2) < min(b1, b2)
                   for (a1, b1), (a2, b2) in zip(bx, by)):
                overlap = True
                break
        if overlap:
            break
    if not overlap:
        return True
    # coordinate compression: every cell between consecutive boundaries is
    # uniform w.r.t. every box, so checking one representative per cell is
    # exact
    import itertools
    coords = []
    for d, (a, b) in enumerate(target):
        cs = {a, b}
        for bx in boxes:
            cs.add(min(max(bx[d][0], a), b))
            cs.add(min(max(bx[d][1], a), b))
        coords.append(sorted(cs))
    for cell in itertools.product(*(zip(c[:-1], c[1:]) for c in coords)):
        if any(lo >= hi for lo, hi in cell):
            continue
        if not any(all(bx[d][0] <= lo and hi <= bx[d][1]
                       for d, (lo, hi) in enumerate(cell))
                   for bx in boxes):
            return False
    return True


def load_state(path: str,
               shardings: Optional[Union[Dict[str, Any],
                                         Callable[[str], Any]]] = None,
               template=None):
    """Load a checkpoint directory.

    shardings: None → jnp arrays on the default device;
    a pytree matching the saved structure (leaves NamedSharding / None), or
    a callable mapping the flattened leaf position name ("ARRAY_i") — use
    `template` instead for name-free placement: a pytree of shardings with
    the same structure as the saved state.
    """
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    ver = meta.get("format_version", 0)
    if not (_MIN_READABLE_VERSION <= ver <= FORMAT_VERSION):
        raise ValueError(
            f"checkpoint format_version {ver} unsupported "
            f"(readable: {_MIN_READABLE_VERSION}..{FORMAT_VERSION})")
    with open(os.path.join(path, "skeleton.pkl"), "rb") as f:
        skeleton = pickle.load(f)

    is_sh_leaf = lambda x: x is None or isinstance(x, NamedSharding)
    t_leaves = None
    if template is not None:
        t_leaves = jax.tree_util.tree_leaves(template, is_leaf=is_sh_leaf)
    s_leaves = None
    if shardings is not None and not callable(shardings):
        s_leaves = jax.tree_util.tree_leaves(shardings, is_leaf=is_sh_leaf)

    leaves, treedef = jax.tree_util.tree_flatten(skeleton)
    out = []
    for li, leaf in enumerate(leaves):
        if isinstance(leaf, _Py):
            out.append(leaf.v)
            continue
        name = leaf
        entry = meta["arrays"][name]
        shape = tuple(entry["shape"])
        np_dtype = _np_dtype(entry["dtype"])
        # indexed by overall leaf position: the shardings/template pytree
        # mirrors the SAVED structure, so its non-array positions (None
        # placeholders) keep array positions aligned
        sharding = None
        if callable(shardings):
            sharding = shardings(name)
        elif s_leaves is not None:
            sharding = s_leaves[li]
        elif t_leaves is not None:
            sharding = t_leaves[li]
        if sharding is None:
            arr = jnp.asarray(_read_slice(
                path, entry, tuple(slice(0, d) for d in shape), shape,
                np_dtype))
        else:
            def cb(index, entry=entry, shape=shape, np_dtype=np_dtype):
                return _read_slice(path, entry, index, shape, np_dtype)

            arr = jax.make_array_from_callback(shape, sharding, cb)
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


@dataclasses.dataclass
class AutoCheckpoint:
    """Epoch-range auto checkpoint ≙ the reference's TrainEpochRange
    (fluid/incubate/checkpoint/auto_checkpoint.py:284): snapshot state each
    epoch under a job directory, transparently resume after preemption.

        ck = AutoCheckpoint("/ckpts", job_id="gpt-run-1", keep=2)
        state = ck.restore() or init_state()
        for epoch in ck.epochs(start=ck.next_epoch, end=100):
            state = train_one_epoch(state)
            ck.save(state, epoch)
    """
    root: str
    job_id: str
    keep: int = 2

    def __post_init__(self):
        self.dir = os.path.join(self.root, self.job_id)
        os.makedirs(self.dir, exist_ok=True)

    def _epochs_on_disk(self):
        eps = []
        for d in os.listdir(self.dir):
            if d.startswith("epoch_") and os.path.exists(
                    os.path.join(self.dir, d, "meta.json")):
                eps.append(int(d.split("_")[1]))
        return sorted(eps)

    @property
    def next_epoch(self) -> int:
        eps = self._epochs_on_disk()
        return (eps[-1] + 1) if eps else 0

    def restore(self, shardings=None, template=None):
        """Latest epoch's state, or None if nothing saved yet."""
        eps = self._epochs_on_disk()
        if not eps:
            return None
        return load_state(os.path.join(self.dir, f"epoch_{eps[-1]}"),
                          shardings=shardings, template=template)

    def restore_like(self, fresh_state, mesh: Optional[Mesh] = None):
        """Resharding resume (the N→M elastic path, ≙ auto_parallel
        converter.py resharding-on-load): load the latest checkpoint ONTO
        the shardings of a freshly-initialized state — typically built on
        a different mesh than the one the checkpoint was saved under.

        With ``mesh``, fresh leaves whose sharding does not span the whole
        mesh (e.g. jit-created scalars committed to one device) are
        normalized to mesh-replicated, so the resumed state is consistent
        for a donating jitted train step. Returns None if nothing saved."""
        is_sh = lambda x: isinstance(x, jax.sharding.Sharding)
        tmpl = jax.tree_util.tree_map(lambda x: x.sharding, fresh_state)
        if mesh is not None:
            rep = NamedSharding(mesh, P())
            n = mesh.size

            def norm(s):
                try:
                    return s if len(s.device_set) == n else rep
                except Exception:
                    return rep
            tmpl = jax.tree_util.tree_map(norm, tmpl, is_leaf=is_sh)
        return self.restore(template=tmpl)

    def save(self, state, epoch: int):
        tmp = os.path.join(self.dir, f".tmp_epoch_{epoch}")
        final = os.path.join(self.dir, f"epoch_{epoch}")
        save_state(state, tmp)  # barriers internally on multi-host
        try:
            if jax.process_index() == 0:
                import shutil
                if os.path.exists(final):
                    shutil.rmtree(final)
                os.rename(tmp, final)
                for e in self._epochs_on_disk()[:-self.keep]:
                    shutil.rmtree(os.path.join(self.dir, f"epoch_{e}"))
        finally:
            # reach the barrier even if the proc0 commit failed (peers must
            # not hang); the exception still propagates on proc0
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices(f"ckpt_commit:{final}")

    def epochs(self, start: int, end: int):
        return range(start, end)
