"""Distributed (sharded) checkpointing with resharding-on-load.

Reference analog (SURVEY §5.4): sharding optimizers' rank-local
state_dicts + auto_parallel dist_saver.py / converter.py (per-rank
programs+params with dist attrs, resharded on load), and the op-version
registry (framework/op_version_registry.h:397) → the format_version field.

Format v2 (one directory per checkpoint):
    meta.json             format_version, per-array {shape, dtype, shards},
                          merged per-shard sha256 checksums
    skeleton.pkl          pytree structure with ARRAY_n placeholders
    data/ARRAY_n.s{k}.npy one file per saved shard (its global index range
                          recorded in meta) — only ONE copy of each distinct
                          shard is written (replicated arrays write once)
    checksums.{p}.json    per-process {shard file: sha256} sidecars (each
                          process can only hash the bytes it wrote; proc 0
                          merges them into meta.json after the save barrier)
    COMMIT                commit marker written LAST by proc 0: a truncated
                          or interrupted save can never masquerade as
                          complete. Contains the sha256 of the final
                          meta.json, so meta tampering/corruption is also
                          detected.

v1 checkpoints (no checksums, no COMMIT) remain readable; verification of
a v1 directory degrades to shard-existence checks.

Resharding on load: the loader assembles each *needed* slice from whichever
saved shard files overlap it via jax.make_array_from_callback, so a
checkpoint written on mesh A (e.g. fsdp=8) restores onto mesh B (e.g.
dp=2×fsdp=4, or a single chip) reading each byte once. The reference can
only restart on the same topology unless the auto-parallel converter
rewrites states (SURVEY §7.3 hard-part 5); here resharding is native.
"""

import dataclasses
import glob
import hashlib
import json
import os
import pickle
import re
import sys
from typing import Any, Callable, Dict, Optional, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["save_state", "load_state", "load_resharded",
           "name_leaves", "verify_checkpoint", "AutoCheckpoint"]

FORMAT_VERSION = 2
_MIN_READABLE_VERSION = 1
_COMMIT_FILE = "COMMIT"


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


class _Py:
    """Skeleton marker for non-array leaves (opaque to tree flattening —
    a bare tuple marker would be descended into as a pytree)."""

    def __init__(self, v):
        self.v = v


def _flatten(state):
    leaves, treedef = jax.tree_util.tree_flatten(state)
    return leaves, treedef


def _range_key(index, shape):
    return tuple((s.start or 0, s.stop if s.stop is not None else dim)
                 for s, dim in zip(index, shape))


def _range_tag(key) -> str:
    """Deterministic shard filename fragment from its global index range —
    identical on every process, so multi-host writers never collide on a
    name for *different* data and agree on the name for the same shard."""
    return "x".join(f"{a}-{b}" for a, b in key)


def _global_shard_layout(arr: jax.Array):
    """All distinct shard ranges of the GLOBAL array (not just addressable
    ones), computable identically on every process from the sharding."""
    try:
        idx_map = arr.sharding.devices_indices_map(arr.shape)
        return sorted({_range_key(ix, arr.shape)
                       for ix in idx_map.values()})
    except Exception:
        # addressable-only fallback is complete ONLY when this process sees
        # every device; on multi-host it would write a meta.json missing
        # other hosts' ranges → an unrestorable checkpoint. Fail loudly.
        if jax.process_count() > 1:
            raise
        return sorted({_range_key(sh.index, arr.shape)
                       for sh in arr.addressable_shards})


def _owned_shards(arr: jax.Array):
    """Addressable shards this process must write: exactly the replica-0
    copy of each range (each distinct range has one replica-0 holder
    globally, so across processes every range is written exactly once)."""
    out = {}
    for sh in arr.addressable_shards:
        if sh.replica_id != 0:
            continue
        key = _range_key(sh.index, arr.shape)
        if key not in out:
            out[key] = np.asarray(sh.data)
    return out


def save_state(state, path: str):
    """Save any pytree of jax/numpy arrays (+ json-able scalars).

    Multi-host safe (ADVICE r1): every distinct shard range is written
    exactly once globally — by the process holding its replica-0 copy —
    under a range-derived filename identical on all processes; meta.json
    and skeleton.pkl (whose content is process-independent) are written by
    process 0 only, and a cross-host barrier closes the save so the
    checkpoint is complete when any process returns.

    Format v2 integrity: each process records a sha256 per shard it wrote
    (checksums.{p}.json); after the barrier proves every write landed,
    process 0 merges the sidecars into meta.json and writes the COMMIT
    marker — so `verify_checkpoint` can reject truncated, bit-flipped, or
    never-committed directories and `AutoCheckpoint.restore` can fall back
    to the newest checkpoint that still verifies."""
    # every process must reach BOTH barriers even if its local write (or
    # proc0's commit) failed — a process that raised between them would
    # leave every peer blocked forever; the exception is re-raised after
    # the last barrier (and the launcher tears the job down). A peer
    # failure that proc0 cannot see here leaves a COMMIT over missing
    # shards/sidecars — verify_checkpoint rejects that directory.
    import time as _time
    from paddle_tpu import stats
    from paddle_tpu.observability import trace

    t_start = _time.perf_counter()
    exc = None
    with trace.span("ckpt/save", path=os.path.basename(path)):
        try:
            with trace.span("ckpt/save/write_shards"), \
                    stats.timer("ckpt/save_write"):
                _save_state_local(state, path)
        except BaseException as e:
            exc = e
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            with trace.span("ckpt/save/barrier"):
                multihost_utils.sync_global_devices(f"ckpt_save:{path}")
        if exc is None and jax.process_index() == 0:
            try:
                with trace.span("ckpt/save/commit"), \
                        stats.timer("ckpt/save_commit"):
                    _commit(path)
            except BaseException as e:
                exc = e
        if jax.process_count() > 1:
            # peers must not return before COMMIT exists, or a crash in
            # this window would leave them believing the save completed
            from jax.experimental import multihost_utils
            with trace.span("ckpt/save/commit_barrier"):
                multihost_utils.sync_global_devices(
                    f"ckpt_commit_mark:{path}")
    stats.observe("ckpt/save_s", _time.perf_counter() - t_start)
    if exc is not None:
        raise exc


def _commit(path: str):
    """Merge per-process checksum sidecars into meta.json, then write the
    COMMIT marker (containing meta's own sha256) — strictly last."""
    mp = os.path.join(path, "meta.json")
    with open(mp) as f:
        meta = json.load(f)
    checksums = {}
    for side in sorted(glob.glob(os.path.join(path, "checksums.*.json"))):
        with open(side) as f:
            checksums.update(json.load(f))
    meta["checksums"] = checksums
    tmp = mp + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1)
    os.replace(tmp, mp)
    commit = {"format_version": meta.get("format_version", FORMAT_VERSION),
              "meta_sha256": _sha256_file(mp)}
    ctmp = os.path.join(path, _COMMIT_FILE + ".tmp")
    with open(ctmp, "w") as f:
        json.dump(commit, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(ctmp, os.path.join(path, _COMMIT_FILE))


def _save_state_local(state, path: str):
    from paddle_tpu.testing import faults

    os.makedirs(os.path.join(path, "data"), exist_ok=True)
    proc0 = jax.process_index() == 0
    leaves, treedef = _flatten(state)
    meta = {"format_version": FORMAT_VERSION, "arrays": {}}
    skeleton = []
    checksums = {}

    def _write_shard(fn, data):
        fp = os.path.join(path, "data", fn)
        np.save(fp, data, allow_pickle=False)
        checksums[fn] = _sha256_file(fp)
        # injection AFTER the hash is recorded: simulates post-write disk
        # corruption, which verification must catch
        faults.corrupt_file("ckpt.shard", fp)

    for i, leaf in enumerate(leaves):
        name = f"ARRAY_{i}"
        if isinstance(leaf, jax.Array) and not isinstance(leaf, jax.core.Tracer):
            layout = _global_shard_layout(leaf)
            entry = {"shape": list(leaf.shape), "dtype": str(leaf.dtype),
                     "shards": [{"file": f"{name}.{_range_tag(k)}.npy",
                                 "range": [list(r) for r in k]}
                                for k in layout]}
            for key, data in _owned_shards(leaf).items():
                _write_shard(f"{name}.{_range_tag(key)}.npy", data)
            meta["arrays"][name] = entry
            skeleton.append(name)
        elif isinstance(leaf, np.ndarray):
            fn = f"{name}.s0.npy"
            if proc0:
                _write_shard(fn, leaf)
            meta["arrays"][name] = {
                "shape": list(leaf.shape), "dtype": str(leaf.dtype),
                "shards": [{"file": fn,
                            "range": [[0, d] for d in leaf.shape]}]}
            skeleton.append(name)
        else:
            skeleton.append(_Py(leaf))
    with open(os.path.join(
            path, f"checksums.{jax.process_index()}.json"), "w") as f:
        json.dump(checksums, f, indent=1)
    if proc0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
        with open(os.path.join(path, "skeleton.pkl"), "wb") as f:
            pickle.dump(jax.tree_util.tree_unflatten(treedef, skeleton), f)


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _read_slice(path, entry, index, shape, dtype):
    """Assemble the requested global slice from overlapping saved shards.

    Verifies the saved shards fully cover the requested slice (ADVICE r1:
    a missing/partial shard file must raise, never restore np.empty
    garbage)."""
    starts = [s.start or 0 for s in index]
    stops = [s.stop if s.stop is not None else dim
             for s, dim in zip(index, shape)]
    out = np.empty([b - a for a, b in zip(starts, stops)], dtype)
    boxes = []  # intersection boxes copied into out (coverage accounting)
    for sh in entry["shards"]:
        r = sh["range"]
        inter = [(max(a, ra), min(b, rb))
                 for (a, b), (ra, rb) in zip(zip(starts, stops), r)]
        if any(a >= b for a, b in inter):
            continue
        f = os.path.join(path, "data", sh["file"])
        if not os.path.exists(f):
            raise ValueError(
                f"checkpoint shard missing: {sh['file']} (range {r}) — "
                f"incomplete save?")
        data = np.load(f, mmap_mode="r")
        if data.dtype.kind == "V":  # ml_dtypes (bf16/fp8) round-trip raw
            data = data.view(dtype)
        src = tuple(slice(a - ra, b - ra)
                    for (a, b), (ra, rb) in zip(inter, r))
        dst = tuple(slice(a - s, b - s)
                    for (a, b), s in zip(inter, starts))
        out[dst] = data[src]
        if tuple(inter) not in boxes:
            boxes.append(tuple(inter))
    if not _boxes_cover(boxes, list(zip(starts, stops))):
        raise ValueError(
            f"saved shards do not cover requested slice "
            f"{list(zip(starts, stops))} of array shape {list(shape)} — "
            f"checkpoint incomplete")
    return out


def _boxes_cover(boxes, target) -> bool:
    """Exact axis-aligned-box coverage check without per-element masks
    (ADVICE r1 follow-up: a bool mask of a 1B-element slice costs 1GB).
    GSPMD shard grids give pairwise-disjoint boxes, so a volume sum decides
    coverage; on (pathological) partial overlap, fall back to coordinate
    compression over the distinct boundaries (#shards^ndim cells, tiny)."""
    if not target or all(a >= b for a, b in target):
        return True  # zero-size slice
    total = 1
    for a, b in target:
        total *= max(0, b - a)
    if total == 0:
        return True
    vol = 0
    for bx in boxes:
        v = 1
        for a, b in bx:
            v *= b - a
        vol += v
    if vol < total:  # even counting overlaps twice there isn't enough
        return False
    if len(boxes) > 512:
        # save_state only writes disjoint GSPMD grids; skip the O(S^2)
        # overlap scan on pod-scale layouts where it would dominate load
        return True
    overlap = False
    for i, bx in enumerate(boxes):
        for by in boxes[i + 1:]:
            if all(max(a1, a2) < min(b1, b2)
                   for (a1, b1), (a2, b2) in zip(bx, by)):
                overlap = True
                break
        if overlap:
            break
    if not overlap:
        return True
    # coordinate compression: every cell between consecutive boundaries is
    # uniform w.r.t. every box, so checking one representative per cell is
    # exact
    import itertools
    coords = []
    for d, (a, b) in enumerate(target):
        cs = {a, b}
        for bx in boxes:
            cs.add(min(max(bx[d][0], a), b))
            cs.add(min(max(bx[d][1], a), b))
        coords.append(sorted(cs))
    for cell in itertools.product(*(zip(c[:-1], c[1:]) for c in coords)):
        if any(lo >= hi for lo, hi in cell):
            continue
        if not any(all(bx[d][0] <= lo and hi <= bx[d][1]
                       for d, (lo, hi) in enumerate(cell))
                   for bx in boxes):
            return False
    return True


def verify_checkpoint(path: str):
    """Integrity-check a checkpoint directory WITHOUT loading it.

    Returns ``(ok, reason)``. For format v2: the COMMIT marker must
    exist, meta.json must hash to the committed sha256, every shard in
    meta must exist, and every shard with a recorded checksum must hash
    to it (catching truncation and bit-flips). v1 directories (no
    COMMIT/checksums) degrade to existence checks — they were written
    before commit markers existed and must stay restorable.
    """
    import time as _time
    from paddle_tpu import stats
    from paddle_tpu.observability import trace
    with trace.span("ckpt/verify", path=os.path.basename(path)):
        t0 = _time.perf_counter()
        try:
            return _verify_checkpoint_impl(path)
        finally:
            stats.observe("ckpt/verify_s", _time.perf_counter() - t0)


def _verify_checkpoint_impl(path: str):
    mp = os.path.join(path, "meta.json")
    if not os.path.exists(mp):
        return False, "meta.json missing"
    try:
        with open(mp) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"meta.json unreadable: {e}"
    ver = meta.get("format_version", 0)
    if not (_MIN_READABLE_VERSION <= ver <= FORMAT_VERSION):
        return False, f"format_version {ver} unsupported"
    if not os.path.exists(os.path.join(path, "skeleton.pkl")):
        return False, "skeleton.pkl missing"
    if ver >= 2:
        cp = os.path.join(path, _COMMIT_FILE)
        if not os.path.exists(cp):
            return False, "COMMIT marker missing (save never completed)"
        try:
            with open(cp) as f:
                commit = json.load(f)
        except (OSError, ValueError) as e:
            return False, f"COMMIT unreadable: {e}"
        want = commit.get("meta_sha256")
        if want and _sha256_file(mp) != want:
            return False, "meta.json does not match committed sha256"
    checksums = meta.get("checksums", {})
    for name, entry in meta.get("arrays", {}).items():
        for sh in entry["shards"]:
            fp = os.path.join(path, "data", sh["file"])
            if not os.path.exists(fp):
                return False, f"shard {sh['file']} missing"
            want = checksums.get(sh["file"])
            if want is None:
                if ver >= 2:
                    return False, f"shard {sh['file']} has no checksum"
                continue
            if _sha256_file(fp) != want:
                return False, (f"shard {sh['file']} checksum mismatch "
                               f"(truncated or corrupted)")
    return True, "ok"


def load_state(path: str,
               shardings: Optional[Union[Dict[str, Any],
                                         Callable[[str], Any]]] = None,
               template=None, verify: bool = False):
    """Load a checkpoint directory.

    verify=True: run `verify_checkpoint` first and raise ValueError with
    the failure reason instead of restoring from a damaged directory.

    shardings: None → jnp arrays on the default device;
    a pytree matching the saved structure (leaves NamedSharding / None), or
    a callable mapping the flattened leaf position name ("ARRAY_i") — use
    `template` instead for name-free placement: a pytree of shardings with
    the same structure as the saved state.
    """
    import time as _time
    from paddle_tpu import stats
    from paddle_tpu.observability import trace
    if verify:
        ok, reason = verify_checkpoint(path)
        if not ok:
            stats.add("ckpt/verify_failures")
            raise ValueError(
                f"checkpoint {path} failed verification: {reason}")
    t_restore = _time.perf_counter()
    with trace.span("ckpt/restore", path=os.path.basename(path)):
        out = _load_state_impl(path, shardings, template)
    stats.observe("ckpt/restore_s", _time.perf_counter() - t_restore)
    return out


def _load_state_impl(path, shardings, template):
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    ver = meta.get("format_version", 0)
    if not (_MIN_READABLE_VERSION <= ver <= FORMAT_VERSION):
        raise ValueError(
            f"checkpoint format_version {ver} unsupported "
            f"(readable: {_MIN_READABLE_VERSION}..{FORMAT_VERSION})")
    with open(os.path.join(path, "skeleton.pkl"), "rb") as f:
        skeleton = pickle.load(f)

    is_sh_leaf = lambda x: x is None or isinstance(x, NamedSharding)
    t_leaves = None
    if template is not None:
        t_leaves = jax.tree_util.tree_leaves(template, is_leaf=is_sh_leaf)
    s_leaves = None
    if shardings is not None and not callable(shardings):
        s_leaves = jax.tree_util.tree_leaves(shardings, is_leaf=is_sh_leaf)

    leaves, treedef = jax.tree_util.tree_flatten(skeleton)
    out = []
    for li, leaf in enumerate(leaves):
        if isinstance(leaf, _Py):
            out.append(leaf.v)
            continue
        name = leaf
        entry = meta["arrays"][name]
        shape = tuple(entry["shape"])
        np_dtype = _np_dtype(entry["dtype"])
        # indexed by overall leaf position: the shardings/template pytree
        # mirrors the SAVED structure, so its non-array positions (None
        # placeholders) keep array positions aligned
        sharding = None
        if callable(shardings):
            sharding = shardings(name)
        elif s_leaves is not None:
            sharding = s_leaves[li]
        elif t_leaves is not None:
            sharding = t_leaves[li]
        if sharding is None:
            arr = jnp.asarray(_read_slice(
                path, entry, tuple(slice(0, d) for d in shape), shape,
                np_dtype))
        else:
            def cb(index, entry=entry, shape=shape, np_dtype=np_dtype):
                return _read_slice(path, entry, index, shape, np_dtype)

            arr = jax.make_array_from_callback(shape, sharding, cb)
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


_UNDECIDED = object()  # last_verified_epoch not yet computed


@dataclasses.dataclass
class AutoCheckpoint:
    """Epoch-range auto checkpoint ≙ the reference's TrainEpochRange
    (fluid/incubate/checkpoint/auto_checkpoint.py:284): snapshot state each
    epoch under a job directory, transparently resume after preemption.

        ck = AutoCheckpoint("/ckpts", job_id="gpt-run-1", keep=2)
        state = ck.restore() or init_state()
        for epoch in ck.epochs(start=ck.next_epoch, end=100):
            state = train_one_epoch(state)
            ck.save(state, epoch)
    """
    root: str
    job_id: str
    keep: int = 2

    def __post_init__(self):
        self.dir = os.path.join(self.root, self.job_id)
        os.makedirs(self.dir, exist_ok=True)
        # memoized verify verdicts: a resume calls restore() AND
        # next_epoch, and hashing every shard of a multi-GB checkpoint
        # twice (plus double-counting failure stats) is pure waste
        self._verify_cache: Dict[int, bool] = {}
        self._decided_epoch = _UNDECIDED
        self._gc_orphaned_tmp()
        if jax.process_count() > 1:
            # construction barrier: no peer may start a save (writing
            # into a fresh .tmp_epoch_* dir) until proc 0's GC above has
            # finished sweeping — otherwise a fast peer's live tmp dir
            # could be rmtree'd as "orphaned"
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"ckpt_init:{self.dir}")

    def _gc_orphaned_tmp(self):
        """Startup GC: a worker killed between `save_state(tmp)` and the
        commit rename leaves a `.tmp_epoch_*` directory that will never
        be completed — delete it so retries of the same epoch start
        clean and dead bytes don't accumulate across preemptions."""
        if jax.process_index() != 0:
            return
        import shutil
        from paddle_tpu import stats
        for d in os.listdir(self.dir):
            if d.startswith(".tmp_epoch_"):
                shutil.rmtree(os.path.join(self.dir, d),
                              ignore_errors=True)
                stats.add("ckpt/tmp_gc")
                print(f"[ckpt] GC'd orphaned {d} (interrupted save)",
                      file=sys.stderr)

    def _epochs_on_disk(self):
        eps = []
        for d in os.listdir(self.dir):
            if d.startswith("epoch_") and os.path.exists(
                    os.path.join(self.dir, d, "meta.json")):
                eps.append(int(d.split("_")[1]))
        return sorted(eps)

    def _epoch_dir(self, epoch: int) -> str:
        return os.path.join(self.dir, f"epoch_{epoch}")

    def _verified(self, epoch: int) -> bool:
        if epoch in self._verify_cache:
            return self._verify_cache[epoch]
        from paddle_tpu import stats
        ok, reason = verify_checkpoint(self._epoch_dir(epoch))
        if not ok:
            stats.add("ckpt/verify_failures")
            self._verify_reason = reason
        self._verify_cache[epoch] = ok
        return ok

    def last_verified_epoch(self) -> Optional[int]:
        """Newest epoch whose directory passes `verify_checkpoint`, or
        None. Damaged newer epochs are reported (and counted, once) but
        skipped — the resume path must never trust an unverified
        directory just because it is newest.

        Multi-host: process 0 decides and broadcasts the epoch, so every
        rank restores the SAME one — per-rank verification over a shared
        FS with visibility skew could disagree (and would hash every
        shard once per host). The broadcast is a COLLECTIVE: the first
        call after construction (or after a save) must happen on every
        rank. The verdict is cached per instance, so later rank-local
        accesses (logging, conditionals) are plain lookups."""
        if self._decided_epoch is not _UNDECIDED:
            return self._decided_epoch
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            e = self._last_verified_local() if jax.process_index() == 0 \
                else None
            e = int(multihost_utils.broadcast_one_to_all(
                np.int32(-1 if e is None else e)))
            e = None if e < 0 else e
        else:
            e = self._last_verified_local()
        self._decided_epoch = e
        return e

    def _last_verified_local(self) -> Optional[int]:
        from paddle_tpu import stats
        for e in reversed(self._epochs_on_disk()):
            if self._verified(e):
                return e
            stats.add("ckpt/restore_fallbacks")
            print(f"[ckpt] epoch_{e} failed verification "
                  f"({getattr(self, '_verify_reason', 'unknown')}); "
                  f"falling back", file=sys.stderr)
        return None

    @property
    def next_epoch(self) -> int:
        """First epoch to (re)run: one past the newest VERIFIED epoch —
        a corrupt newest checkpoint is re-trained, not skipped with
        stale state."""
        e = self.last_verified_epoch()
        return 0 if e is None else e + 1

    def restore(self, shardings=None, template=None):
        """Newest VERIFIED epoch's state, or None if no epoch passes
        verification (truncated shard, checksum mismatch, missing
        COMMIT marker all disqualify — see `verify_checkpoint`)."""
        e = self.last_verified_epoch()
        if e is None:
            return None
        return load_state(self._epoch_dir(e),
                          shardings=shardings, template=template)

    def restore_like(self, fresh_state, mesh: Optional[Mesh] = None):
        """Resharding resume (the N→M elastic path, ≙ auto_parallel
        converter.py resharding-on-load): load the latest checkpoint ONTO
        the shardings of a freshly-initialized state — typically built on
        a different mesh than the one the checkpoint was saved under.

        With ``mesh``, fresh leaves whose sharding does not span the whole
        mesh (e.g. jit-created scalars committed to one device) are
        normalized to mesh-replicated, so the resumed state is consistent
        for a donating jitted train step. Returns None if nothing saved."""
        is_sh = lambda x: isinstance(x, jax.sharding.Sharding)
        tmpl = jax.tree_util.tree_map(lambda x: x.sharding, fresh_state)
        if mesh is not None:
            rep = NamedSharding(mesh, P())
            n = mesh.size

            def norm(s):
                try:
                    return s if len(s.device_set) == n else rep
                except Exception:
                    return rep
            tmpl = jax.tree_util.tree_map(norm, tmpl, is_leaf=is_sh)
        return self.restore(template=tmpl)

    def restore_resharded(self, fresh_state, mesh=None):
        """Elastic resume across topology AND layout changes: load the
        newest VERIFIED epoch onto ``fresh_state``'s exact pytree,
        shardings, and block layout — converting stacked↔per-layer
        block weights when the checkpoint was saved in the other layout
        (checkpoint.load_resharded). Each host reads only the saved bytes its
        own shards need; there is no gather to host 0. Returns None if
        no epoch verifies. Unlike `restore_like`, this survives a pytree
        STRUCTURE change between save and resume, not just a mesh
        change. ``mesh`` normalizes non-spanning template shardings to
        mesh-replicated, exactly like `restore_like`."""
        e = self.last_verified_epoch()
        if e is None:
            return None
        # last_verified_epoch already hashed this directory — don't
        # re-verify every shard a second time inside the load
        return load_resharded(self._epoch_dir(e), fresh_state,
                              verify=False, mesh=mesh)

    def save(self, state, epoch: int):
        from paddle_tpu.testing import faults

        tmp = os.path.join(self.dir, f".tmp_epoch_{epoch}")
        final = os.path.join(self.dir, f"epoch_{epoch}")
        save_state(state, tmp)  # barriers internally on multi-host
        # kill-injection window: dying here orphans the .tmp dir, which
        # the next startup's GC must collect (site: ckpt.tmp_saved)
        faults.fire("ckpt.tmp_saved")
        self._verify_cache.pop(epoch, None)  # dir contents replaced
        self._decided_epoch = _UNDECIDED     # epoch set changed
        try:
            if jax.process_index() == 0:
                import shutil
                if os.path.exists(final):
                    shutil.rmtree(final)
                os.rename(tmp, final)
                # the full save+commit protocol just completed — no need
                # to re-hash what we wrote when the prune quota (or a
                # later restore) asks
                self._verify_cache[epoch] = True
                # retention counts only VERIFIED epochs toward `keep`:
                # when the newest dirs are corrupt (the exact scenario
                # the fallback exists for), pruning by raw age would
                # delete the only restorable epochs while keeping rot
                kept = 0
                for e in reversed(self._epochs_on_disk()):
                    if kept < self.keep and self._verified(e):
                        kept += 1
                    elif kept >= self.keep:
                        self._verify_cache.pop(e, None)
                        shutil.rmtree(os.path.join(self.dir,
                                                   f"epoch_{e}"))
        finally:
            # reach the barrier even if the proc0 commit failed (peers must
            # not hang); the exception still propagates on proc0
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices(f"ckpt_commit:{final}")

    def epochs(self, start: int, end: int):
        return range(start, end)


# ---------------------------------------------------------------------------
# Layout-portable reshard pass (ISSUE 8)
# ---------------------------------------------------------------------------
# PAPERS "Memory-efficient array redistribution through portable collective
# communication" motivates the policy: moving a verified checkpoint between
# meshes must never stage the full state on one host. load_state already
# reshards *shardings* natively (each target shard assembled from the saved
# files overlapping it, per host). What it cannot do is change the state's
# *layout*: a train state saved with pre-stacked block weights
# (init_train_state(stacked=True), one '_stacked_blocks' pytree with a
# leading layer axis) has a different pytree STRUCTURE than the per-layer
# state ('blocks.item_i.*' keys), so a template-driven load fails
# structurally even though the bytes are all there. `load_resharded` closes
# that gap: it name-indexes both the saved skeleton and the target template,
# matches leaves through the stacked<->per-layer correspondence
#
#     <pfx>._stacked_<list>.<rest>  =  <pfx>.<list>.item_{l}.<rest>  (all l)
#
# (the convention models.gpt '_stacked_blocks'<->'blocks' and models.bert
# '_stacked_layers'<->'layers' follow), and reads each target shard's bytes
# straight out of the overlapping saved files — a stacked target leaf reads
# layer l's rows from layer l's saved per-layer file, a per-layer target
# reads its rows from the saved stack's layer-l slice. Optimizer slots
# convert the same way (their pytree mirrors the params').

def _is_module(o) -> bool:
    from paddle_tpu.nn.module import Module
    return isinstance(o, Module)


_STACKED_RE = re.compile(r"^(.*?)_stacked_([A-Za-z0-9]+)\.(.+)$")
_PER_LAYER_RE = re.compile(r"^(.*?)([A-Za-z0-9]+)\.item_(\d+)\.(.+)$")


def name_leaves(obj, prefix: str = "") -> Dict[str, Any]:
    """Flatten a state pytree into ``{dotted-name: leaf}``.

    Modules walk their pytree keys (sorted param/buffer/module names — the
    same order named_parameters uses), dicts their keys, sequences their
    indices; so a saved skeleton and a freshly initialized template of the
    same logical state produce the same names even though one holds
    'ARRAY_n' placeholders and the other live arrays."""
    out: Dict[str, Any] = {}

    def walk(o, pfx):
        if o is None:
            return  # jax treats None as an empty pytree, not a leaf
        if isinstance(o, dict):
            for k in sorted(o):
                walk(o[k], f"{pfx}.{k}" if pfx else str(k))
        elif _is_module(o):
            for k in o._tree_keys():
                walk(getattr(o, k), f"{pfx}.{k}" if pfx else k)
        elif isinstance(o, (list, tuple)):
            for i, v in enumerate(o):
                walk(v, f"{pfx}.{i}" if pfx else str(i))
        else:
            out[pfx] = o

    walk(obj, prefix)
    return out


def _canon_per_layer(name: str) -> Optional[Tuple[str, int]]:
    """'<pfx>.<list>.item_{l}.<rest>' → ('<pfx>._stacked_<list>.<rest>', l)
    — the stacked-side name this per-layer leaf corresponds to."""
    m = _PER_LAYER_RE.match(name)
    if not m:
        return None
    pfx, lst, l, rest = m.groups()
    return f"{pfx}_stacked_{lst}.{rest}", int(l)


def _per_layer_name(stacked_name: str, layer: int) -> Optional[str]:
    """Inverse of `_canon_per_layer` for one layer index."""
    m = _STACKED_RE.match(stacked_name)
    if not m:
        return None
    pfx, lst, rest = m.groups()
    return f"{pfx}{lst}.item_{layer}.{rest}"


def _sharding_of(leaf, mesh=None):
    s = getattr(leaf, "sharding", None)
    if not isinstance(s, jax.sharding.Sharding):
        return None
    if mesh is not None:
        # normalize leaves whose sharding does not span the whole target
        # mesh to mesh-replicated (same policy as restore_like):
        # jit-created states (optimizer.init) can commit scalars/vectors
        # to one device, and faithfully reproducing that mixed placement
        # makes the donating train step refuse the restored state
        try:
            if len(s.device_set) != mesh.size:
                return NamedSharding(mesh, P())
        except Exception:
            return NamedSharding(mesh, P())
    return s


def load_resharded(path: str, template, verify: bool = True,
                   mesh: Optional[Mesh] = None):
    """Load a checkpoint directory onto ``template``'s exact layout.

    ``template``: a pytree of arrays (or ShapeDtypeStructs carrying a
    ``sharding``) shaped like the TARGET state — typically the output of a
    fresh ``init_train_state(...)`` on the new mesh. Every array leaf is
    restored with the template leaf's sharding via
    ``jax.make_array_from_callback``: each process reads only the saved
    bytes overlapping its own addressable shards (sharded-read per host,
    never a host-0 gather), assembling across the stacked↔per-layer
    layout boundary when the saved state used the other block layout.
    Dtypes are cast to the template's when they differ (e.g. a changed
    optimizer moment dtype).

    ``verify=True`` runs `verify_checkpoint` first (v2 sha256 sidecars +
    COMMIT marker) and raises instead of restoring damaged bytes.

    ``mesh``: when given, template leaves whose sharding does not span
    the whole mesh (e.g. jit-created optimizer scalars committed to one
    device) restore mesh-replicated instead — the `restore_like`
    normalization, so the restored state is consistent for a donating
    jitted train step.
    """
    if verify:
        ok, reason = verify_checkpoint(path)
        if not ok:
            raise ValueError(
                f"checkpoint {path} failed verification: {reason}")
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    ver = meta.get("format_version", 0)
    if not (_MIN_READABLE_VERSION <= ver <= FORMAT_VERSION):
        raise ValueError(f"checkpoint format_version {ver} unsupported")
    with open(os.path.join(path, "skeleton.pkl"), "rb") as f:
        skeleton = pickle.load(f)

    saved = name_leaves(skeleton)
    # per-layer leaves of the SAVED state, grouped under their stacked
    # name: {'<pfx>._stacked_<list>.<rest>': {layer: saved name}}
    saved_layers: Dict[str, Dict[int, str]] = {}
    for n in saved:
        c = _canon_per_layer(n)
        if c is not None and isinstance(saved[n], str):
            saved_layers.setdefault(c[0], {})[c[1]] = n

    leaves, treedef = jax.tree_util.tree_flatten(template)
    names = list(name_leaves(template))
    if len(names) != len(leaves):
        raise ValueError(
            "template names/leaves mismatch: a Module in the template "
            "carries non-pytree leaves the walker saw")

    def read_direct(entry, index, out_dtype):
        shape = tuple(entry["shape"])
        data = _read_slice(path, entry, index,
                           shape, _np_dtype(entry["dtype"]))
        return data if data.dtype == out_dtype else data.astype(out_dtype)

    out = []
    for name, leaf in zip(names, leaves):
        if not hasattr(leaf, "shape"):
            # non-array target slot (python scalar in the skeleton): keep
            # the saved value when present, else the template's
            sv = saved.get(name)
            out.append(sv.v if isinstance(sv, _Py) else leaf)
            continue
        shape = tuple(leaf.shape)
        np_dtype = (leaf.dtype if isinstance(leaf.dtype, np.dtype)
                    else _np_dtype(str(leaf.dtype)))
        src = saved.get(name)
        if isinstance(src, str):
            entry = meta["arrays"][src]
            if tuple(entry["shape"]) != shape:
                raise ValueError(
                    f"{name}: saved shape {entry['shape']} != template "
                    f"shape {list(shape)}")

            def cb(index, entry=entry, dt=np_dtype):
                return read_direct(entry, index, dt)
        elif name in saved_layers:
            # target stacked, saved per-layer: leading dim indexes layers
            per = saved_layers[name]
            L = shape[0]
            missing = [l for l in range(L) if l not in per]
            if missing:
                raise ValueError(
                    f"{name}: saved per-layer state lacks layers "
                    f"{missing} (have {sorted(per)})")
            entries = {l: meta["arrays"][saved[per[l]]]
                       for l in range(L)}
            blk_shape = tuple(entries[0]["shape"])
            if blk_shape != shape[1:]:
                raise ValueError(
                    f"{name}: per-layer saved shape {list(blk_shape)} != "
                    f"stacked template trailing shape {list(shape[1:])}")

            def cb(index, entries=entries, L=L, dt=np_dtype):
                l0 = index[0].start or 0
                l1 = index[0].stop if index[0].stop is not None else L
                return np.stack([read_direct(entries[l], index[1:], dt)
                                 for l in range(l0, l1)])
        else:
            # target per-layer, saved stacked: read one layer's slice
            c = _canon_per_layer(name)
            src_stacked = saved.get(c[0]) if c else None
            if not isinstance(src_stacked, str):
                raise ValueError(
                    f"checkpoint {path} has no source for template leaf "
                    f"{name!r} (neither direct, per-layer, nor stacked)")
            layer = c[1]
            entry = meta["arrays"][src_stacked]
            if tuple(entry["shape"])[1:] != shape:
                raise ValueError(
                    f"{name}: stacked saved shape {entry['shape']} does "
                    f"not slice to template shape {list(shape)}")

            def cb(index, entry=entry, layer=layer, dt=np_dtype):
                return read_direct(
                    entry, (slice(layer, layer + 1),) + tuple(index),
                    dt)[0]

        sharding = _sharding_of(leaf, mesh)
        if sharding is None:
            arr = jnp.asarray(cb(tuple(slice(0, d) for d in shape)))
        else:
            arr = jax.make_array_from_callback(shape, sharding, cb)
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)
