"""nn.utils (ref: python/paddle/nn/utils/ — weight_norm, spectral_norm,
clip helpers, parameters_to_vector)."""

import jax
import jax.numpy as jnp

__all__ = ["parameters_to_vector", "vector_to_parameters", "clip_grad_norm_",
           "clip_grad_value_"]


def parameters_to_vector(parameters):
    return jnp.concatenate([jnp.ravel(p) for p in parameters])


def vector_to_parameters(vec, parameters):
    out = []
    offset = 0
    for p in parameters:
        n = p.size
        out.append(vec[offset:offset + n].reshape(p.shape))
        offset += n
    return out


def clip_grad_norm_(grads, max_norm, norm_type=2.0):
    """Functional grad clipping over a pytree; returns (clipped, total_norm)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in leaves])) ** (
                1.0 / norm_type)
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * scale, grads), total


def clip_grad_value_(grads, clip_value):
    return jax.tree_util.tree_map(
        lambda g: jnp.clip(g, -clip_value, clip_value), grads)
