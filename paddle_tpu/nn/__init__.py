"""paddle_tpu.nn — layers (ref: python/paddle/nn/, ~31k LoC layer zoo).

The Module base is a JAX pytree (see module.py) so models pass directly
through jit/grad/vmap/pjit; layers mirror the reference's class surface.
"""

from paddle_tpu.nn.module import (Buffer, Context, LayerDict, LayerList,
                                  Module, Parameter, ParameterList,
                                  Sequential, current_context, is_training,
                                  stateful)

Layer = Module  # reference name (paddle.nn.Layer)

from paddle_tpu.nn import functional  # noqa: E402
from paddle_tpu.nn import initializer  # noqa: E402
from paddle_tpu.nn import utils  # noqa: E402

from paddle_tpu.nn.layer.common import *  # noqa: F401,F403,E402
from paddle_tpu.nn.layer.conv import *  # noqa: F401,F403,E402
from paddle_tpu.nn.layer.norm import *  # noqa: F401,F403,E402
from paddle_tpu.nn.layer.activation import *  # noqa: F401,F403,E402
from paddle_tpu.nn.layer.pooling import *  # noqa: F401,F403,E402
from paddle_tpu.nn.layer.loss import *  # noqa: F401,F403,E402
from paddle_tpu.nn.layer.transformer import *  # noqa: F401,F403,E402
from paddle_tpu.nn.layer.rnn import *  # noqa: F401,F403,E402
from paddle_tpu.nn.decode import (BeamSearchDecoder,  # noqa: E402
                                  dynamic_decode)
# grad-clip classes live with the optimizers; the reference also exports
# them from paddle.nn
from paddle_tpu.optimizer.clip import (ClipGradByGlobalNorm,  # noqa: E402
                                       ClipGradByNorm, ClipGradByValue)
