"""Weight initializers (ref: python/paddle/nn/initializer/ — Constant,
Normal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform, TruncatedNormal,
Assign, Orthogonal, Dirac)."""

import math

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu import random as pt_random

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "Orthogonal", "calculate_gain", "set_global_initializer", "Bilinear", "Dirac"]


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: (out_c, in_c, *k) reference layout or (..., in, out)
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4.0}
    return gains[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype=jnp.float32, key=None):
        raise NotImplementedError

    def _key(self, key):
        return key if key is not None else pt_random.next_key()


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32, key=None):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32, key=None):
        return self.mean + self.std * jax.random.normal(
            self._key(key), shape, jnp.float32).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32, key=None):
        r = jax.random.truncated_normal(self._key(key), -2.0, 2.0, shape,
                                        jnp.float32)
        return (self.mean + self.std * r).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32, key=None):
        return jax.random.uniform(self._key(key), shape, jnp.float32,
                                  self.low, self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32, key=None):
        fan_in, fan_out = _fans(shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(self._key(key), shape,
                                       jnp.float32).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32, key=None):
        fan_in, fan_out = _fans(shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(self._key(key), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu"):
        self.fan_in = fan_in
        self.gain = calculate_gain(nonlinearity, negative_slope)

    def __call__(self, shape, dtype=jnp.float32, key=None):
        fan_in, _ = _fans(shape)
        fan_in = self.fan_in or fan_in
        std = self.gain / math.sqrt(fan_in)
        return std * jax.random.normal(self._key(key), shape,
                                       jnp.float32).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu"):
        self.fan_in = fan_in
        self.gain = calculate_gain(nonlinearity, negative_slope)

    def __call__(self, shape, dtype=jnp.float32, key=None):
        fan_in, _ = _fans(shape)
        fan_in = self.fan_in or fan_in
        limit = self.gain * math.sqrt(3.0 / fan_in)
        return jax.random.uniform(self._key(key), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32, key=None):
        arr = jnp.asarray(self.value, dtype)
        assert tuple(arr.shape) == tuple(shape), \
            f"Assign shape {arr.shape} != {shape}"
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32, key=None):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(self._key(key), (rows, cols), jnp.float32)
        q, r = jnp.linalg.qr(flat.T if rows < cols else flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init


def default_weight_init():
    return _global_weight_init or XavierUniform()


def default_bias_init():
    return _global_bias_init or Constant(0.0)


class Bilinear(Initializer):
    """ref: nn/initializer/Bilinear — upsampling-kernel init for
    (Cout, Cin, kh, kw) transposed-conv weights."""

    def __call__(self, shape, dtype=jnp.float32, key=None):
        import numpy as np
        assert len(shape) == 4, "Bilinear expects a 4-D conv weight"
        _, _, kh, kw = shape
        fh, fw = (kh + 1) // 2, (kw + 1) // 2
        cy = (kh - 1) / 2.0
        cx = (kw - 1) / 2.0
        og = np.ogrid[:kh, :kw]
        filt = ((1 - abs(og[0] - cy) / fh) * (1 - abs(og[1] - cx) / fw))
        # reference BilinearInitializer tiles the filter into EVERY
        # (out, in) channel pair, not just the diagonal
        w = np.broadcast_to(filt, shape).astype(np.float32)
        return jnp.asarray(w, dtype)


class Dirac(Initializer):
    """ref: nn/initializer/dirac.py:28 — identity-preserving conv init:
    channel i passes through at the kernel center."""

    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32, key=None):
        import numpy as np
        assert len(shape) >= 3, "Dirac expects a conv weight (3-D+)"
        w = np.zeros(shape, np.float32)
        out_per_g = shape[0] // self.groups
        center = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(min(out_per_g, shape[1])):
                w[(g * out_per_g + i, i) + center] = 1.0
        return jnp.asarray(w, dtype)
