"""Convolutions (ref: python/paddle/nn/functional/conv.py → phi conv kernels
/ cuDNN). On TPU these lower to XLA ``conv_general_dilated`` which tiles onto
the MXU; NCHW in the API for reference parity, transposed internally when it
helps XLA (XLA handles layout assignment itself)."""

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose", "unfold", "fold"]


def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _norm_padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format):
    # conv is on the reference O1 white list (amp/auto_cast WHITE_LIST:44)
    from paddle_tpu.amp.auto_cast import amp_cast
    x = amp_cast(jnp.asarray(x))
    if hasattr(weight, "dequantize"):
        # int8 QuantTensor kernel: XLA fuses the dequant convert into the
        # conv read (no Pallas conv kernel — convs are MXU-bound, not
        # weight-bandwidth-bound like decode matmuls)
        weight = weight.dequantize()
    w = amp_cast(jnp.asarray(weight))  # (out_c, in_c/groups, *k) ref layout
    if x.dtype != w.dtype:  # lax.conv requires matching dtypes
        ct = jnp.promote_types(x.dtype, w.dtype)
        x, w = x.astype(ct), w.astype(ct)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW"[:n + 2] if n == 2 else
         ("NCH" if n == 1 else "NCDHW"),
         "OIHW"[:n + 2] if n == 2 else ("OIH" if n == 1 else "OIDHW"),
         "NCHW"[:n + 2] if n == 2 else ("NCH" if n == 1 else "NCDHW")))
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=_norm_tuple(stride, n),
        padding=_norm_padding(padding, n),
        rhs_dilation=_norm_tuple(dilation, n),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.float32 else None)
    out = out.astype(x.dtype)
    if bias is not None:
        b = jnp.asarray(bias).reshape((1, -1) + (1,) * n)
        out = out + b
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, n, data_format):
    x = jnp.asarray(x)
    w = jnp.asarray(weight)  # reference layout: (in_c, out_c/groups, *k)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    strides = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    pad = _norm_padding(padding, n)
    if isinstance(pad, str):
        pad = [(0, 0)] * n if pad == "VALID" else None
        assert pad is not None, "SAME padding unsupported for transpose conv"
    out_pad = _norm_tuple(output_padding, n)
    k = w.shape[2:]
    # grad-of-conv formulation: lhs_dilation = stride
    pads = []
    for i in range(n):
        eff_k = (k[i] - 1) * dilation[i] + 1
        lo = eff_k - 1 - pad[i][0]
        hi = eff_k - 1 - pad[i][1] + out_pad[i]
        pads.append((lo, hi))
    if groups > 1:
        ws = jnp.split(w, groups, axis=0)
        w = jnp.concatenate([jnp.swapaxes(t, 0, 1) for t in ws], axis=0)
    else:
        w = jnp.swapaxes(w, 0, 1)  # → (out_c, in_c, *k)
    w = jnp.flip(w, axis=tuple(range(2, 2 + n)))
    dn_str = ("NCH", "OIH", "NCH") if n == 1 else (
        ("NCHW", "OIHW", "NCHW") if n == 2 else ("NCDHW", "OIDHW", "NCDHW"))
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, dn_str)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1,) * n, padding=pads,
        lhs_dilation=strides, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)
    out = out.astype(x.dtype)
    if bias is not None:
        out = out + jnp.asarray(bias).reshape((1, -1) + (1,) * n)
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCL"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (ref: paddle.nn.functional.unfold)."""
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    d = _norm_tuple(dilations, 2)
    p = _norm_padding(paddings, 2)
    x = jnp.pad(x, [(0, 0), (0, 0), p[0], p[1]])
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s, padding=[(0, 0), (0, 0)],
        rhs_dilation=d, dimension_numbers=jax.lax.conv_dimension_numbers(
            x.shape, (1, c) + k, ("NCHW", "OIHW", "NCHW")))
    nn, cc, oh, ow = patches.shape
    return patches.reshape(nn, cc, oh * ow)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im — inverse of unfold via scatter-add."""
    x = jnp.asarray(x)
    n, ckk, l = x.shape
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    d = _norm_tuple(dilations, 2)
    p = _norm_padding(paddings, 2)
    oh, ow = output_sizes
    c = ckk // (k[0] * k[1])
    ph = oh + p[0][0] + p[0][1]
    pw = ow + p[1][0] + p[1][1]
    nh = (ph - (k[0] - 1) * d[0] - 1) // s[0] + 1
    nw = (pw - (k[1] - 1) * d[1] - 1) // s[1] + 1
    cols = x.reshape(n, c, k[0], k[1], nh, nw)
    out = jnp.zeros((n, c, ph, pw), x.dtype)
    for i in range(k[0]):
        for j in range(k[1]):
            hi = i * d[0]
            wj = j * d[1]
            out = out.at[:, :, hi:hi + nh * s[0]:s[0],
                         wj:wj + nw * s[1]:s[1]].add(cols[:, :, i, j])
    return out[:, :, p[0][0]:ph - p[0][1], p[1][0]:pw - p[1][1]]
