from paddle_tpu.nn.functional.activation import *  # noqa: F401,F403
from paddle_tpu.nn.functional.conv import *        # noqa: F401,F403
from paddle_tpu.nn.functional.pooling import *     # noqa: F401,F403
from paddle_tpu.nn.functional.norm import *        # noqa: F401,F403
from paddle_tpu.nn.functional.loss import *        # noqa: F401,F403
from paddle_tpu.nn.functional.common import *      # noqa: F401,F403
from paddle_tpu.nn.functional.attention import *   # noqa: F401,F403
from paddle_tpu.nn.functional.extension import *   # noqa: F401,F403


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None):
    """ref: nn/functional/sparse_attention.py — block-sparse attention
    with the pattern given as CSR (offset, columns); delegates to the
    sparse-tensor attention kernel (sparse/nn.py)."""
    import jax.numpy as jnp

    import paddle_tpu.sparse as S
    from paddle_tpu.sparse.nn import functional as sparse_F

    import numpy as _np

    # batch/head-shared 2-D pattern (the kernel broadcasts over B, H);
    # refuse to silently collapse genuinely per-head patterns. The
    # pattern is static data, so the check runs host-side — under jit a
    # traced >1-D pattern cannot be verified and is rejected outright.
    def _collapse(arr_name, arr):
        if getattr(arr, "ndim", None) is not None and arr.ndim <= 1:
            return jnp.asarray(arr)  # 1-D (incl. traced) passes through
        try:
            host = _np.asarray(arr)  # lists/np/eager-jax concretize here
        except Exception:
            raise NotImplementedError(
                f"sparse_attention: traced multi-dim CSR {arr_name} under "
                "jit; pass a shared 1-D pattern instead") from None
        if host.ndim <= 1:
            return jnp.asarray(host)
        first = host.reshape(-1, host.shape[-1])[0]
        if not (host == first).all():
            raise NotImplementedError(
                f"sparse_attention: per-batch/per-head CSR {arr_name} "
                "patterns differ; only a shared pattern is supported")
        return jnp.asarray(first)

    offs = _collapse("offset", sparse_csr_offset)
    cols = _collapse("columns", sparse_csr_columns)
    s = query.shape[-2]
    mask = S.sparse_csr_tensor(offs, cols,
                               jnp.ones(cols.shape, jnp.float32), (s, s))
    return sparse_F.attention(query, key, value, mask,
                              key_padding_mask=key_padding_mask,
                              attn_mask=attn_mask)


# inplace-suffix aliases (eager aliases of the pure ops, ≙ the
# reference's *_ functional variants)
elu_ = elu        # noqa: F405
relu_ = relu      # noqa: F405
softmax_ = softmax  # noqa: F405
