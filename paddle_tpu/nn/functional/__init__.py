from paddle_tpu.nn.functional.activation import *  # noqa: F401,F403
from paddle_tpu.nn.functional.conv import *        # noqa: F401,F403
from paddle_tpu.nn.functional.pooling import *     # noqa: F401,F403
from paddle_tpu.nn.functional.norm import *        # noqa: F401,F403
from paddle_tpu.nn.functional.loss import *        # noqa: F401,F403
from paddle_tpu.nn.functional.common import *      # noqa: F401,F403
from paddle_tpu.nn.functional.attention import *   # noqa: F401,F403
from paddle_tpu.nn.functional.extension import *   # noqa: F401,F403


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None):
    """ref: nn/functional/sparse_attention.py — block-sparse attention
    with the pattern given as CSR (offset, columns); delegates to the
    sparse-tensor attention kernel (sparse/nn.py)."""
    import jax.numpy as jnp

    import paddle_tpu.sparse as S
    from paddle_tpu.sparse.nn import functional as sparse_F

    offs = jnp.asarray(sparse_csr_offset)
    cols = jnp.asarray(sparse_csr_columns)
    # batch/head-shared 2-D pattern (the kernel broadcasts over B, H);
    # refuse to silently collapse genuinely per-head patterns
    for arr_name, arr in (("offset", offs), ("columns", cols)):
        while arr.ndim > 1:
            first = arr[0]
            if not bool(jnp.all(arr == first[None])):
                raise NotImplementedError(
                    f"sparse_attention: per-batch/per-head CSR {arr_name} "
                    "patterns differ; only a shared pattern is supported")
            arr = first
        if arr_name == "offset":
            offs = arr
        else:
            cols = arr
    s = query.shape[-2]
    mask = S.sparse_csr_tensor(offs, cols,
                               jnp.ones(cols.shape, jnp.float32), (s, s))
    return sparse_F.attention(query, key, value, mask,
                              key_padding_mask=key_padding_mask,
                              attn_mask=attn_mask)


# inplace-suffix aliases (eager aliases of the pure ops, ≙ the
# reference's *_ functional variants)
elu_ = elu        # noqa: F405
relu_ = relu      # noqa: F405
softmax_ = softmax  # noqa: F405
