"""Extension functionals (ref: python/paddle/nn/functional/extension.py —
sequence_mask/gather_tree/temporal_shift/diag_embed — and vision.py —
affine_grid/grid_sample)."""

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["sequence_mask", "gather_tree", "temporal_shift", "diag_embed",
           "affine_grid", "grid_sample"]


def sequence_mask(x, maxlen=None, dtype="int64"):
    """ref: extension.py:162 — y[..., j] = (j < x[...]). The dtype maps
    through the framework dtype table (int64 → int32 under JAX's default
    32-bit mode, silently, like every other int64-taking op here)."""
    from paddle_tpu.dtypes import to_dtype
    x = jnp.asarray(x)
    if maxlen is None:
        maxlen = int(jnp.max(x))  # host read, like the reference's max(x)
    mask = jnp.arange(maxlen) < x[..., None]
    return mask.astype(to_dtype(dtype))


def gather_tree(ids, parents):
    """ref: extension.py:253 — beam-search backtrace over
    (max_time, batch, beam) id/parent arrays, as a reverse lax.scan."""
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    T = ids.shape[0]
    beam_iota = jnp.arange(ids.shape[2])[None, :]

    def step(beam_idx, t):
        # beam_idx: (batch, beam) — which beam each FINAL sequence rides
        # at time t+1; collect ids[t] at that beam, then hop to parents
        out_t = jnp.take_along_axis(ids[t], beam_idx, axis=1)
        prev = jnp.take_along_axis(parents[t], beam_idx, axis=1)
        return prev, out_t

    last = jnp.broadcast_to(beam_iota, ids.shape[1:])
    _, outs = lax.scan(step, last, jnp.arange(T - 1, -1, -1))
    return outs[::-1]


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """ref: extension.py:346 — TSM channel shift across the segment axis:
    the first ``shift_ratio`` of channels shift t-1→t, the next block
    shifts t+1→t, the rest stay."""
    x = jnp.asarray(x)
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    fwd = jnp.concatenate(
        [jnp.zeros_like(x5[:, :1, :c1]), x5[:, :-1, :c1]], axis=1)
    bwd = jnp.concatenate(
        [x5[:, 1:, c1:c2], jnp.zeros_like(x5[:, :1, c1:c2])], axis=1)
    out = jnp.concatenate([fwd, bwd, x5[:, :, c2:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    """ref: functional diag_embed — delegates to the registered tensor op
    (tensor/manipulation.py), one implementation for both surfaces."""
    from paddle_tpu.tensor.manipulation import diag_embed as _impl
    return _impl(x, offset=offset, dim1=dim1, dim2=dim2)


def affine_grid(theta, out_shape, align_corners=True):
    """ref: vision.py:28 — (N, 2, 3) affine params → (N, H, W, 2) sampling
    grid in [-1, 1] coords (2-D case; (N, 3, 4) → (N, D, H, W, 3))."""
    theta = jnp.asarray(theta)
    shape = [int(s) for s in out_shape]

    def line(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    if theta.shape[1] == 2:  # 2-D
        n, _, h, w = shape
        ys, xs = jnp.meshgrid(line(h), line(w), indexing="ij")
        base = jnp.stack([xs, ys, jnp.ones_like(xs)], -1)   # (H, W, 3)
        grid = jnp.einsum("hwk,nck->nhwc", base,
                          theta.astype(jnp.float32))
        return grid.astype(theta.dtype)
    n, _, d, h, w = shape
    zs, ys, xs = jnp.meshgrid(line(d), line(h), line(w), indexing="ij")
    base = jnp.stack([xs, ys, zs, jnp.ones_like(xs)], -1)   # (D, H, W, 4)
    grid = jnp.einsum("dhwk,nck->ndhwc", base, theta.astype(jnp.float32))
    return grid.astype(theta.dtype)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """ref: vision.py:136 — sample NCHW ``x`` at (N, H', W', 2) grid
    locations given in [-1, 1]; bilinear or nearest, zeros/border/
    reflection padding."""
    x = jnp.asarray(x)
    grid = jnp.asarray(grid, jnp.float32)
    n, c, h, w = x.shape

    def unnormalize(coord, size):
        if align_corners:
            return (coord + 1.0) / 2.0 * (size - 1)
        return ((coord + 1.0) * size - 1.0) / 2.0

    ix = unnormalize(grid[..., 0], w)   # (N, H', W')
    iy = unnormalize(grid[..., 1], h)

    def reflect(coord, size):
        if align_corners:
            span = 2.0 * (size - 1)
            if size == 1:
                return jnp.zeros_like(coord)
            coord = jnp.abs(coord) % span
            return jnp.where(coord > size - 1, span - coord, coord)
        span = 2.0 * size
        coord = jnp.abs(coord + 0.5) % span
        coord = jnp.where(coord > size, span - coord, coord) - 0.5
        return jnp.clip(coord, 0, size - 1)

    if padding_mode == "border":
        ix = jnp.clip(ix, 0, w - 1)
        iy = jnp.clip(iy, 0, h - 1)
    elif padding_mode == "reflection":
        ix = reflect(ix, w)
        iy = reflect(iy, h)

    def gather(iy_idx, ix_idx):
        """x[n, :, iy, ix] with zero padding outside."""
        valid = ((iy_idx >= 0) & (iy_idx <= h - 1)
                 & (ix_idx >= 0) & (ix_idx <= w - 1))
        iy_c = jnp.clip(iy_idx, 0, h - 1).astype(jnp.int32)
        ix_c = jnp.clip(ix_idx, 0, w - 1).astype(jnp.int32)
        out = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, iy_c, ix_c)
        # out: (N, C, H', W'); valid: (N, H', W')
        return out * valid[:, None].astype(x.dtype)

    if mode == "nearest":
        return gather(jnp.round(iy), jnp.round(ix))
    x0, y0 = jnp.floor(ix), jnp.floor(iy)
    x1, y1 = x0 + 1, y0 + 1
    wa = ((x1 - ix) * (y1 - iy))[:, None]
    wb = ((x1 - ix) * (iy - y0))[:, None]
    wc = ((ix - x0) * (y1 - iy))[:, None]
    wd = ((ix - x0) * (iy - y0))[:, None]
    va = gather(y0, x0)
    vb = gather(y1, x0)
    vc = gather(y0, x1)
    vd = gather(y1, x1)
    return (va * wa + vb * wb + vc * wc + vd * wd).astype(x.dtype)
