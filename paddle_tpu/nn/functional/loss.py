"""Loss functionals (ref: python/paddle/nn/functional/loss.py, 26 loss
classes' functional mirrors)."""

import jax
import jax.numpy as jnp

__all__ = ["cross_entropy", "softmax_with_cross_entropy",
           "edit_distance", "margin_cross_entropy",
           "fluid_softmax_with_cross_entropy", "nll_loss",
           "binary_cross_entropy", "binary_cross_entropy_with_logits",
           "mse_loss", "l1_loss", "smooth_l1_loss", "huber_loss", "kl_div",
           "margin_ranking_loss", "cosine_embedding_loss", "ctc_loss",
           "hinge_embedding_loss", "log_loss", "square_error_cost",
           "triplet_margin_loss", "sigmoid_focal_loss", "dice_loss",
           "npair_loss", "soft_margin_loss", "multi_label_soft_margin_loss",
           "poisson_nll_loss", "multi_margin_loss",
           "triplet_margin_with_distance_loss", "hsigmoid_loss"]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    """ref: nn.functional.cross_entropy → c_softmax_with_cross_entropy for
    the TP-sharded variant (see paddle_tpu.distributed.parallel_cross_entropy)."""
    x = jnp.asarray(input)
    label_arr = jnp.asarray(label)
    logp = jax.nn.log_softmax(x, axis=axis) if use_softmax else jnp.log(x)
    if soft_label:
        target = label_arr
        if label_smoothing > 0:
            n = x.shape[axis]
            target = (1 - label_smoothing) * target + label_smoothing / n
        loss = -jnp.sum(target * logp, axis=axis)
    else:
        if label_arr.ndim == x.ndim:
            label_arr = jnp.squeeze(label_arr, axis=axis)
        label_arr = label_arr.astype(jnp.int32)
        valid = label_arr != ignore_index
        safe = jnp.where(valid, label_arr, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis)
        picked = jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0:
            n = x.shape[axis]
            smooth = jnp.mean(logp, axis=axis)
            loss = -((1 - label_smoothing) * picked + label_smoothing * smooth)
        else:
            loss = -picked
        if weight is not None:
            w = jnp.take(jnp.asarray(weight), safe)
            loss = loss * w
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
        else:
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(valid.astype(loss.dtype)), 1.0)
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    loss = cross_entropy(logits, label, reduction="none",
                         soft_label=soft_label, ignore_index=ignore_index,
                         axis=axis)
    loss = jnp.expand_dims(loss, axis)
    if return_softmax:
        return loss, jax.nn.softmax(jnp.asarray(logits), axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100,  # noqa: A002
             reduction="mean"):
    x = jnp.asarray(input)  # log-probabilities
    label_arr = jnp.asarray(label).astype(jnp.int32)
    valid = label_arr != ignore_index
    safe = jnp.where(valid, label_arr, 0)
    picked = jnp.take_along_axis(x, safe[..., None], axis=-1)[..., 0]
    loss = -picked
    if weight is not None:
        w = jnp.take(jnp.asarray(weight), safe)
        loss = loss * w
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        denom = jnp.sum(jnp.take(jnp.asarray(weight), safe) * valid) \
            if weight is not None else jnp.sum(valid)
        return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean"):  # noqa: A002
    x = jnp.clip(jnp.asarray(input), 1e-12, 1.0 - 1e-7)
    y = jnp.asarray(label)
    loss = -(y * jnp.log(x) + (1 - y) * jnp.log1p(-x))
    if weight is not None:
        loss = loss * jnp.asarray(weight)
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    z = jnp.asarray(logit)
    y = jnp.asarray(label)
    # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
    base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    if pos_weight is not None:
        pw = jnp.asarray(pos_weight)
        log_w = (pw - 1) * y + 1
        base = base * log_w
    if weight is not None:
        base = base * jnp.asarray(weight)
    return _reduce(base, reduction)


def mse_loss(input, label, reduction="mean"):  # noqa: A002
    loss = jnp.square(jnp.asarray(input) - jnp.asarray(label))
    return _reduce(loss, reduction)


def l1_loss(input, label, reduction="mean"):  # noqa: A002
    loss = jnp.abs(jnp.asarray(input) - jnp.asarray(label))
    return _reduce(loss, reduction)


def square_error_cost(input, label):  # noqa: A002
    return jnp.square(jnp.asarray(input) - jnp.asarray(label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):  # noqa: A002
    d = jnp.asarray(input) - jnp.asarray(label)
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
    return _reduce(loss, reduction)


def huber_loss(input, label, delta=1.0, reduction="mean"):  # noqa: A002
    return smooth_l1_loss(input, label, reduction, delta)


def kl_div(input, label, reduction="mean"):  # noqa: A002
    x = jnp.asarray(input)  # log-probs
    y = jnp.asarray(label)
    loss = jnp.where(y > 0, y * (jnp.log(jnp.maximum(y, 1e-12)) - x), 0.0)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return _reduce(loss, reduction)


def log_loss(input, label, epsilon=1e-4):  # noqa: A002
    x = jnp.asarray(input)
    y = jnp.asarray(label)
    return -y * jnp.log(x + epsilon) - (1 - y) * jnp.log(1 - x + epsilon)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):  # noqa: A002
    loss = jnp.maximum(
        0.0, -jnp.asarray(label) * (jnp.asarray(input) - jnp.asarray(other))
        + margin)
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean"):
    from paddle_tpu.nn.functional.common import cosine_similarity
    cos = cosine_similarity(input1, input2, axis=-1)
    y = jnp.asarray(label)
    loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):  # noqa: A002
    x = jnp.asarray(input)
    y = jnp.asarray(label)
    loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
    return _reduce(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean"):
    a = jnp.asarray(input)
    pos = jnp.asarray(positive)
    neg = jnp.asarray(negative)

    def dist(u, v):
        return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)

    d_pos = dist(a, pos)
    d_neg = dist(a, neg)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(pos, neg))
    loss = jnp.maximum(0.0, d_pos - d_neg + margin)
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    z = jnp.asarray(logit)
    y = jnp.asarray(label)
    p = jax.nn.sigmoid(z)
    ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    p_t = p * y + (1 - p) * (1 - y)
    a_t = alpha * y + (1 - alpha) * (1 - y)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / jnp.asarray(normalizer)
    return _reduce(loss, reduction)


def dice_loss(input, label, epsilon=1e-5):  # noqa: A002
    x = jnp.asarray(input)
    y = jax.nn.one_hot(jnp.asarray(label)[..., 0], x.shape[-1])
    red = tuple(range(1, x.ndim))
    inter = jnp.sum(x * y, axis=red)
    union = jnp.sum(x, axis=red) + jnp.sum(y, axis=red)
    return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    a = jnp.asarray(anchor)
    p = jnp.asarray(positive)
    y = jnp.asarray(labels).reshape(-1, 1)
    same = (y == y.T).astype(a.dtype)
    same = same / jnp.sum(same, axis=1, keepdims=True)
    logits = a @ p.T
    logp = jax.nn.log_softmax(logits, axis=-1)
    xent = jnp.mean(-jnp.sum(same * logp, axis=-1))
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(a), -1))
                    + jnp.mean(jnp.sum(jnp.square(p), -1))) * 0.25
    return xent + reg


def soft_margin_loss(input, label, reduction="mean"):  # noqa: A002
    loss = jnp.log1p(jnp.exp(-jnp.asarray(label) * jnp.asarray(input)))
    return _reduce(loss, reduction)


def multi_label_soft_margin_loss(input, label, weight=None,  # noqa: A002
                                 reduction="mean"):
    x = jnp.asarray(input)
    y = jnp.asarray(label)
    loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
    loss = jnp.mean(loss, axis=-1)
    if weight is not None:
        loss = loss * jnp.asarray(weight)
    return _reduce(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False,  # noqa: A002
                     epsilon=1e-8, reduction="mean"):
    x = jnp.asarray(input)
    y = jnp.asarray(label)
    if log_input:
        loss = jnp.exp(x) - y * x
    else:
        loss = x - y * jnp.log(x + epsilon)
    if full:
        stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
            2 * jnp.pi * (y + epsilon))
        loss = loss + jnp.where(y > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard alpha-recursion expressed with lax.scan
    (ref: warpctc binding, paddle/fluid/operators/warpctc_op.*)."""
    lp = jnp.asarray(log_probs)  # (T, B, C) log-softmax already applied? ref takes logits
    lp = jax.nn.log_softmax(lp, axis=-1)
    labels = jnp.asarray(labels).astype(jnp.int32)  # (B, S)
    T, B, C = lp.shape
    S = labels.shape[1]
    # extended label sequence with blanks: length 2S+1
    ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    ext_len = 2 * jnp.asarray(label_lengths) + 1

    neg_inf = -1e30
    alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
    alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(S > 0, lp[0, jnp.arange(B), ext[:, 1]], neg_inf))

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, lp_t):
        a_shift1 = jnp.concatenate(
            [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate(
            [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
        a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        return merged + emit, None

    def scan_collect(alpha, lp_t):
        new, _ = step(alpha, lp_t)
        return new, new

    _, alphas = jax.lax.scan(scan_collect, alpha0, lp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, B, 2S+1)
    t_idx = jnp.asarray(input_lengths) - 1
    final = alphas[t_idx, jnp.arange(B)]  # (B, 2S+1)
    last = jnp.take_along_axis(final, (ext_len - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(
        final, jnp.maximum(ext_len - 2, 0)[:, None], axis=1)[:, 0]
    ll = jnp.logaddexp(last, last2)
    loss = -ll
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(jnp.asarray(label_lengths), 1))
    return _reduce(loss, reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,  # noqa: A002
                      reduction="mean"):
    """ref: nn.functional.multi_margin_loss — per-sample mean over
    non-target classes of max(0, margin - x_y + x_j)^p."""
    x = jnp.asarray(input)
    y = jnp.asarray(label).astype(jnp.int32)
    n, c = x.shape
    x_y = jnp.take_along_axis(x, y[:, None], axis=1)
    hinge = jnp.maximum(0.0, margin - x_y + x) ** p
    if weight is not None:
        hinge = hinge * jnp.asarray(weight)[y][:, None]
    mask = jax.nn.one_hot(y, c, dtype=x.dtype)
    loss = jnp.sum(hinge * (1.0 - mask), axis=1) / c
    return _reduce(loss, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,  # noqa: A002
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean"):
    """ref: nn.functional.triplet_margin_with_distance_loss — triplet loss
    with a user distance callable (default: euclidean)."""
    a = jnp.asarray(input)
    pos = jnp.asarray(positive)
    neg = jnp.asarray(negative)
    dist = distance_function if distance_function is not None else (
        lambda u, v: jnp.sqrt(jnp.sum(jnp.square(u - v), axis=-1) + 1e-12))
    d_pos = dist(a, pos)
    d_neg = dist(a, neg)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(pos, neg))
    loss = jnp.maximum(0.0, d_pos - d_neg + margin)
    return _reduce(loss, reduction)


import functools as _functools


@_functools.lru_cache(maxsize=64)
def _hsigmoid_paths(num_classes):
    """Heap-layout complete binary tree over ``num_classes`` leaves:
    internal nodes 0..num_classes-2, leaf for class c at num_classes-1+c.
    Returns (path_table, path_code, path_mask) padded to the max depth."""
    import numpy as np
    paths = []
    for c in range(num_classes):
        node = num_classes - 1 + c
        path = []
        while node > 0:
            parent = (node - 1) // 2
            path.append((parent, 1.0 if node == 2 * parent + 2 else 0.0))
            node = parent
        paths.append(path[::-1])
    depth = max(len(p) for p in paths)
    table = np.zeros((num_classes, depth), np.int32)
    code = np.zeros((num_classes, depth), np.float32)
    mask = np.zeros((num_classes, depth), np.float32)
    for c, p in enumerate(paths):
        for d, (node, bit) in enumerate(p):
            table[c, d] = node
            code[c, d] = bit
            mask[c, d] = 1.0
    return jnp.asarray(table), jnp.asarray(code), jnp.asarray(mask)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  reduction="mean"):
    """Hierarchical sigmoid (ref: nn.functional.hsigmoid_loss → phi
    hsigmoid_loss kernel). Default tree: heap-layout complete binary tree
    (the reference's non-custom-tree mode); custom trees via
    path_table/path_code (+ implicit all-valid mask). O(log C) per sample:
    sum over the root→leaf path of BCE-with-logits on each internal-node
    binary decision. A correct implementation satisfies
    Σ_c exp(-loss(x, c)) == 1 (leaf probabilities normalize), which the
    tests assert."""
    x = jnp.asarray(input)
    y = jnp.asarray(label).astype(jnp.int32).reshape(-1)
    w = jnp.asarray(weight)  # (num_classes-1, D) internal-node weights
    if path_table is None:
        table, code, mask = _hsigmoid_paths(int(num_classes))
        t, cde, msk = table[y], code[y], mask[y]
    else:
        # custom mode (≙ is_custom=True): tables are PER-SAMPLE (N, L),
        # exactly as the reference passes them — never re-indexed by label
        t = jnp.asarray(path_table)
        cde = jnp.asarray(path_code)
        msk = jnp.where(t >= 0, 1.0, 0.0)
        t = jnp.maximum(t, 0)
    w_path = w[t]                                   # (N, depth, D)
    logits = jnp.einsum("nd,npd->np", x, w_path)
    if bias is not None:
        logits = logits + jnp.asarray(bias).reshape(-1)[t]
    # BCE with logits against the path code (right child = 1)
    bce = jnp.maximum(logits, 0) - logits * cde + jnp.log1p(
        jnp.exp(-jnp.abs(logits)))
    loss = jnp.sum(bce * msk, axis=1)
    return _reduce(loss, reduction)


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """ref: nn/functional/loss.py:472 — batched Levenshtein distance via a
    lax.scan DP over the hypothesis axis (anti-diagonal-free formulation:
    one row of the DP table per scan step). Returns
    (distance (B, 1) float32, sequence_num (1,) float32)."""
    from jax import lax
    inp = jnp.asarray(input, jnp.int32)
    lab = jnp.asarray(label, jnp.int32)
    b, m = inp.shape
    n = lab.shape[1]
    if input_length is None:
        input_length = jnp.full((b,), m, jnp.int32)
    if label_length is None:
        label_length = jnp.full((b,), n, jnp.int32)
    input_length = jnp.asarray(input_length, jnp.int32)
    label_length = jnp.asarray(label_length, jnp.int32)
    if ignored_tokens:
        # drop ignored tokens by compacting each row (stable order)
        def compact(seq, length, toks):
            keep = jnp.ones(seq.shape, bool)
            for t in toks:
                keep &= seq != t
            keep &= jnp.arange(seq.shape[0]) < length
            order = jnp.argsort(~keep, stable=True)
            return seq[order], jnp.sum(keep).astype(jnp.int32)
        inp, input_length = jax.vmap(
            lambda s, l: compact(s, l, ignored_tokens))(inp, input_length)
        lab, label_length = jax.vmap(
            lambda s, l: compact(s, l, ignored_tokens))(lab, label_length)

    # DP rows: prev[j] = D(i-1, j); masked positions beyond lengths pinned
    j_iota = jnp.arange(n + 1)

    def per_example(hyp, ref, hlen, rlen):
        def row(prev, i):
            # i: 1..m (current hypothesis position)
            sub_cost = (hyp[i - 1] != ref) & (jnp.arange(n) < rlen)
            # compute current row left-to-right with an inner scan
            def cell(left, j):
                up = prev[j]
                diag = prev[j - 1]
                cur = jnp.minimum(jnp.minimum(up + 1, left + 1),
                                  diag + jnp.where(sub_cost[j - 1], 1, 0))
                return cur, cur
            first = jnp.asarray(i, jnp.int32)
            _, rest = lax.scan(cell, first, jnp.arange(1, n + 1))
            cur_row = jnp.concatenate([first[None], rest])
            # beyond hlen the row must stay at the hlen row's values
            return jnp.where(i <= hlen, cur_row, prev), None

        row0 = j_iota.astype(jnp.int32)
        final, _ = lax.scan(row, row0, jnp.arange(1, m + 1))
        return final[rlen]

    dist = jax.vmap(per_example)(inp, lab, input_length,
                                 label_length).astype(jnp.float32)
    if normalized:
        dist = dist / jnp.maximum(label_length.astype(jnp.float32), 1.0)
    return dist.reshape(b, 1), jnp.asarray([float(b)], jnp.float32)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ref: nn/functional/loss.py:1841 (ArcFace margin loss). ``logits``
    are cos(theta) of normalized features × normalized weights. With a
    'tp'-sharded class dim under shard_map the softmax normalizer would
    need a psum — this single-program version expects full logits (the
    model-parallel variant lives in distributed/mp_ops.py
    parallel_cross_entropy)."""
    logits = jnp.asarray(logits)
    label = jnp.asarray(label, jnp.int32).reshape(-1)
    n, c = logits.shape
    cos_t = jnp.clip(jnp.take_along_axis(
        logits, label[:, None], axis=1)[:, 0], -1.0, 1.0)
    theta = jnp.arccos(cos_t)
    target = jnp.cos(margin1 * theta + margin2) - margin3
    adjusted = logits.at[jnp.arange(n), label].set(target)
    z = adjusted * scale
    logp = jax.nn.log_softmax(z, axis=-1)
    loss = -jnp.take_along_axis(logp, label[:, None], axis=1)
    if reduction == "mean":
        loss = jnp.mean(loss)
    elif reduction == "sum":
        loss = jnp.sum(loss)
    if return_softmax:
        return loss, jax.nn.softmax(z, axis=-1)
    return loss


def fluid_softmax_with_cross_entropy(logits, label, soft_label=False,
                                     ignore_index=-100, numeric_stable_mode=True,
                                     return_softmax=False, axis=-1):
    """ref: fluid alias of softmax_with_cross_entropy (loss.py)."""
    return softmax_with_cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        return_softmax=return_softmax, axis=axis)
