"""Attention functionals.

Reference analog: the fused attention CUDA inventory —
paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h,
fused_softmax_mask.cu.h. Here the hot path is a Pallas flash-attention TPU
kernel (paddle_tpu.ops.pallas.flash_attention) with an XLA reference path for
CPU/debugging; selection via the ``use_pallas_kernels`` flag.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu import flags

__all__ = ["scaled_dot_product_attention", "attention_reference"]


def attention_reference(q, k, v, mask=None, is_causal=False, scale=None,
                        dropout_p=0.0, key=None):
    """Plain XLA attention. q/k/v: (B, S, H, D) like the reference's
    fused_attention layout."""
    q = jnp.asarray(q)
    k = jnp.asarray(k)
    v = jnp.asarray(v)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # (B, H, Sq, Sk)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(causal, logits, -1e30)
    if mask is not None:
        mask = jnp.asarray(mask)
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None,
                                 rng_key: Optional[jax.Array] = None):
    """Flash attention on TPU (Pallas) or XLA fallback.

    Layout (B, S, H, D) matching paddle.nn.functional.scaled_dot_product_attention.
    """
    # attention matmuls are O1-white-listed (amp/auto_cast WHITE_LIST:44)
    from paddle_tpu.amp.auto_cast import amp_cast
    q = amp_cast(jnp.asarray(query))
    key = amp_cast(jnp.asarray(key))
    value = amp_cast(jnp.asarray(value))
    # head_dim % 8: Mosaic-lowerable without a sublane-misaligned layout
    # (failures there surface at jit-compile time, outside the try/except)
    use_pallas = (flags.get_flag("use_pallas_kernels")
                  and q.ndim == 4
                  and attn_mask is None
                  and dropout_p == 0.0
                  and jax.default_backend() == "tpu"
                  and q.shape[1] >= 128
                  and q.shape[-1] % 8 == 0)
    if use_pallas:
        try:
            from paddle_tpu.ops.pallas.flash_attention import flash_attention
            return flash_attention(q, jnp.asarray(key), jnp.asarray(value),
                                   causal=is_causal, scale=scale)
        except Exception:
            pass
    return attention_reference(q, key, value, mask=attn_mask,
                               is_causal=is_causal, scale=scale,
                               dropout_p=dropout_p if training else 0.0,
                               key=rng_key)
