"""Attention functionals.

Reference analog: the fused attention CUDA inventory —
paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h,
fused_softmax_mask.cu.h. Here the hot path is a Pallas flash-attention TPU
kernel (paddle_tpu.ops.pallas.flash_attention) with an XLA reference path for
CPU/debugging; selection via the ``use_pallas_kernels`` flag.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu import flags

__all__ = ["scaled_dot_product_attention", "attention_reference"]


def attention_reference(q, k, v, mask=None, is_causal=False, scale=None,
                        dropout_p=0.0, key=None):
    """Plain XLA attention. q/k/v: (B, S, H, D) like the reference's
    fused_attention layout. k/v may carry fewer heads (GQA)."""
    q = jnp.asarray(q)
    k = jnp.asarray(k)
    v = jnp.asarray(v)
    if k.shape[2] != q.shape[2]:  # GQA: repeat kv heads per group
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # (B, H, Sq, Sk)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(causal, logits, -1e30)
    if mask is not None:
        mask = jnp.asarray(mask)
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None,
                                 rng_key: Optional[jax.Array] = None,
                                 kv_lens: Optional[jax.Array] = None):
    """Flash attention on TPU (Pallas) or XLA fallback.

    Layout (B, S, H, D) matching paddle.nn.functional.scaled_dot_product_attention.
    ``kv_lens`` (B,) declares a contiguous key-padding mask (keys at
    positions >= kv_lens[b] are invisible); when given it routes the
    Pallas kernel instead of falling back to the XLA path, which is the
    BERT fast path (VERDICT r2 item 3). ``attn_mask`` is still honored by
    the fallback; callers passing ``kv_lens`` must ensure the two agree.
    Dropout on the TPU path uses a deterministic counter-based PRF seeded
    from ``rng_key``. k/v may carry fewer heads than q (GQA).
    """
    # attention matmuls are O1-white-listed (amp/auto_cast WHITE_LIST:44)
    from paddle_tpu.amp.auto_cast import amp_cast
    q = amp_cast(jnp.asarray(query))
    key = amp_cast(jnp.asarray(key))
    value = amp_cast(jnp.asarray(value))
    eff_dropout = dropout_p if training else 0.0
    # head_dim % 8: Mosaic-lowerable without a sublane-misaligned layout
    # (failures there surface at jit-compile time, outside the try/except)
    use_pallas = (flags.get_flag("use_pallas_kernels")
                  and q.ndim == 4
                  and (eff_dropout == 0.0 or rng_key is not None)
                  and jax.default_backend() == "tpu"
                  and q.shape[1] >= 128
                  and q.shape[-1] % 8 == 0)
    if use_pallas:
        try:
            from paddle_tpu.ops.pallas.flash_attention import flash_attention
            seed = None
            if eff_dropout > 0.0:
                seed = jax.random.bits(rng_key, (), jnp.uint32).astype(
                    jnp.int32)
            bias = None
            if attn_mask is not None:
                # any mask shape is honored via the kernel's blocked bias
                # (a size-1 Sq dim is never materialized to (..,Sq,Sk));
                # kv_lens remains a pure block-skip accelerator on top
                mask = jnp.asarray(attn_mask)
                bias = (jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
                        if mask.dtype == jnp.bool_ else mask)
                while bias.ndim < 4:
                    bias = bias[None]
            return flash_attention(q, jnp.asarray(key), jnp.asarray(value),
                                   causal=is_causal, scale=scale,
                                   kv_lens=kv_lens, bias=bias,
                                   dropout_p=eff_dropout,
                                   dropout_seed=seed)
        except Exception:
            pass
    if attn_mask is None and kv_lens is not None:
        # fallback must honor the padding mask too (kv_lens is not a
        # Pallas-only hint): build the additive key mask it declares.
        # Finite fill (-1e30, the attention_reference convention): an
        # example with kv_lens == 0 must yield zeros, not NaN softmax.
        sk = key.shape[1]
        attn_mask = jnp.where(
            jnp.arange(sk)[None, :] < jnp.asarray(kv_lens)[:, None],
            0.0, -1e30).astype(jnp.float32)[:, None, None, :]
    return attention_reference(q, key, value, mask=attn_mask,
                               is_causal=is_causal, scale=scale,
                               dropout_p=eff_dropout,
                               key=rng_key)
