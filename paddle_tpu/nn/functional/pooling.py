"""Pooling (ref: python/paddle/nn/functional/pooling.py, 15 classes).
All lower to XLA reduce_window."""

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d",
           "adaptive_max_pool3d", "max_unpool2d", "max_unpool1d", "max_unpool3d"]


def _t(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


def _pads(padding, n):
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    return [tuple(p) for p in padding]


def _ceil_extra(in_sizes, kernel, stride, pads, ceil_mode):
    """Per-dim extra right-padding so reduce_window emits ceil-mode output
    sizes (ref semantics: pooling ceil_mode — the last, partial window is
    kept iff it starts inside input+left-pad)."""
    extras = []
    for i in range(len(kernel)):
        size = in_sizes[i] + pads[i][0] + pads[i][1]
        if ceil_mode:
            out = -(-(size - kernel[i]) // stride[i]) + 1
            if (out - 1) * stride[i] >= in_sizes[i] + pads[i][0]:
                out -= 1
        else:
            out = (size - kernel[i]) // stride[i] + 1
        extras.append(max(0, (out - 1) * stride[i] + kernel[i] - size))
    return extras


def _pool(x, n, kernel, stride, padding, init, op, avg=False,
          exclusive=True, ceil_mode=False, divisor_override=None):
    x = jnp.asarray(x)
    kernel = _t(kernel, n)
    stride = _t(stride if stride is not None else kernel, n)
    pads = _pads(padding, n)
    extras = _ceil_extra(x.shape[-n:], kernel, stride, pads, ceil_mode)
    pads = [(pl, pr + e) for (pl, pr), e in zip(pads, extras)]
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    full_pads = [(0, 0), (0, 0)] + pads
    if avg:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                       full_pads)
        if divisor_override is not None:
            return summed / divisor_override
        if exclusive and any(p != (0, 0) for p in pads):
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                           strides, full_pads)
            return summed / counts
        return summed / np.prod(kernel)
    neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
        jnp.iinfo(x.dtype).min
    return jax.lax.reduce_window(x, neg_inf, jax.lax.max, window, strides,
                                 full_pads)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False):
    return _pool(x, 1, kernel_size, stride, padding, 0.0, jax.lax.add,
                 avg=True, exclusive=exclusive, ceil_mode=ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    return _pool(x, 2, kernel_size, stride, padding, 0.0, jax.lax.add,
                 avg=True, exclusive=exclusive, ceil_mode=ceil_mode,
                 divisor_override=divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW"):
    return _pool(x, 3, kernel_size, stride, padding, 0.0, jax.lax.add,
                 avg=True, exclusive=exclusive, ceil_mode=ceil_mode,
                 divisor_override=divisor_override)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False):
    if return_mask:
        return _max_pool_with_mask(x, 1, kernel_size, stride, padding,
                                   ceil_mode)
    return _pool(x, 1, kernel_size, stride, padding, -jnp.inf, jax.lax.max,
                 ceil_mode=ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW"):
    if return_mask:
        return _max_pool_with_mask(x, 2, kernel_size, stride, padding,
                                   ceil_mode)
    return _pool(x, 2, kernel_size, stride, padding, -jnp.inf, jax.lax.max,
                 ceil_mode=ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW"):
    if return_mask:
        return _max_pool_with_mask(x, 3, kernel_size, stride, padding,
                                   ceil_mode)
    return _pool(x, 3, kernel_size, stride, padding, -jnp.inf, jax.lax.max,
                 ceil_mode=ceil_mode)


def _max_pool_with_mask(x, n, kernel, stride, padding, ceil_mode=False):
    """(pooled values, flat spatial index of each window max).

    Values come from the plain reduce_window max (differentiable, exact for
    ints); indices from a variadic argmax pass under stop_gradient — JAX
    cannot differentiate a variadic custom combiner, and the float32 detour
    it needs would corrupt int values above 2**24. Indices are int32 for the
    same 2**24 reason (reachable on 3D volumes)."""
    x = jnp.asarray(x)
    out = _pool(x, n, kernel, stride, padding, -jnp.inf, jax.lax.max,
                ceil_mode=ceil_mode)
    x = jax.lax.stop_gradient(x)
    spatial = x.shape[-n:]
    idx = jnp.arange(int(np.prod(spatial)), dtype=jnp.int32).reshape(
        (1, 1) + spatial)
    idx = jnp.broadcast_to(idx, x.shape)
    k = _t(kernel, n)
    s = _t(stride if stride is not None else kernel, n)
    pads = _pads(padding, n)
    extras = _ceil_extra(spatial, k, s, pads, ceil_mode)
    pads = [(0, 0), (0, 0)] + [(pl, pr + e)
                               for (pl, pr), e in zip(pads, extras)]

    def select(a, b):
        av, ai = a
        bv, bi = b
        pick = av >= bv
        return jnp.where(pick, av, bv), jnp.where(pick, ai, bi)

    init = (-jnp.inf, jnp.int32(-1))
    _, idxs = jax.lax.reduce_window(
        (x.astype(jnp.float32), idx), init,
        lambda a, b: select(a, b),
        (1, 1) + k, (1, 1) + s, pads)
    return out, idxs


def _adaptive_start_end(out_size, in_size):
    starts = (np.arange(out_size) * in_size) // out_size
    ends = -(-(np.arange(1, out_size + 1) * in_size) // out_size)
    return starts, ends


def _adaptive_pool(x, output_size, n, reduce_fn):
    x = jnp.asarray(x)
    out_sizes = _t(output_size, n)
    spatial = x.shape[-n:]
    # uniform case → plain strided pooling
    if all(s % o == 0 for s, o in zip(spatial, out_sizes)):
        kernel = tuple(s // o for s, o in zip(spatial, out_sizes))
        return _pool(x, n, kernel, kernel, 0, None,
                     jax.lax.max if reduce_fn == "max" else jax.lax.add,
                     avg=(reduce_fn == "avg"), exclusive=False)
    # general case: gather per output cell (static python loop, small sizes)
    out = x
    for dim in range(n):
        axis = x.ndim - n + dim
        starts, ends = _adaptive_start_end(out_sizes[dim], out.shape[axis])
        slices = []
        for s0, e0 in zip(starts, ends):
            sl = jax.lax.slice_in_dim(out, int(s0), int(e0), axis=axis)
            red = jnp.max(sl, axis=axis, keepdims=True) if reduce_fn == "max" \
                else jnp.mean(sl, axis=axis, keepdims=True)
            slices.append(red)
        out = jnp.concatenate(slices, axis=axis)
    return out


def adaptive_avg_pool1d(x, output_size):
    return _adaptive_pool(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False):
    return _adaptive_pool(x, output_size, 1, "max")


def adaptive_max_pool2d(x, output_size, return_mask=False):
    return _adaptive_pool(x, output_size, 2, "max")


def adaptive_max_pool3d(x, output_size, return_mask=False):
    return _adaptive_pool(x, output_size, 3, "max")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW"):
    x = jnp.asarray(x)
    indices = jnp.asarray(indices)
    n, c, h, w = x.shape
    if output_size is None:
        k = _t(kernel_size, 2)
        s = _t(stride if stride is not None else kernel_size, 2)
        p = (padding,) * 2 if isinstance(padding, int) else _t(padding, 2)
        oh = (h - 1) * s[0] + k[0] - 2 * p[0]
        ow = (w - 1) * s[1] + k[1] - 2 * p[1]
    else:
        oh, ow = output_size[-2:]
    flat = jnp.zeros((n, c, oh * ow), x.dtype).at[
        jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
        indices.reshape(n, c, -1)].set(x.reshape(n, c, -1))
    return flat.reshape(n, c, oh, ow)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL"):
    """ref: nn.functional.max_unpool1d — scatter via the 2d path on a
    width-1 spatial dim."""
    x = jnp.asarray(x)
    indices = jnp.asarray(indices)
    pad = padding if isinstance(padding, int) else _t(padding, 1)[0]
    if output_size is None:
        k = _t(kernel_size, 1)[0]
        s = _t(stride if stride is not None else kernel_size, 1)[0]
        # padding applies to the length dim only — the synthetic width-1
        # dim below must see padding 0
        ol = (x.shape[-1] - 1) * s + k - 2 * pad
    else:
        ol = output_size[-1]
    out = max_unpool2d(x[:, :, :, None], indices[:, :, :, None],
                       (kernel_size, 1),
                       (stride if stride is not None else kernel_size, 1),
                       0, (ol, 1))
    return out[:, :, :, 0]


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW"):
    """ref: nn.functional.max_unpool3d — flat-index scatter over D*H*W."""
    x = jnp.asarray(x)
    indices = jnp.asarray(indices)
    n, c, d, h, w = x.shape
    if output_size is None:
        k = _t(kernel_size, 3)
        s = _t(stride if stride is not None else kernel_size, 3)
        p = (padding,) * 3 if isinstance(padding, int) else _t(padding, 3)
        od = (d - 1) * s[0] + k[0] - 2 * p[0]
        oh = (h - 1) * s[1] + k[1] - 2 * p[1]
        ow = (w - 1) * s[2] + k[2] - 2 * p[2]
    else:
        od, oh, ow = output_size[-3:]
    flat = jnp.zeros((n, c, od * oh * ow), x.dtype).at[
        jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
        indices.reshape(n, c, -1)].set(x.reshape(n, c, -1))
    return flat.reshape(n, c, od, oh, ow)
