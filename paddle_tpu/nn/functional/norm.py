"""Normalization functionals (ref: python/paddle/nn/functional/norm.py).
XLA fuses these into neighbouring ops; a Pallas fused layer-norm is provided
for the cases XLA's fusion misses (paddle_tpu.ops.pallas.layer_norm)."""

import jax
import jax.numpy as jnp

__all__ = ["normalize", "batch_norm", "layer_norm", "instance_norm",
           "group_norm", "local_response_norm", "rms_norm"]


def normalize(x, p=2, axis=1, epsilon=1e-12):
    x = jnp.asarray(x)
    if p == 2:
        denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    else:
        denom = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(denom, epsilon)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW"):
    """Returns (out, new_mean, new_var) in training mode, out otherwise.
    ref semantics: phi batch_norm kernel; running stats use
    ``momentum * old + (1-momentum) * batch`` like the reference."""
    x = jnp.asarray(x)
    in_dtype = x.dtype
    c_axis = 1 if data_format.startswith("NC") and x.ndim > 1 else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    shape = [1] * x.ndim
    shape[c_axis] = x.shape[c_axis]

    if training:
        # statistics in fp32 (bf16 accumulations drift); output is cast
        # back to the input dtype so bf16 activations stay bf16 through
        # the conv stack (mixed-precision norm convention).
        # SHIFTED one-pass moments: jnp.var's two-pass form reads the
        # activation twice — at ResNet batch sizes that is a full extra
        # HBM sweep per BN. The naive E[x^2]-E[x]^2 cancels
        # catastrophically in f32 when |mean| >> std, so one sample per
        # channel (a free read) is subtracted first: Var[x] =
        # E[(x-s)^2] - E[x-s]^2 is exact for ANY shift s, and any
        # in-distribution s kills the DC offset that drives the
        # cancellation. Both reductions fuse into ONE pass over x.
        xf = x.astype(jnp.float32)
        shift = jax.lax.stop_gradient(xf[tuple(
            slice(None) if i == c_axis else 0 for i in range(x.ndim))])
        d = xf - shift.reshape(shape)
        mean_d = jnp.mean(d, axis=reduce_axes)
        m2_d = jnp.mean(jnp.square(d), axis=reduce_axes)
        var = jnp.maximum(m2_d - jnp.square(mean_d), 0.0)
        mean = shift + mean_d
        rm, rv = jnp.asarray(running_mean), jnp.asarray(running_var)
        n = x.size // x.shape[c_axis]
        unbiased = var * n / max(n - 1, 1)
        # keep buffer dtypes stable across steps (AOT-compiled steps feed
        # updated buffers back in; a dtype drift would mismatch the
        # executable signature)
        new_mean = (momentum * rm + (1 - momentum) * mean).astype(rm.dtype)
        new_var = (momentum * rv + (1 - momentum) * unbiased).astype(rv.dtype)
    else:
        xf = x.astype(jnp.float32)
        mean, var = jnp.asarray(running_mean), jnp.asarray(running_var)
        new_mean, new_var = mean, var

    inv = jax.lax.rsqrt(var.astype(jnp.float32) + epsilon)
    out = (xf - mean.astype(jnp.float32).reshape(shape)) * inv.reshape(shape)
    if weight is not None:
        out = out * jnp.asarray(weight).astype(jnp.float32).reshape(shape)
    if bias is not None:
        out = out + jnp.asarray(bias).astype(jnp.float32).reshape(shape)
    out = out.astype(in_dtype)
    if training:
        return out, new_mean, new_var
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05):
    x = jnp.asarray(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * jnp.asarray(weight)
    if bias is not None:
        out = out + jnp.asarray(bias)
    return out


def rms_norm(x, weight=None, epsilon=1e-06, axis=-1):
    """RMSNorm — not in the reference snapshot but required by modern LLM
    blocks; normalizes by root-mean-square without centering."""
    x = jnp.asarray(x)
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    out = (x * jax.lax.rsqrt(var + epsilon).astype(x.dtype))
    if weight is not None:
        out = out * jnp.asarray(weight)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, training=True, momentum=0.9, epsilon=1e-05,
                  data_format="NCHW"):
    x = jnp.asarray(x)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        shape = [1, -1] + [1] * (x.ndim - 2)
        out = out * jnp.asarray(weight).reshape(shape)
    if bias is not None:
        shape = [1, -1] + [1] * (x.ndim - 2)
        out = out + jnp.asarray(bias).reshape(shape)
    return out


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW"):
    x = jnp.asarray(x)
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    g = x.reshape((n, num_groups, c // num_groups) + spatial)
    axes = tuple(range(2, g.ndim))
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.var(g, axis=axes, keepdims=True)
    g = (g - mean) * jax.lax.rsqrt(var + epsilon)
    out = g.reshape(x.shape)
    shape = [1, c] + [1] * len(spatial)
    if weight is not None:
        out = out * jnp.asarray(weight).reshape(shape)
    if bias is not None:
        out = out + jnp.asarray(bias).reshape(shape)
    return out


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    x = jnp.asarray(x)
    sq = jnp.square(x)
    half = size // 2
    c = x.shape[1]
    pads = [(0, 0)] * x.ndim
    pads[1] = (half, size - half - 1)
    sq = jnp.pad(sq, pads)
    window = [1] * x.ndim
    window[1] = size
    summed = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(window),
                                   (1,) * x.ndim, [(0, 0)] * x.ndim)
    div = (k + alpha * summed / size) ** beta
    return x / div
