"""Common functionals: linear, dropout, embedding, interpolate, pad…
(ref: python/paddle/nn/functional/common.py, input.py)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.nn.module import current_context, is_training

__all__ = ["linear", "bilinear", "class_center_sample",
           "dropout", "dropout2d", "dropout3d", "alpha_dropout",
           "embedding", "one_hot", "interpolate", "upsample", "pad",
           "cosine_similarity", "pixel_shuffle", "pixel_unshuffle",
           "channel_shuffle", "label_smooth", "zeropad2d", "fold_ctx_key",
           "pairwise_distance"]


def linear(x, weight, bias=None):
    """ref: nn.functional.linear → phi matmul+add; weight layout
    (in_features, out_features) as in the reference. Inside an
    amp.auto_cast region (O1) the matmul inputs are cast to the amp dtype
    (matmul is on the reference white list, fluid/dygraph/amp/auto_cast
    WHITE_LIST:44)."""
    from paddle_tpu.amp.auto_cast import amp_cast
    x = amp_cast(jnp.asarray(x))
    if hasattr(weight, "dequantize"):
        # int8 QuantTensor: dispatch through __rmatmul__ so the Pallas
        # int8 kernel (not a dequantized copy) serves the matmul on TPU
        out = x @ weight
    else:
        out = x @ amp_cast(jnp.asarray(weight))
    if bias is not None:
        out = out + amp_cast(jnp.asarray(bias))
    return out


def fold_ctx_key(salt=0, key=None):
    if key is not None:
        return key
    ctx = current_context()
    if ctx is not None:
        return ctx.next_key(salt)
    from paddle_tpu import random as pt_random
    return pt_random.next_key()


def dropout(x, p=0.5, axis=None, training=None, mode="upscale_in_train",
            key=None):
    x = jnp.asarray(x)
    if training is None:
        training = is_training()
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    k = fold_ctx_key(key=key)
    shape = list(x.shape)
    if axis is not None:
        axes = (axis,) if isinstance(axis, int) else axis
        shape = [s if i in axes else 1 for i, s in enumerate(x.shape)]
    keep = jax.random.bernoulli(k, 1.0 - p, tuple(shape))
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


def dropout2d(x, p=0.5, training=None, data_format="NCHW", key=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=axis, training=training, key=key)


def dropout3d(x, p=0.5, training=None, data_format="NCDHW", key=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=axis, training=training, key=key)


def alpha_dropout(x, p=0.5, training=None, key=None):
    x = jnp.asarray(x)
    if training is None:
        training = is_training()
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    k = fold_ctx_key(key=key)
    keep = jax.random.bernoulli(k, 1.0 - p, x.shape)
    a = (1.0 - p + p * alpha_p ** 2) ** -0.5
    b = -a * alpha_p * p
    return a * jnp.where(keep, x, alpha_p) + b


def embedding(x, weight, padding_idx=None, sparse=False):
    """ref: nn.functional.embedding → phi embedding kernel. On TPU this is a
    gather feeding the MXU; ``sparse`` (SelectedRows grads) has no analog —
    XLA produces dense scatter-add grads."""
    w = jnp.asarray(weight)
    idx = jnp.asarray(x)
    out = jnp.take(w, idx, axis=0)
    if padding_idx is not None:
        mask = (idx == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


def one_hot(x, num_classes):
    return jax.nn.one_hot(jnp.asarray(x), num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1):
    label = jnp.asarray(label)
    n = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * jnp.asarray(prior_dist)
    return (1 - epsilon) * label + epsilon / n


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    x1 = jnp.asarray(x1)
    x2 = jnp.asarray(x2)
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):  # noqa: A002
    from paddle_tpu.tensor.manipulation import pad as _tensor_pad
    return _tensor_pad(x, pad, mode=mode, value=value,
                       data_format=data_format)


def zeropad2d(x, padding, data_format="NCHW"):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


def _resize_nearest(x, out_hw):
    n, c, h, w = x.shape
    oh, ow = out_hw
    ridx = (jnp.arange(oh) * h // oh).astype(jnp.int32)
    cidx = (jnp.arange(ow) * w // ow).astype(jnp.int32)
    return x[:, :, ridx[:, None], cidx[None, :]]


def _resize_linear(x, out_hw, align_corners=False):
    # jax.image.resize implements bilinear with half-pixel centers
    n, c, h, w = x.shape
    method = "bilinear"
    return jax.image.resize(x, (n, c) + tuple(out_hw), method=method)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW"):
    x = jnp.asarray(x)
    channel_last = data_format in ("NHWC", "NWC", "NDHWC")
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    spatial = x.shape[2:]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    size = tuple(int(s) for s in np.asarray(size).reshape(-1))
    if mode == "nearest":
        assert len(size) == 2, "nearest resize supports 4-D input"
        out = _resize_nearest(x, size)
    else:
        method = {"bilinear": "bilinear", "linear": "linear",
                  "trilinear": "trilinear", "bicubic": "bicubic",
                  "area": "linear"}[mode]
        out = jax.image.resize(x, x.shape[:2] + size, method=method)
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW"):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    x = jnp.asarray(x)
    r = upscale_factor
    n, c, h, w = x.shape
    oc = c // (r * r)
    x = x.reshape(n, oc, r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, oc, h * r, w * r)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    x = jnp.asarray(x)
    r = downscale_factor
    n, c, h, w = x.shape
    oh, ow = h // r, w // r
    x = x.reshape(n, c, oh, r, ow, r)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return x.reshape(n, c * r * r, oh, ow)


def channel_shuffle(x, groups, data_format="NCHW"):
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    x = jnp.swapaxes(x, 1, 2)
    return x.reshape(n, c, h, w)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    """ref: nn.functional.pairwise_distance (PairwiseDistance layer) —
    p-norm of x - y along the last dim, epsilon added for gradient
    stability at zero."""
    d = jnp.asarray(x) - jnp.asarray(y) + epsilon
    if p == 2.0:
        out = jnp.sqrt(jnp.sum(jnp.square(d), axis=-1))
    elif p == float("inf"):
        out = jnp.max(jnp.abs(d), axis=-1)
    else:
        out = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    return out[..., None] if keepdim else out


def bilinear(x1, x2, weight, bias=None):
    """ref: nn/functional/common.py bilinear — out[n, o] =
    x1[n, i] · W[o, i, j] · x2[n, j] (+ bias)."""
    x1 = jnp.asarray(x1)
    x2 = jnp.asarray(x2)
    w = jnp.asarray(weight)
    out = jnp.einsum("ni,oij,nj->no", x1, w, x2)
    if bias is not None:
        out = out + jnp.asarray(bias).reshape(1, -1)
    return out


def class_center_sample(label, num_classes, num_samples, group=None,
                        seed=0):
    """ref: nn/functional/common.py:2008 — sample ``num_samples`` class
    centers ALWAYS including every positive class in ``label``; returns
    (remapped_label, sampled_class_indices). Deterministic given seed
    (the reference seeds from the global generator)."""
    import numpy as np
    label_np = np.asarray(label).reshape(-1)
    pos = np.unique(label_np)
    rs = np.random.RandomState(seed)
    if len(pos) >= num_samples:
        sampled = np.sort(pos)
    else:
        neg = np.setdiff1d(np.arange(num_classes), pos)
        extra = rs.choice(neg, size=num_samples - len(pos), replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones((num_classes,), np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (jnp.asarray(remap[label_np], jnp.int32),
            jnp.asarray(sampled, jnp.int32))
