"""Activation functions (ref: python/paddle/nn/functional/activation.py, 28
classes' functional mirrors). All fuse into surrounding XLA computations."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op
from paddle_tpu.tensor._gen import _sample

__all__ = ["celu", "elu", "gelu", "glu", "hardshrink", "hardsigmoid",
           "hardswish", "hardtanh", "leaky_relu", "log_sigmoid", "log_softmax",
           "maxout", "mish", "prelu", "relu", "relu6", "rrelu", "selu", "silu",
           "sigmoid", "softmax", "softplus", "softshrink", "softsign",
           "swish", "tanhshrink", "thresholded_relu", "gumbel_softmax",
           "tanh"]


def celu(x, alpha=1.0):
    return jax.nn.celu(jnp.asarray(x), alpha)


def elu(x, alpha=1.0):
    return jax.nn.elu(jnp.asarray(x), alpha)


def gelu(x, approximate=False):
    return jax.nn.gelu(jnp.asarray(x), approximate=approximate)


def glu(x, axis=-1):
    return jax.nn.glu(jnp.asarray(x), axis=axis)


def hardshrink(x, threshold=0.5):
    x = jnp.asarray(x)
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5):
    x = jnp.asarray(x)
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hardswish(x):
    x = jnp.asarray(x)
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def hardtanh(x, min=-1.0, max=1.0):  # noqa: A002
    return jnp.clip(jnp.asarray(x), min, max)


def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(jnp.asarray(x), negative_slope)


def log_sigmoid(x):
    return jax.nn.log_sigmoid(jnp.asarray(x))


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(jnp.asarray(x), axis=axis)


def maxout(x, groups, axis=1):
    x = jnp.asarray(x)
    c = x.shape[axis]
    assert c % groups == 0
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def mish(x):
    x = jnp.asarray(x)
    return x * jnp.tanh(jax.nn.softplus(x))


def prelu(x, weight, data_format="NCHW"):
    x = jnp.asarray(x)
    w = jnp.asarray(weight)
    if w.size > 1:
        axis = 1 if data_format == "NCHW" else x.ndim - 1
        shape = [1] * x.ndim
        shape[axis] = w.size
        w = w.reshape(shape)
    return jnp.where(x >= 0, x, w * x)


def relu(x):
    return jax.nn.relu(jnp.asarray(x))


def relu6(x):
    return jax.nn.relu6(jnp.asarray(x))


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, key=None):
    x = jnp.asarray(x)
    if training:
        from paddle_tpu import random as pt_random
        k = key if key is not None else pt_random.next_key()
        a = jax.random.uniform(k, x.shape, x.dtype, lower, upper)
    else:
        a = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, a * x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    x = jnp.asarray(x)
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def silu(x):
    return jax.nn.silu(jnp.asarray(x))


swish = silu


def sigmoid(x):
    return jax.nn.sigmoid(jnp.asarray(x))


def softmax(x, axis=-1, dtype=None):
    x = jnp.asarray(x)
    if dtype is not None:
        from paddle_tpu.dtypes import to_dtype
        x = x.astype(to_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


def softplus(x, beta=1.0, threshold=20.0):
    x = jnp.asarray(x)
    return jnp.where(x * beta > threshold, x,
                     jax.nn.softplus(x * beta) / beta)


def softshrink(x, threshold=0.5):
    x = jnp.asarray(x)
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


def softsign(x):
    return jax.nn.soft_sign(jnp.asarray(x))


def tanh(x):
    return jnp.tanh(jnp.asarray(x))


def tanhshrink(x):
    x = jnp.asarray(x)
    return x - jnp.tanh(x)


def thresholded_relu(x, threshold=1.0):
    x = jnp.asarray(x)
    return jnp.where(x > threshold, x, 0.0)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, key=None):
    x = jnp.asarray(x)
    from paddle_tpu import random as pt_random
    k = key if key is not None else pt_random.next_key()
    g = jax.random.gumbel(k, x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
        y = jax.lax.stop_gradient(y_hard - y) + y  # straight-through estimator
    return y


# register numpy-oracled activations for OpTest sweeps
def _np_softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


for _name, _np in [
        ("relu", lambda x: np.maximum(x, 0)),
        ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
        ("tanh", np.tanh),
        ("softmax", _np_softmax),
        ("hardswish", lambda x: x * np.clip(x + 3, 0, 6) / 6),
        ("mish", lambda x: x * np.tanh(np.log1p(np.exp(x)))),
        ("silu", lambda x: x / (1 + np.exp(-x))),
        ("relu6", lambda x: np.clip(x, 0, 6)),
        ("log_softmax", lambda x: np.log(_np_softmax(x))),
        ("softsign", lambda x: x / (1 + np.abs(x))),
        ("tanhshrink", lambda x: x - np.tanh(x)),
        ("hardsigmoid", lambda x: np.clip(x / 6 + 0.5, 0, 1)),
        ("leaky_relu", lambda x: np.where(x >= 0, x, 0.01 * x)),
        ("elu", lambda x: np.where(x > 0, x, np.expm1(x))),
        ("selu", lambda x: 1.0507009873554805 * np.where(
            x > 0, x, 1.6732632423543772 * np.expm1(x)))]:
    register_op(f"nn.{_name}", globals()[_name], "activation", np_ref=_np,
                sample_args=(lambda: ((_sample("nonzero"),), {})))
