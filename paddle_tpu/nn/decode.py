"""Beam-search decoding (ref: python/paddle/nn/decode.py —
BeamSearchDecoder + dynamic_decode over RNNCellBase cells).

TPU-native: the decode loop is a ``lax.scan`` over ``max_step_num`` steps
with per-beam finished masks (static shapes; the reference's early-exit
while_op becomes mask arithmetic the compiler pipelines), and the beam
bookkeeping — log-prob accumulation, top-k over (beam × vocab), parent
backtrace via ``gather_tree`` — is plain vectorized jnp."""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.nn.module import Module

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder(Module):
    """≙ paddle.nn.BeamSearchDecoder: wraps a cell (h = cell(x, states))
    with an embedding fn and an output (logits) fn, expanding every
    input to ``beam_size`` hypotheses."""

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn=None, output_fn=None):
        super().__init__()
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- internals -----------------------------------------------------------
    def _embed(self, ids):
        if self.embedding_fn is None:
            return ids
        return self.embedding_fn(ids)

    def _logits(self, cell_out):
        return cell_out if self.output_fn is None else self.output_fn(
            cell_out)

    def _tile(self, t):
        """(B, ...) → (B*beam, ...) repeating each row beam_size times."""
        return jnp.repeat(jnp.asarray(t), self.beam_size, axis=0)

    def initialize(self, initial_states):
        b = jax.tree_util.tree_leaves(initial_states)[0].shape[0]
        states = jax.tree_util.tree_map(self._tile, initial_states)
        ids = jnp.full((b * self.beam_size,), self.start_token, jnp.int32)
        # beam 0 starts live, the rest at -inf so step 1 expands ONE beam
        lp = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1),
                        jnp.float32), b)
        finished = jnp.zeros((b * self.beam_size,), bool)
        return ids, states, lp, finished

    def step(self, ids, states, log_probs, finished):
        """One expand-and-prune beam step. Returns
        (token_ids, parent_idx, new_states, new_log_probs, new_finished)
        with everything shaped (B*beam, ...)."""
        K = self.beam_size
        out, new_states = self.cell(self._embed(ids), states)
        logits = self._logits(out)
        v = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        # finished beams only extend with end_token at zero cost
        keep = jnp.full((v,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[:, None], keep[None, :], step_lp)
        total = log_probs[:, None] + step_lp          # (B*K, V)
        b = total.shape[0] // K
        flat = total.reshape(b, K * v)
        top_lp, top_idx = lax.top_k(flat, K)           # (B, K)
        parent_in_beam = top_idx // v                  # which source beam
        token = (top_idx % v).astype(jnp.int32)
        parent = (parent_in_beam
                  + (jnp.arange(b) * K)[:, None]).reshape(-1)
        token = token.reshape(-1)
        new_lp = top_lp.reshape(-1)
        new_states = jax.tree_util.tree_map(lambda s: s[parent],
                                            new_states)
        new_finished = finished[parent] | (token == self.end_token)
        return token, parent, new_states, new_lp, new_finished


def dynamic_decode(decoder: BeamSearchDecoder, inits=None,
                   max_step_num: int = 32, **kwargs):
    """≙ paddle.nn.dynamic_decode: run the decoder to ``max_step_num``
    steps (static bound — the reference's dynamic while-loop exit becomes
    finished-mask arithmetic). Returns (ids (B, T, beam), final_lp
    (B, beam)) with beams backtraced through their parents."""
    from paddle_tpu.nn.functional.extension import gather_tree

    ids0, states, lp, finished = decoder.initialize(inits)
    K = decoder.beam_size
    b = ids0.shape[0] // K

    def body(carry, _):
        ids, states, lp, finished = carry
        token, parent, states, lp, finished = decoder.step(
            ids, states, lp, finished)
        return (token, states, lp, finished), (token, parent)

    (_, _, lp, finished), (tokens, parents) = lax.scan(
        body, (ids0, states, lp, finished), None, length=max_step_num)
    # (T, B*K) → (T, B, K) for the backtrace
    tokens = tokens.reshape(max_step_num, b, K)
    parents = parents.reshape(max_step_num, b, K) % K
    seqs = gather_tree(tokens, parents)            # (T, B, K)
    return jnp.transpose(seqs, (1, 0, 2)), lp.reshape(b, K)
