"""Pooling layer classes (ref: python/paddle/nn/layer/pooling.py — 15
classes)."""

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.module import Module

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D", "MaxUnPool2D", "MaxUnPool1D", "MaxUnPool3D"]


class _Pool(Module):
    fn = None

    def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
        super().__init__()
        kwargs.pop("name", None)
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kwargs = kwargs

    def forward(self, x):
        return getattr(F, self.fn)(x, self.kernel_size, self.stride,
                                   self.padding, **self.kwargs)


class AvgPool1D(_Pool):
    fn = "avg_pool1d"


class AvgPool2D(_Pool):
    fn = "avg_pool2d"


class AvgPool3D(_Pool):
    fn = "avg_pool3d"


class MaxPool1D(_Pool):
    fn = "max_pool1d"


class MaxPool2D(_Pool):
    fn = "max_pool2d"


class MaxPool3D(_Pool):
    fn = "max_pool3d"


class _AdaptivePool(Module):
    fn = None

    def __init__(self, output_size, **kwargs):
        super().__init__()
        kwargs.pop("name", None)
        self.output_size = output_size
        self.kwargs = kwargs

    def forward(self, x):
        return getattr(F, self.fn)(x, self.output_size)


class AdaptiveAvgPool1D(_AdaptivePool):
    fn = "adaptive_avg_pool1d"


class AdaptiveAvgPool2D(_AdaptivePool):
    fn = "adaptive_avg_pool2d"


class AdaptiveAvgPool3D(_AdaptivePool):
    fn = "adaptive_avg_pool3d"


class AdaptiveMaxPool1D(_AdaptivePool):
    fn = "adaptive_max_pool1d"


class AdaptiveMaxPool2D(_AdaptivePool):
    fn = "adaptive_max_pool2d"


class AdaptiveMaxPool3D(_AdaptivePool):
    fn = "adaptive_max_pool3d"


class MaxUnPool2D(Module):
    def __init__(self, kernel_size, stride=None, padding=0, output_size=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, *self.args)


class MaxUnPool1D(Module):
    def __init__(self, kernel_size, stride=None, padding=0, output_size=None,
                 data_format="NCL", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, *self.args)


class MaxUnPool3D(Module):
    def __init__(self, kernel_size, stride=None, padding=0, output_size=None,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, *self.args)
