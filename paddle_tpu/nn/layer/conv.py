"""Conv layers (ref: python/paddle/nn/layer/conv.py — 7 classes)."""

import numpy as np
import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.module import Module, Parameter

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose"]


def _t(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Module):
    def __init__(self, n, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        self.n = n
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _t(kernel_size, n)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.transpose = transpose
        self.output_padding = output_padding
        if transpose:
            wshape = (in_channels, out_channels // groups) + self.kernel_size
        else:
            wshape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = in_channels // groups * int(np.prod(self.kernel_size))
        winit = weight_attr if isinstance(weight_attr, I.Initializer) else \
            I.KaimingUniform(negative_slope=np.sqrt(5.0),
                             nonlinearity="leaky_relu")
        self.weight = Parameter(winit(wshape))
        if bias_attr is False:
            self.bias = None
        else:
            binit = bias_attr if isinstance(bias_attr, I.Initializer) else \
                I.Uniform(-1.0 / np.sqrt(fan_in), 1.0 / np.sqrt(fan_in))
            self.bias = Parameter(binit((out_channels,)))

    def forward(self, x):
        if self.transpose:
            fn = [F.conv1d_transpose, F.conv2d_transpose,
                  F.conv3d_transpose][self.n - 1]
            return fn(x, self.weight, self.bias, self.stride, self.padding,
                      self.output_padding, self.dilation, self.groups,
                      self.data_format)
        fn = [F.conv1d, F.conv2d, F.conv3d][self.n - 1]
        return fn(x, self.weight, self.bias, self.stride, self.padding,
                  self.dilation, self.groups, self.data_format)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)
