"""Norm layers (ref: python/paddle/nn/layer/norm.py — 13 classes).

BatchNorm running stats flow through the nn.Context (see module.py):
in a stateful training context the layer records its new running stats into
``ctx.updates`` and the caller applies them functionally — the XLA-visible
equivalent of the reference's in-place mutation."""

import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.module import (Buffer, Module, Parameter, current_context,
                                  is_training)

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "RMSNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "GroupNorm",
           "LocalResponseNorm", "SpectralNorm"]


class _BatchNormBase(Module):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            winit = weight_attr if isinstance(weight_attr, I.Initializer) \
                else I.Constant(1.0)
            self.weight = Parameter(winit((num_features,)))
        if bias_attr is False:
            self.bias = None
        else:
            binit = bias_attr if isinstance(bias_attr, I.Initializer) else \
                I.Constant(0.0)
            self.bias = Parameter(binit((num_features,)))
        self.register_buffer("_mean", jnp.zeros((num_features,)))
        self.register_buffer("_variance", jnp.ones((num_features,)))
        # path of this module inside the root model, filled lazily by
        # Context bookkeeping through named_modules at update-collection time
        self._stat_tag = name

    def forward(self, x):
        training = is_training() and not self.use_global_stats
        res = F.batch_norm(x, self._mean, self._variance, self.weight,
                           self.bias, training=training,
                           momentum=self.momentum, epsilon=self.epsilon,
                           data_format=self.data_format)
        if training:
            out, new_mean, new_var = res
            ctx = current_context()
            if ctx is not None:
                tag = self._stat_tag
                if tag is None:
                    tag = f"id{id(self) % 10**9}"  # untagged: call tag_paths()
                prefix = f"{tag}." if tag else ""
                ctx.record_update(f"{prefix}_mean", new_mean)
                ctx.record_update(f"{prefix}_variance", new_var)
            return out
        return res


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCL" else
                         data_format, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """ref: paddle.nn.SyncBatchNorm (cross-rank stats via NCCL allreduce).
    Under GSPMD, batch statistics computed inside a sharded jit program are
    already global — XLA inserts the cross-chip reductions — so SyncBatchNorm
    is BatchNorm; kept as a distinct class for API parity.

    convert_sync_batchnorm mirrors the reference helper."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Module):
    """ref: paddle.nn.LayerNorm → Pallas fused layer-norm on the TPU hot path."""

    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = Parameter(jnp.ones(self.normalized_shape))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = Parameter(jnp.zeros(self.normalized_shape))

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)


class RMSNorm(Module):
    """TPU-native extra (modern LLM block); see functional.rms_norm."""

    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        self.epsilon = epsilon
        self.weight = Parameter(jnp.ones((hidden_size,)))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class _InstanceNormBase(Module):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = Parameter(jnp.ones((num_features,)))
            self.bias = Parameter(jnp.zeros((num_features,)))

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               epsilon=self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class GroupNorm(Module):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = Parameter(jnp.ones((num_channels,)))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = Parameter(jnp.zeros((num_channels,)))

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias)


class LocalResponseNorm(Module):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Module):
    """ref: paddle.nn.SpectralNorm — power-iteration weight normalization."""

    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        self.axis = axis
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = weight_shape[axis]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != axis:
                w *= s
        self.register_buffer("weight_u", jnp.ones((h,)) / jnp.sqrt(h))
        self.register_buffer("weight_v", jnp.ones((w,)) / jnp.sqrt(w))
        self._stat_tag = name

    def forward(self, weight):
        from paddle_tpu.nn.module import current_context
        w = jnp.asarray(weight)
        w_mat = jnp.moveaxis(w, self.axis, 0).reshape(w.shape[self.axis], -1)
        u, v = self.weight_u, self.weight_v
        for _ in range(self.power_iters):
            v = w_mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.epsilon)
            u = w_mat @ v
            u = u / (jnp.linalg.norm(u) + self.epsilon)
        sigma = u @ w_mat @ v
        # persist power iteration across steps (ref mutates u/v in place;
        # here they flow out functionally like BatchNorm running stats)
        ctx = current_context()
        if ctx is not None:
            tag = self._stat_tag if self._stat_tag is not None \
                else f"id{id(self) % 10**9}"
            prefix = f"{tag}." if tag else ""
            ctx.record_update(f"{prefix}weight_u", u)
            ctx.record_update(f"{prefix}weight_v", v)
        return w / sigma
