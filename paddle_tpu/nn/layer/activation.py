"""Activation layer classes (ref: python/paddle/nn/layer/activation.py — 28
classes)."""

import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.module import Module, Parameter

__all__ = ["CELU", "ELU", "GELU", "GLU", "Hardshrink", "Hardsigmoid",
           "Hardswish", "Hardtanh", "LeakyReLU", "LogSigmoid", "LogSoftmax",
           "Maxout", "Mish", "PReLU", "ReLU", "ReLU6", "RReLU", "SELU",
           "Sigmoid", "Silu", "Softmax", "Softplus", "Softshrink",
           "Softsign", "Swish", "Tanh", "Tanhshrink", "ThresholdedReLU",
           "Softmax2D"]


def _mk(name, fname, params=()):
    def __init__(self, *args, **kwargs):
        Module.__init__(self)
        kwargs.pop("name", None)
        self._args = args
        self._kwargs = kwargs

    def forward(self, x):
        return getattr(F, fname)(x, *self._args, **self._kwargs)

    cls = type(name, (Module,), {"__init__": __init__, "forward": forward,
                                 "__doc__": f"ref: paddle.nn.{name}"})
    return cls


CELU = _mk("CELU", "celu")
ELU = _mk("ELU", "elu")
GELU = _mk("GELU", "gelu")
GLU = _mk("GLU", "glu")
Hardshrink = _mk("Hardshrink", "hardshrink")
Hardsigmoid = _mk("Hardsigmoid", "hardsigmoid")
Hardswish = _mk("Hardswish", "hardswish")
Hardtanh = _mk("Hardtanh", "hardtanh")
LeakyReLU = _mk("LeakyReLU", "leaky_relu")
LogSigmoid = _mk("LogSigmoid", "log_sigmoid")
LogSoftmax = _mk("LogSoftmax", "log_softmax")
Maxout = _mk("Maxout", "maxout")
Mish = _mk("Mish", "mish")
ReLU = _mk("ReLU", "relu")
ReLU6 = _mk("ReLU6", "relu6")
RReLU = _mk("RReLU", "rrelu")
SELU = _mk("SELU", "selu")
Sigmoid = _mk("Sigmoid", "sigmoid")
Silu = _mk("Silu", "silu")
Softmax = _mk("Softmax", "softmax")
Softplus = _mk("Softplus", "softplus")
Softshrink = _mk("Softshrink", "softshrink")
Softsign = _mk("Softsign", "softsign")
Swish = _mk("Swish", "swish")
Tanh = _mk("Tanh", "tanh")
Tanhshrink = _mk("Tanhshrink", "tanhshrink")
ThresholdedReLU = _mk("ThresholdedReLU", "thresholded_relu")


class PReLU(Module):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = Parameter(jnp.full((num_parameters,), init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class Softmax2D(Module):
    """ref: nn/layer/activation.py Softmax2D — softmax over the channel
    dim of (N, C, H, W) / (C, H, W) inputs."""

    def forward(self, x):
        return F.softmax(x, axis=-3)
