"""Recurrent layers (ref: python/paddle/nn/layer/rnn.py — 15 classes).

The reference dispatches to cuDNN RNN kernels; on TPU recurrence is a
``lax.scan`` over time whose per-step matmuls batch onto the MXU, and the
input projection (x @ W_ih for all timesteps) is hoisted out of the scan —
one big matmul instead of T small ones."""

import jax
import jax.numpy as jnp

from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.module import Module, Parameter, LayerList

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU", "RNNBase"]


class RNNCellBase(Module):
    def get_initial_states(self, batch, state_shape=None):
        raise NotImplementedError


def _uniform_std(hidden_size):
    return 1.0 / jnp.sqrt(hidden_size)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        std = float(1.0 / (hidden_size ** 0.5))
        init = I.Uniform(-std, std)
        self.weight_ih = Parameter(init((hidden_size, input_size)))
        self.weight_hh = Parameter(init((hidden_size, hidden_size)))
        self.bias_ih = Parameter(init((hidden_size,)))
        self.bias_hh = Parameter(init((hidden_size,)))

    def forward(self, inputs, states=None):
        h = states if states is not None else jnp.zeros(
            (inputs.shape[0], self.hidden_size), inputs.dtype)
        pre = inputs @ self.weight_ih.T + self.bias_ih + \
            h @ self.weight_hh.T + self.bias_hh
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        h = act(pre)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = float(1.0 / (hidden_size ** 0.5))
        init = I.Uniform(-std, std)
        self.weight_ih = Parameter(init((4 * hidden_size, input_size)))
        self.weight_hh = Parameter(init((4 * hidden_size, hidden_size)))
        self.bias_ih = Parameter(init((4 * hidden_size,)))
        self.bias_hh = Parameter(init((4 * hidden_size,)))

    def forward(self, inputs, states=None):
        if states is None:
            b = inputs.shape[0]
            states = (jnp.zeros((b, self.hidden_size), inputs.dtype),
                      jnp.zeros((b, self.hidden_size), inputs.dtype))
        h, c = states
        gates = inputs @ self.weight_ih.T + self.bias_ih + \
            h @ self.weight_hh.T + self.bias_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = float(1.0 / (hidden_size ** 0.5))
        init = I.Uniform(-std, std)
        self.weight_ih = Parameter(init((3 * hidden_size, input_size)))
        self.weight_hh = Parameter(init((3 * hidden_size, hidden_size)))
        self.bias_ih = Parameter(init((3 * hidden_size,)))
        self.bias_hh = Parameter(init((3 * hidden_size,)))

    def forward(self, inputs, states=None):
        h = states if states is not None else jnp.zeros(
            (inputs.shape[0], self.hidden_size), inputs.dtype)
        gi = inputs @ self.weight_ih.T + self.bias_ih
        gh = h @ self.weight_hh.T + self.bias_hh
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(i_r + h_r)
        z = jax.nn.sigmoid(i_z + h_z)
        n = jnp.tanh(i_n + r * h_n)
        h = (1 - z) * n + z * h
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Module):
    """Runs a cell over time with lax.scan (ref: paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = jnp.asarray(inputs)
        if not self.time_major:
            x = jnp.swapaxes(x, 0, 1)  # (T, B, F)
        if self.is_reverse:
            x = jnp.flip(x, axis=0)
        b = x.shape[1]
        if initial_states is None:
            if isinstance(self.cell, LSTMCell):
                initial_states = (
                    jnp.zeros((b, self.cell.hidden_size), x.dtype),
                    jnp.zeros((b, self.cell.hidden_size), x.dtype))
            else:
                initial_states = jnp.zeros((b, self.cell.hidden_size),
                                           x.dtype)
        cell = self.cell

        def step(carry, x_t):
            out, new_states = cell(x_t, carry)
            return new_states, out

        final, outs = jax.lax.scan(step, initial_states, x)
        if self.is_reverse:
            outs = jnp.flip(outs, axis=0)
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, final


class BiRNN(Module):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states = initial_states or (None, None)
        out_f, st_f = self.fw(inputs, states[0])
        out_b, st_b = self.bw(inputs, states[1])
        return jnp.concatenate([out_f, out_b], axis=-1), (st_f, st_b)


class _RNNBase(Module):
    CELL = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirectional else 1
        layers = []
        for l in range(num_layers):
            in_size = input_size if l == 0 else hidden_size * num_dir
            kwargs = {}
            if activation is not None and self.CELL is SimpleRNNCell:
                kwargs["activation"] = activation
            if self.bidirectional:
                layers.append(BiRNN(self.CELL(in_size, hidden_size, **kwargs),
                                    self.CELL(in_size, hidden_size, **kwargs),
                                    time_major))
            else:
                layers.append(RNN(self.CELL(in_size, hidden_size, **kwargs),
                                  time_major=time_major))
        self.layers = LayerList(layers)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        out = inputs
        finals = []
        for i, layer in enumerate(self.layers):
            init_i = None
            if initial_states is not None:
                init_i = jax.tree_util.tree_map(
                    lambda s: s[i], initial_states)
            out, st = layer(out, init_i)
            finals.append(st)
            if self.dropout and i < len(self.layers) - 1:
                from paddle_tpu.nn import functional as F
                out = F.dropout(out, self.dropout)
        states = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *finals)
        return out, states


RNNBase = _RNNBase  # public alias (ref: paddle.nn.layer.rnn.RNNBase)


class SimpleRNN(_RNNBase):
    CELL = SimpleRNNCell


class LSTM(_RNNBase):
    CELL = LSTMCell


class GRU(_RNNBase):
    CELL = GRUCell
