"""Transformer layers (ref: python/paddle/nn/layer/transformer.py — 6
classes). The attention core dispatches to the Pallas flash-attention kernel
on TPU (ref contrast: fused_attention_op.cu / fused_multi_transformer_op.cu)."""

from typing import Optional

import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.common import Dropout, Linear
from paddle_tpu.nn.layer.norm import LayerNorm
from paddle_tpu.nn.module import Module, LayerList

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "TransformerDecoderLayer",
           "TransformerDecoder", "Transformer"]


class MultiHeadAttention(Module):
    """ref: paddle.nn.MultiHeadAttention (layer/transformer.py)."""

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split(self, x):
        b, s, _ = x.shape
        return x.reshape(b, s, self.num_heads, self.head_dim)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        key = query if key is None else key
        value = query if value is None else value
        q = self._split(self.q_proj(query))
        k = self._split(self.k_proj(key))
        v = self._split(self.v_proj(value))
        if cache is not None:
            k = jnp.concatenate([cache[0], k], axis=1)
            v = jnp.concatenate([cache[1], v], axis=1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout)
        b, s = out.shape[:2]
        out = self.out_proj(out.reshape(b, s, self.embed_dim))
        if cache is not None:
            return out, (k, v)
        return out

    def gen_cache(self, key, value=None, type=None):  # noqa: A002
        k = self._split(self.k_proj(key))
        v = self._split(self.v_proj(value if value is not None else key))
        return (k, v)


class TransformerEncoderLayer(Module):
    """ref: paddle.nn.TransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, attn_dropout if attn_dropout is not None
            else dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None
                                   else dropout)
        self.activation = activation

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, attn_mask=src_mask)
        else:
            src, cache = self.self_attn(src, attn_mask=src_mask, cache=cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        act = getattr(F, self.activation)
        src = self.linear2(self.act_dropout(act(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)


class TransformerEncoder(Module):
    def __init__(self, encoder_layer_fn, num_layers, norm=None):
        super().__init__()
        if isinstance(encoder_layer_fn, Module):
            # reference signature: prototype layer replicated num_layers times
            import copy
            proto = encoder_layer_fn
            layers = [proto]
            for _ in range(num_layers - 1):
                layers.append(copy.deepcopy(proto))
        else:
            layers = [encoder_layer_fn() for _ in range(num_layers)]
        self.layers = LayerList(layers)
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Module):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, ad,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, ad,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None
                                   else dropout)
        self.activation = activation

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, attn_mask=tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        act = getattr(F, self.activation)
        tgt = self.linear2(self.act_dropout(act(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Module):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        if isinstance(decoder_layer, Module):
            import copy
            layers = [decoder_layer]
            for _ in range(num_layers - 1):
                layers.append(copy.deepcopy(decoder_layer))
        else:
            layers = [decoder_layer() for _ in range(num_layers)]
        self.layers = LayerList(layers)
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask=tgt_mask,
                        memory_mask=memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Module):
    """ref: paddle.nn.Transformer."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            self.encoder = TransformerEncoder(
                lambda: TransformerEncoderLayer(
                    d_model, nhead, dim_feedforward, dropout, activation,
                    attn_dropout, act_dropout, normalize_before, weight_attr,
                    bias_attr),
                num_encoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            self.decoder = TransformerDecoder(
                lambda: TransformerDecoderLayer(
                    d_model, nhead, dim_feedforward, dropout, activation,
                    attn_dropout, act_dropout, normalize_before, weight_attr,
                    bias_attr),
                num_decoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        return jnp.tril(jnp.ones((length, length), jnp.bool_))
