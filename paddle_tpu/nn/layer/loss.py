"""Loss layer classes (ref: python/paddle/nn/layer/loss.py — 26 classes)."""

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.module import Module

__all__ = ["CrossEntropyLoss", "NLLLoss", "BCELoss", "BCEWithLogitsLoss",
           "MSELoss", "L1Loss", "SmoothL1Loss", "HuberLoss", "KLDivLoss",
           "MarginRankingLoss", "CosineEmbeddingLoss", "CTCLoss",
           "HingeEmbeddingLoss", "TripletMarginLoss", "SoftMarginLoss",
           "MultiLabelSoftMarginLoss", "PoissonNLLLoss", "MultiMarginLoss",
           "TripletMarginWithDistanceLoss", "HSigmoidLoss"]


class _Loss(Module):
    fn = None

    def __init__(self, **kwargs):
        super().__init__()
        kwargs.pop("name", None)
        self.kwargs = kwargs

    def forward(self, *args):
        return getattr(F, self.fn)(*args, **self.kwargs)


class CrossEntropyLoss(_Loss):
    fn = "cross_entropy"


class NLLLoss(_Loss):
    fn = "nll_loss"


class BCELoss(_Loss):
    fn = "binary_cross_entropy"


class BCEWithLogitsLoss(_Loss):
    fn = "binary_cross_entropy_with_logits"


class MSELoss(_Loss):
    fn = "mse_loss"


class L1Loss(_Loss):
    fn = "l1_loss"


class SmoothL1Loss(_Loss):
    fn = "smooth_l1_loss"


class HuberLoss(_Loss):
    fn = "huber_loss"


class KLDivLoss(_Loss):
    fn = "kl_div"


class MarginRankingLoss(_Loss):
    fn = "margin_ranking_loss"


class CosineEmbeddingLoss(_Loss):
    fn = "cosine_embedding_loss"


class CTCLoss(_Loss):
    fn = "ctc_loss"


class HingeEmbeddingLoss(_Loss):
    fn = "hinge_embedding_loss"


class TripletMarginLoss(_Loss):
    fn = "triplet_margin_loss"


class SoftMarginLoss(_Loss):
    fn = "soft_margin_loss"


class MultiLabelSoftMarginLoss(_Loss):
    fn = "multi_label_soft_margin_loss"


class PoissonNLLLoss(_Loss):
    fn = "poisson_nll_loss"


class MultiMarginLoss(_Loss):
    fn = "multi_margin_loss"


class TripletMarginWithDistanceLoss(_Loss):
    fn = "triplet_margin_with_distance_loss"


class HSigmoidLoss(Module):
    """Hierarchical sigmoid head (ref: nn/layer/loss.py HSigmoidLoss →
    hsigmoid_loss functional): owns the (num_classes-1, D) internal-node
    weights of the default complete binary tree."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.nn.module import Parameter
        self.num_classes = num_classes
        n_nodes = num_classes - 1
        rs = np.random.RandomState(0)
        bound = float(np.sqrt(6.0 / (feature_size + n_nodes)))
        self.weight = Parameter(jnp.asarray(
            rs.uniform(-bound, bound, (n_nodes, feature_size)),
            jnp.float32))
        self.bias = (None if bias_attr is False
                     else Parameter(jnp.zeros((n_nodes,), jnp.float32)))

    def forward(self, input, label, path_table=None, path_code=None):  # noqa: A002
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table=path_table,
                               path_code=path_code)
