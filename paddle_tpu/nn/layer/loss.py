"""Loss layer classes (ref: python/paddle/nn/layer/loss.py — 26 classes)."""

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.module import Module

__all__ = ["CrossEntropyLoss", "NLLLoss", "BCELoss", "BCEWithLogitsLoss",
           "MSELoss", "L1Loss", "SmoothL1Loss", "HuberLoss", "KLDivLoss",
           "MarginRankingLoss", "CosineEmbeddingLoss", "CTCLoss",
           "HingeEmbeddingLoss", "TripletMarginLoss", "SoftMarginLoss",
           "MultiLabelSoftMarginLoss", "PoissonNLLLoss"]


class _Loss(Module):
    fn = None

    def __init__(self, **kwargs):
        super().__init__()
        kwargs.pop("name", None)
        self.kwargs = kwargs

    def forward(self, *args):
        return getattr(F, self.fn)(*args, **self.kwargs)


class CrossEntropyLoss(_Loss):
    fn = "cross_entropy"


class NLLLoss(_Loss):
    fn = "nll_loss"


class BCELoss(_Loss):
    fn = "binary_cross_entropy"


class BCEWithLogitsLoss(_Loss):
    fn = "binary_cross_entropy_with_logits"


class MSELoss(_Loss):
    fn = "mse_loss"


class L1Loss(_Loss):
    fn = "l1_loss"


class SmoothL1Loss(_Loss):
    fn = "smooth_l1_loss"


class HuberLoss(_Loss):
    fn = "huber_loss"


class KLDivLoss(_Loss):
    fn = "kl_div"


class MarginRankingLoss(_Loss):
    fn = "margin_ranking_loss"


class CosineEmbeddingLoss(_Loss):
    fn = "cosine_embedding_loss"


class CTCLoss(_Loss):
    fn = "ctc_loss"


class HingeEmbeddingLoss(_Loss):
    fn = "hinge_embedding_loss"


class TripletMarginLoss(_Loss):
    fn = "triplet_margin_loss"


class SoftMarginLoss(_Loss):
    fn = "soft_margin_loss"


class MultiLabelSoftMarginLoss(_Loss):
    fn = "multi_label_soft_margin_loss"


class PoissonNLLLoss(_Loss):
    fn = "poisson_nll_loss"
