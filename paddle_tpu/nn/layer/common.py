"""Common layers (ref: python/paddle/nn/layer/common.py — 18 classes)."""

import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.module import Module, Parameter, current_context

__all__ = ["Linear", "Identity", "Dropout", "Dropout2D", "Dropout3D",
           "AlphaDropout", "Embedding", "Flatten", "Upsample",
           "UpsamplingNearest2D", "UpsamplingBilinear2D", "Pad1D", "Pad2D",
           "Pad3D", "ZeroPad2D", "CosineSimilarity", "Bilinear", "Unfold",
           "Fold", "PixelShuffle", "PixelUnshuffle", "ChannelShuffle",
           "LinearLowRank", "PairwiseDistance"]


class Linear(Module):
    """ref: paddle.nn.Linear (weight layout (in, out))."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None, dtype=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        winit = weight_attr if isinstance(weight_attr, I.Initializer) else \
            I.default_weight_init()
        self.weight = Parameter(winit((in_features, out_features),
                                      dtype or jnp.float32))
        if bias_attr is False:
            self.bias = None
        else:
            binit = bias_attr if isinstance(bias_attr, I.Initializer) else \
                I.default_bias_init()
            self.bias = Parameter(binit((out_features,), dtype or jnp.float32))

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class LinearLowRank(Module):
    """LoRA-style factored linear — TPU-native extra (no reference analog)."""

    def __init__(self, in_features, out_features, rank, alpha=1.0):
        super().__init__()
        self.alpha = alpha
        self.rank = rank
        self.a = Parameter(I.KaimingUniform()((in_features, rank)))
        self.b = Parameter(I.Constant(0.0)((rank, out_features)))

    def forward(self, x):
        return (x @ self.a) @ self.b * (self.alpha / self.rank)


class Identity(Module):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Dropout(Module):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, mode=self.mode)


class Dropout2D(Module):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, data_format=self.data_format)


class Dropout3D(Module):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, data_format=self.data_format)


class AlphaDropout(Module):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p)


class Embedding(Module):
    """ref: paddle.nn.Embedding → phi embedding kernel (gather on TPU)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        winit = weight_attr if isinstance(weight_attr, I.Initializer) else \
            I.Normal(0.0, 1.0)
        w = winit((num_embeddings, embedding_dim))
        if padding_idx is not None:
            w = w.at[padding_idx].set(0.0)
        self.weight = Parameter(w)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)


class Flatten(Module):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from paddle_tpu.tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Module):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest",
                         data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True,
                         data_format=data_format)


class _PadNd(Module):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value,
                     self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(_PadNd):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Module):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Module):
    """ref: paddle.nn.Bilinear — out = x1 @ W @ x2 + b."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        winit = weight_attr if isinstance(weight_attr, I.Initializer) else \
            I.default_weight_init()
        self.weight = Parameter(
            winit((out_features, in1_features, in2_features)))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = Parameter(I.Constant(0.0)((out_features,)))

    def forward(self, x1, x2):
        out = jnp.einsum("bi,oij,bj->bo", jnp.asarray(x1), self.weight,
                         jnp.asarray(x2))
        if self.bias is not None:
            out = out + self.bias
        return out


class Unfold(Module):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Module):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


class PixelShuffle(Module):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Module):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Module):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class PairwiseDistance(Module):
    """ref: nn/layer/distance.py PairwiseDistance → p_norm(x - y)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)
