"""Module system: pytree-registered layers with torch/paddle ergonomics and
pure-functional semantics.

Reference analog: ``paddle.nn.Layer`` (python/paddle/fluid/dygraph/layers.py)
— attribute registration of parameters/sub-layers, named traversal,
state_dict. Differences forced (and enabled) by TPU/XLA:

- A Module IS a pytree: ``jax.jit``/``grad``/``vmap`` consume it directly.
  Arrays (parameters/buffers) are leaves; everything else is static aux data
  that keys the jit cache.
- Forward is pure. Stateful bits (dropout RNG, batch-norm running stats,
  training flag) thread through an explicit :class:`Context` entered with
  ``nn.stateful(...)``; updated buffers are collected functionally instead of
  mutated in place (the reference mutates, which XLA tracing cannot see).
- ``split_params``/``merge_params`` give the canonical train-step pattern:
  optimizers operate on a flat dict of trainable arrays.
"""

import contextlib
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp


class Parameter:
    """Marker wrapper used at assignment time: ``self.w = Parameter(arr)``
    registers ``w`` as trainable and stores the raw array. (ref:
    fluid/dygraph/layers.py parameter registration via ParamBase)."""

    __slots__ = ("value", "trainable")

    def __init__(self, value, trainable: bool = True):
        self.value = jnp.asarray(value)
        self.trainable = trainable


class Buffer:
    """Non-trainable state (running stats etc.); ref: Layer.register_buffer."""

    __slots__ = ("value", "persistable")

    def __init__(self, value, persistable: bool = True):
        self.value = jnp.asarray(value)
        self.persistable = persistable


def _is_array(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


def _freeze_static(v):
    """Reduce a static attribute to a stable hashable form. Must satisfy:
    values that compare equal in _Static.__eq__ produce equal frozen forms
    (so hash obeys the eq contract); the id()-dependent repr of arbitrary
    objects is never used (it would silently fragment the jit cache —
    VERDICT r1 weak 8)."""
    if isinstance(v, (np.ndarray, jax.Array)):
        a = np.asarray(v)
        return ("__arr__", a.shape, str(a.dtype), a.tobytes())
    if isinstance(v, (list, tuple)):
        return ("__seq__",) + tuple(_freeze_static(x) for x in v)
    if isinstance(v, dict):
        return ("__map__",) + tuple(
            sorted((k, _freeze_static(x)) for k, x in v.items()))
    if isinstance(v, set):
        return ("__set__",) + tuple(sorted(map(repr, sorted(v, key=repr))))
    try:
        hash(v)
        return v
    except TypeError:
        # coarse but contract-safe: unhashable exotic objects hash by type
        # only; _Static.__eq__ still does the real comparison
        return ("__unhash__", type(v).__qualname__)


class _Static:
    """Hashable wrapper for a module's static attributes (jit cache key)."""

    __slots__ = ("items", "_hash")

    def __init__(self, items: Tuple[Tuple[str, Any], ...]):
        self.items = items
        self._hash = hash(tuple(
            (k, _freeze_static(v)) for k, v in items))

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        if not isinstance(other, _Static):
            return False
        if len(self.items) != len(other.items):
            return False
        for (ka, va), (kb, vb) in zip(self.items, other.items):
            if ka != kb:
                return False
            if isinstance(va, (np.ndarray, jax.Array)) or isinstance(
                    vb, (np.ndarray, jax.Array)):
                if not isinstance(va, (np.ndarray, jax.Array)) \
                        or not isinstance(vb, (np.ndarray, jax.Array)) \
                        or np.shape(va) != np.shape(vb):
                    return False
                if not bool(np.all(np.asarray(va) == np.asarray(vb))):
                    return False
                continue
            try:
                eq = va == vb
            except Exception:
                return False
            if isinstance(eq, (np.ndarray, jax.Array)):
                eq = bool(np.all(eq))
            if not eq:
                return False
        return True


class Module:
    """Base layer class. Subclasses define ``__init__`` (register params via
    ``Parameter``/``create_parameter`` and sub-modules by attribute
    assignment) and ``forward``."""

    def __init__(self):
        d = object.__setattr__
        d(self, "_params", set())
        d(self, "_buffers", set())
        d(self, "_non_trainable", set())
        d(self, "_non_persistable", set())
        d(self, "_modules", set())

    # -- attribute registration ------------------------------------------------
    def __setattr__(self, name, value):
        if not hasattr(self, "_params"):
            # subclass forgot super().__init__; bootstrap silently
            Module.__init__(self)
        self._params.discard(name)
        self._buffers.discard(name)
        self._modules.discard(name)
        self._non_trainable.discard(name)
        self._non_persistable.discard(name)
        if isinstance(value, Parameter):
            self._params.add(name)
            if not value.trainable:
                self._non_trainable.add(name)
            value = value.value
        elif isinstance(value, Buffer):
            self._buffers.add(name)
            if not value.persistable:
                self._non_persistable.add(name)
            value = value.value
        elif isinstance(value, Module):
            self._modules.add(name)
        elif isinstance(value, (list, tuple)) and value and all(
                isinstance(v, Module) for v in value):
            # bare list of modules → auto-wrap is intrusive; register names
            self._modules.add(name)
        object.__setattr__(self, name, value)

    def __delattr__(self, name):
        self._params.discard(name)
        self._buffers.discard(name)
        self._modules.discard(name)
        object.__delattr__(self, name)

    # -- torch/paddle-style helpers -------------------------------------------
    def create_parameter(self, shape, dtype=None, init=None,
                         trainable: bool = True):
        from paddle_tpu.dtypes import get_default_dtype
        from paddle_tpu.nn import initializer
        dtype = dtype or get_default_dtype()
        init = init or initializer.XavierUniform()
        return Parameter(init(shape, dtype), trainable=trainable)

    def register_buffer(self, name, value, persistable=True):
        setattr(self, name, Buffer(value, persistable))

    def add_sublayer(self, name, layer):
        setattr(self, name, layer)
        return layer

    # -- traversal -------------------------------------------------------------
    def named_children(self) -> Iterator[Tuple[str, "Module"]]:
        for name in sorted(self._modules):
            v = getattr(self, name)
            if isinstance(v, Module):
                yield name, v
            else:  # list/tuple of modules
                for i, m in enumerate(v):
                    yield f"{name}.{i}", m

    def children(self):
        for _, m in self.named_children():
            yield m

    def named_modules(self, prefix="") -> Iterator[Tuple[str, "Module"]]:
        yield prefix, self
        for name, child in self.named_children():
            sub = f"{prefix}.{name}" if prefix else name
            yield from child.named_modules(sub)

    def sublayers(self, include_self=False):
        mods = [m for _, m in self.named_modules()]
        return mods if include_self else mods[1:]

    def named_parameters(self, prefix="", include_non_trainable=True):
        for path, mod in self.named_modules(prefix):
            for name in sorted(mod._params):
                if not include_non_trainable and name in mod._non_trainable:
                    continue
                full = f"{path}.{name}" if path else name
                yield full, getattr(mod, name)

    def parameters(self, include_non_trainable=True):
        return [v for _, v in
                self.named_parameters(include_non_trainable=include_non_trainable)]

    def named_buffers(self, prefix="", include_non_persistable=True):
        for path, mod in self.named_modules(prefix):
            for name in sorted(mod._buffers):
                if not include_non_persistable and \
                        name in getattr(mod, "_non_persistable", ()):
                    continue
                full = f"{path}.{name}" if path else name
                yield full, getattr(mod, name)

    def buffers(self):
        return [v for _, v in self.named_buffers()]

    # -- state dict ------------------------------------------------------------
    def state_dict(self) -> Dict[str, jax.Array]:
        out = dict(self.named_parameters())
        out.update(dict(self.named_buffers(include_non_persistable=False)))
        return out

    def set_state_dict(self, state: Dict[str, Any], strict: bool = True):
        own = self.state_dict()
        missing = [k for k in own if k not in state]
        unexpected = [k for k in state if k not in own]
        if strict and (missing or unexpected):
            raise KeyError(f"state_dict mismatch: missing={missing}, "
                           f"unexpected={unexpected}")
        for k, v in state.items():
            if k in own:
                self._set_by_path(k, jnp.asarray(v))
        return self

    load_dict = set_state_dict
    load_state_dict = set_state_dict

    def _get_module_by_path(self, path: str):
        mod = self
        parts = path.split(".")
        for p in parts:
            v = getattr(mod, p) if not p.isdigit() else mod[int(p)]
            mod = v
        return mod

    def _set_by_path(self, path: str, value):
        parts = path.split(".")
        mod = self
        for p in parts[:-1]:
            mod = getattr(mod, p) if not p.isdigit() else mod[int(p)]
        leaf = parts[-1]
        # bypass re-registration (kind is already recorded)
        object.__setattr__(mod, leaf, value)

    # -- functional split/merge ------------------------------------------------
    def split_params(self):
        """Return (trainable_params, everything_else_dict). The canonical
        train-step pattern:

            params, _ = model.split_params()
            def loss_fn(params, batch):
                m = model.merge_params(params)
                ...
        """
        params = dict(self.named_parameters(include_non_trainable=False))
        buffers = {k: v for k, v in self.named_parameters()
                   if k not in params}
        buffers.update(self.named_buffers())
        return params, buffers

    def merge_params(self, params: Dict[str, jax.Array]) -> "Module":
        """Return a copy of self with ``params`` swapped in (pure)."""
        new = jax.tree_util.tree_map(lambda x: x, self)  # structural copy
        for k, v in params.items():
            new._set_by_path(k, v)
        return new

    def apply_updates(self, updates: Dict[str, jax.Array]) -> "Module":
        """Pure buffer update (e.g. BN running stats collected by Context)."""
        return self.merge_params(updates)

    # -- train/eval flags (thread through Context) ----------------------------
    def train(self):
        """Per-MODULE mode (≙ reference Layer.train, recursive), not a
        process global: two models in one process can be in different modes
        (VERDICT r1 weak 7). An active nn.stateful Context still wins."""
        for m in self.sublayers(include_self=True):
            object.__setattr__(m, "_training_mode", True)
        return self

    def eval(self):
        for m in self.sublayers(include_self=True):
            object.__setattr__(m, "_training_mode", False)
        return self

    @property
    def training(self):
        t = getattr(self, "_training_mode", None)
        return is_training() if t is None else t

    def tag_paths(self):
        """Stamp each submodule with its dotted path (used by layers that
        record functional buffer updates into the Context, e.g. BatchNorm).
        Called automatically by the high-level Trainer/Model APIs; call once
        after construction when using raw nn.stateful contexts."""
        for path, mod in self.named_modules():
            object.__setattr__(mod, "_stat_tag", path)
        return self

    def apply(self, fn):
        for m in self.sublayers(include_self=True):
            fn(m)
        return self

    def astype(self, dtype):
        """Cast all floating params/buffers (ref: Layer.to / amp O2 cast)."""
        from paddle_tpu.dtypes import to_dtype, is_floating
        dt = to_dtype(dtype)
        new_state = {}
        for k, v in self.state_dict().items():
            if is_floating(v.dtype):
                new_state[k] = jnp.asarray(v, dt)
        return self.merge_params(new_state)

    to = astype

    # -- call ------------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        t = getattr(self, "_training_mode", None)
        if t is None:
            return self.forward(*args, **kwargs)
        # scope this module's train()/eval() mode over the call so layers
        # (and functionals like F.dropout) resolve it via is_training()
        prev = getattr(_default_mode, "module_override", None)
        _default_mode.module_override = t
        try:
            return self.forward(*args, **kwargs)
        finally:
            _default_mode.module_override = prev

    # -- pytree protocol -------------------------------------------------------
    def _tree_keys(self):
        dyn = sorted(self._params | self._buffers | self._modules)
        return dyn

    def tree_flatten(self):
        dyn_keys = self._tree_keys()
        children = tuple(getattr(self, k) for k in dyn_keys)
        reserved = set(dyn_keys) | {"_params", "_buffers", "_modules",
                                    "_non_trainable", "_non_persistable"}
        static_items = tuple(sorted(
            (k, v) for k, v in self.__dict__.items() if k not in reserved))
        meta = (tuple(dyn_keys), tuple(sorted(self._params)),
                tuple(sorted(self._buffers)), tuple(sorted(self._modules)),
                tuple(sorted(self._non_trainable)),
                tuple(sorted(self._non_persistable)))
        return children, (meta, _Static(static_items))

    @classmethod
    def tree_unflatten(cls, aux, children):
        meta, static = aux
        (dyn_keys, params, buffers, modules, non_trainable,
         non_persistable) = meta
        obj = object.__new__(cls)
        object.__setattr__(obj, "_params", set(params))
        object.__setattr__(obj, "_buffers", set(buffers))
        object.__setattr__(obj, "_modules", set(modules))
        object.__setattr__(obj, "_non_trainable", set(non_trainable))
        object.__setattr__(obj, "_non_persistable", set(non_persistable))
        for k, v in zip(dyn_keys, children):
            object.__setattr__(obj, k, v)
        for k, v in static.items:
            object.__setattr__(obj, k, v)
        return obj

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        jax.tree_util.register_pytree_node(
            cls,
            lambda m: m.tree_flatten(),
            lambda aux, ch, _cls=cls: _cls.tree_unflatten(aux, ch))

    def __repr__(self):
        lines = [type(self).__name__ + "("]
        for name, child in self.named_children():
            head = repr(child).splitlines()
            body = "\n".join("  " + h for h in head)
            lines.append(f"  ({name}): {body.strip()}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else type(self).__name__ + "()"


jax.tree_util.register_pytree_node(
    Module, lambda m: m.tree_flatten(),
    lambda aux, ch: Module.tree_unflatten(aux, ch))


# ---------------------------------------------------------------------------
# Execution context: training flag, RNG, functional buffer updates.
# ---------------------------------------------------------------------------

class _Mode(threading.local):
    training = False


_default_mode = _Mode()
_ctx_stack = threading.local()


class Context:
    """Threaded execution state for one forward pass (ref contrast: the
    reference mutates layer attributes / global tracer state; under XLA
    tracing state must flow functionally)."""

    def __init__(self, training: bool = False, rng: Optional[jax.Array] = None):
        self.training = training
        self._rng = rng
        self._rng_counter = 0
        self.updates: Dict[str, jax.Array] = {}
        self._path_stack: List[str] = []

    def next_key(self, salt: int = 0) -> jax.Array:
        if self._rng is None:
            from paddle_tpu import random as pt_random
            return pt_random.next_key()
        self._rng_counter += 1
        return jax.random.fold_in(self._rng, self._rng_counter * 1000003 + salt)

    def record_update(self, path: str, value):
        self.updates[path] = value


def current_context() -> Optional[Context]:
    return getattr(_ctx_stack, "ctx", None)


def is_training() -> bool:
    """Resolution order: active stateful Context (hapi/fit loops) → the
    enclosing module's train()/eval() mode → process default (False)."""
    ctx = current_context()
    if ctx is not None:
        return ctx.training
    override = getattr(_default_mode, "module_override", None)
    if override is not None:
        return override
    return _default_mode.training


@contextlib.contextmanager
def stateful(training: bool = False, rng: Optional[jax.Array] = None):
    """Enter an execution context::

        with nn.stateful(training=True, rng=key) as ctx:
            loss = loss_fn(model(x), y)
        model = model.apply_updates(ctx.updates)
    """
    ctx = Context(training=training, rng=rng)
    prev = getattr(_ctx_stack, "ctx", None)
    _ctx_stack.ctx = ctx
    try:
        yield ctx
    finally:
        _ctx_stack.ctx = prev


# ---------------------------------------------------------------------------
# Containers
# ---------------------------------------------------------------------------

class Sequential(Module):
    """ref: paddle.nn.Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            layers = [m for _, m in layers[0]]
        self._n = len(layers)
        for i, l in enumerate(layers):
            setattr(self, f"layer_{i}", l)

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return Sequential(*[self[j] for j in range(*i.indices(self._n))])
        if not -self._n <= i < self._n:
            raise IndexError(f"index {i} out of range for Sequential of "
                             f"length {self._n}")
        return getattr(self, f"layer_{i % self._n}")

    def __iter__(self):
        return (self[i] for i in range(self._n))

    def forward(self, x):
        for i in range(self._n):
            x = self[i](x)
        return x


class LayerList(Module):
    """ref: paddle.nn.LayerList."""

    def __init__(self, layers=None):
        super().__init__()
        self._n = 0
        for l in (layers or []):
            self.append(l)

    def append(self, layer):
        setattr(self, f"item_{self._n}", layer)
        self._n += 1
        return self

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return LayerList([self[j] for j in range(*i.indices(self._n))])
        if not -self._n <= i < self._n:
            raise IndexError(f"index {i} out of range for LayerList of "
                             f"length {self._n}")
        return getattr(self, f"item_{i % self._n}")

    def __iter__(self):
        return (self[i] for i in range(self._n))


class ParameterList(Module):
    """ref: paddle.nn.ParameterList — indexable parameter container."""

    def __init__(self, parameters=None):
        super().__init__()
        self._n = 0
        for p in (parameters or []):
            self.append(p)

    def append(self, parameter):
        if not isinstance(parameter, Parameter):
            parameter = Parameter(parameter)
        setattr(self, f"p_{self._n}", parameter)
        self._n += 1
        return self

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        if not -self._n <= i < self._n:
            raise IndexError(f"index {i} out of range for ParameterList "
                             f"of length {self._n}")
        return getattr(self, f"p_{i % self._n}")

    def __iter__(self):
        return (self[i] for i in range(self._n))


class LayerDict(Module):
    """ref: paddle.nn.LayerDict."""

    def __init__(self, sublayers=None):
        super().__init__()
        self._keys: Tuple[str, ...] = ()
        for k, v in (sublayers or {}).items():
            self[k] = v

    def __setitem__(self, key, layer):
        setattr(self, f"kv_{key}", layer)
        if key not in self._keys:
            object.__setattr__(self, "_keys", self._keys + (key,))

    def __getitem__(self, key):
        return getattr(self, f"kv_{key}")

    def keys(self):
        return list(self._keys)

    def items(self):
        return [(k, self[k]) for k in self._keys]

    def values(self):
        return [self[k] for k in self._keys]

    def __len__(self):
        return len(self._keys)

    def __iter__(self):
        return iter(self._keys)
