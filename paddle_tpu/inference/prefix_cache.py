"""Prefix (radix) caching over the paged KV pool: identical prompt
prefixes are prefilled ONCE and their pages shared read-only across
requests (the vLLM "automatic prefix caching" memory model; no reference
analog — the reference's fused_multi_transformer owns one contiguous
CacheKV per sequence and cannot share rows between sequences).

Design (ISSUE 6):

- **Page-aligned token-hash chains.** The unit of sharing is one FULL
  page of prompt tokens. Node ``i`` of a prompt's chain is keyed by
  ``sha1(parent_digest + tokens[i*page:(i+1)*page])`` — the digest
  therefore encodes the page's tokens AND its entire left context, which
  is exactly what determines the page's KV content (attention rows
  depend on every earlier token; rope positions are the chain depth).
  Content-addressing by chain digest means a re-registered parent
  reattaches existing children automatically.

- **Refcounts, not ownership.** ``refs[pid]`` counts the slot tables a
  cached page is currently mapped into. ``unref`` at slot retirement
  moves a count-zero page to an LRU of *reclaimable* pages instead of
  freeing it — the KV stays warm for the next hit (a hit revives it
  from the LRU). Under pool pressure ``reclaim`` frees LRU-oldest
  count-zero pages back to the allocator and drops their trie nodes.

- **Read-only mapping + COW.** Matched pages enter a slot's table
  read-only; the engine guarantees no write ever lands in them because
  suffix prefill and decode appends only touch positions >= the match
  boundary, which live in freshly allocated private pages. The one
  exception is a prompt that is an exact multiple of the page size and
  matches in full: the final prompt token must still be re-run to
  produce first-token logits, and its KV row lands INSIDE the last
  matched page — the engine copies that page to a private one first
  (copy-on-write on the first partial page; see
  ``PagedDecodeEngine._admit``).

Everything here is host-side bookkeeping (dict/OrderedDict ops at
admission and retirement); no jax imports, nothing traced.
"""

import collections
import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PrefixCache", "chain_digests"]


def chain_digests(tokens, page: int) -> List[bytes]:
    """Cumulative digests of ``tokens``' FULL pages (one per page; the
    trailing partial page has no digest — it is not shareable). The ONE
    digest definition: the local radix cache, the fleet-wide directory
    (serving/disagg.py), and the router's pre-placement consult must
    agree byte-for-byte or cross-replica hits silently vanish."""
    toks = np.asarray(tokens, np.int32)
    out, digest = [], b"paged-prefix-v1"
    for i in range(len(toks) // page):
        h = hashlib.sha1(digest)
        h.update(toks[i * page:(i + 1) * page].tobytes())
        digest = h.digest()
        out.append(digest)
    return out


class PrefixCache:
    """Refcounted prefix trie over a ``PageAllocator``'s page ids.

    The allocator is shared with the engine: pages the trie holds at
    refcount zero are NOT on the allocator's free list (they are warm
    cache), and ``reclaim`` is the only way they return to it.
    """

    def __init__(self, allocator, page_size: int):
        self._alloc = allocator
        self.page = int(page_size)
        self._nodes: Dict[bytes, int] = {}       # chain digest -> pid
        self._bypid: Dict[int, bytes] = {}       # pid -> chain digest
        self._refs: Dict[int, int] = {}          # pid -> live mappings
        # refcount-zero cached pages, oldest-first (LRU reclaim order)
        self._zero: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        # pages with refcount > 0, maintained incrementally: the engine
        # reads shared_pages on every reservation/release (gauge
        # update), which must not scan the refs dict on the host path
        self._n_shared = 0
        # invalidated (poisoned-KV) pages still mapped by live sharers:
        # their trie nodes are gone, and the last unref frees them to
        # the allocator instead of warming the LRU
        self._dead: set = set()
        # fleet hook: called with (digest, pid) whenever a trie node is
        # REMOVED (invalidate / reclaim) — the disaggregated serving
        # layer withdraws the digest from the fleet-wide prefix
        # directory here, so eviction/poison on the owning replica
        # invalidates fleet-wide before any sharer can map a stale
        # page (serving/disagg.py)
        self.on_drop = None

    # -- chain hashing ------------------------------------------------------

    def chain(self, tokens) -> List[bytes]:
        """Cumulative digests of ``tokens``' FULL pages (one per page;
        the trailing partial page has no digest — it is not
        shareable). Delegates to module-level :func:`chain_digests` —
        the shared definition the fleet directory and router reuse."""
        return chain_digests(tokens, self.page)

    # -- bookkeeping --------------------------------------------------------

    def owns(self, pid: int) -> bool:
        """True iff ``pid`` is a cached (trie-held) page — released via
        ``unref``, never via the allocator free list."""
        return pid in self._bypid

    @property
    def cached_pages(self) -> int:
        return len(self._bypid)

    @property
    def shared_pages(self) -> int:
        """Cached pages currently mapped into at least one slot."""
        return self._n_shared

    @property
    def reclaimable_pages(self) -> int:
        return len(self._zero)

    def ref(self, pid: int):
        if pid not in self._bypid:
            raise KeyError(f"page {pid} is not cached")
        before = self._refs.get(pid, 0)
        if before == 0:
            self._n_shared += 1
        self._refs[pid] = before + 1
        self._zero.pop(pid, None)

    def unref(self, pid: int) -> Optional[int]:
        """Drop one mapping. Returns ``pid`` when this was the last ref
        of an INVALIDATED page and it went back to the allocator — the
        caller owns the KV pool and must scrub the poisoned rows before
        the page can be reused; returns ``None`` otherwise."""
        n = self._refs.get(pid, 0) - 1
        if n < 0:
            raise ValueError(f"unref of unmapped cached page {pid}")
        self._refs[pid] = n
        if n == 0:
            self._n_shared -= 1
            if pid in self._dead:
                # last sharer of an invalidated page: back to the
                # allocator, never the warm LRU
                self._dead.discard(pid)
                self._bypid.pop(pid, None)
                self._refs.pop(pid, None)
                self._alloc.release([pid])
                return pid
            # warm but reclaimable; most-recently-retired goes to the
            # LRU tail so reclaim eats the coldest prefix first
            self._zero[pid] = None
            self._zero.move_to_end(pid)
        return None

    def invalidate(self, pid: int) -> Optional[int]:
        """Drop ``pid``'s trie node so no FUTURE lookup can map it —
        the poisoned-KV escape hatch: a request evicted for non-finite
        logits must not leave its prefix pages canonical, or every
        later submit of the same (popular) prompt would map the
        poisoned KV and fail forever. Current sharers keep their
        refcounted mapping (they fail loudly at their own harvest);
        the page returns to the allocator once the last ref drops.
        Descendant nodes become unreachable (lookup breaks at the
        missing parent) and age out of the LRU on their own. Returns
        ``pid`` when the page was warm/unmapped and went straight back
        to the allocator (the caller must scrub its KV), else None."""
        digest = self._bypid.get(pid)
        if digest is None:
            return None
        if self._nodes.get(digest) == pid:
            # guard against a STALE invalidation: if this pid was
            # already invalidated and the digest re-registered with a
            # healthy page (the poisoned prompt re-submitted), a late
            # sharer's failure must not de-canonicalize the new copy
            self._nodes.pop(digest)
            if self.on_drop is not None:
                self.on_drop(digest, pid)
        if self._refs.get(pid, 0) == 0:
            # warm and unmapped: free immediately
            self._zero.pop(pid, None)
            self._bypid.pop(pid)
            self._refs.pop(pid, None)
            self._alloc.release([pid])
            return pid
        self._dead.add(pid)
        return None

    # -- lookup / registration ----------------------------------------------

    def lookup(self, tokens, chain: Optional[List[bytes]] = None
               ) -> List[int]:
        """Longest cached prefix of ``tokens``: the page ids of the
        leading full pages whose chain digests are all present, each
        ref'd for the caller (the caller maps them into a slot table
        and MUST ``unref`` any it decides not to keep). Pass ``chain``
        (from ``self.chain``) to reuse an already computed digest
        chain — admission hashes the prompt exactly once."""
        pids: List[int] = []
        for digest in (self.chain(tokens) if chain is None else chain):
            pid = self._nodes.get(digest)
            if pid is None:
                break
            self.ref(pid)
            pids.append(pid)
        return pids

    def revive(self, digest: bytes) -> Optional[int]:
        """Ref-and-return the page canonical under ``digest``, or None.
        The fleet-extend path uses this for STALE DESCENDANTS: reclaim
        drops one node and leaves its children canonical-but-
        unreachable (lookup breaks at the missing parent); when the
        missing parents are refetched from the fleet, the surviving
        child pages resume service locally — their KV is valid
        regardless (the chain digest encodes the full left context),
        and re-adopting them would be a KeyError."""
        pid = self._nodes.get(digest)
        if pid is None:
            return None
        self.ref(pid)
        return pid

    def adopt(self, digest: bytes, pid: int):
        """Insert ONE already-populated page under ``digest`` with the
        caller's mapping as its first ref — the fleet-fetch install
        path: a page whose KV just arrived over the wire becomes
        canonical locally so the admission that fetched it (and every
        later submit of the same prefix) maps it like a local hit.
        Refuses an occupied digest or an already-cached pid (the caller
        checked the miss before paying the fetch)."""
        if digest in self._nodes:
            raise KeyError("digest already canonical")
        if pid in self._bypid:
            raise KeyError(f"page {pid} already cached")
        self._nodes[digest] = pid
        self._bypid[pid] = digest
        self._refs[pid] = 1
        self._n_shared += 1

    def register(self, tokens, table: List[int],
                 chain: Optional[List[bytes]] = None) -> int:
        """Insert ``tokens``' full pages (backed by ``table``'s leading
        page ids, which the registering slot currently maps) into the
        trie. Pages whose digest is already present are skipped — the
        existing copy stays canonical and the caller's private
        duplicate is freed normally at retirement. Returns the number
        of pages newly registered (each gains the caller's mapping as
        its first ref); the newly-canonical ``(index, digest, pid)``
        triples land in ``last_registered`` for the fleet-publication
        hook (serving/disagg.py publishes exactly the new ones — never
        a re-upload per admission)."""
        added = 0
        self.last_registered: List[Tuple[int, bytes, int]] = []
        for i, digest in enumerate(self.chain(tokens)
                                   if chain is None else chain):
            if digest in self._nodes:
                continue
            pid = table[i]
            if pid in self._bypid:       # already canonical elsewhere
                continue
            self._nodes[digest] = pid
            self._bypid[pid] = digest
            self._refs[pid] = 1          # the registering slot's mapping
            self._n_shared += 1
            self.last_registered.append((i, digest, pid))
            added += 1
        return added

    # -- reclaim ------------------------------------------------------------

    def reclaim(self, n_pages: int) -> int:
        """Free up to ``n_pages`` refcount-zero cached pages back to the
        allocator, LRU-oldest first (their trie nodes are dropped —
        descendants keyed through them become unreachable and age out
        of the LRU on their own). Returns the number freed."""
        freed = 0
        while freed < n_pages and self._zero:
            pid, _ = self._zero.popitem(last=False)
            digest = self._bypid.pop(pid)
            if self._nodes.get(digest) == pid:
                del self._nodes[digest]
                if self.on_drop is not None:
                    self.on_drop(digest, pid)
            self._refs.pop(pid, None)
            self._alloc.release([pid])
            freed += 1
        return freed
