"""Default-engine factory for the serving surface (ISSUE 19).

Every hardware number says short-length decode is launch-bound, and the
paged engine now owns the single-dispatch megakernel step — so PAGED is
the default serving engine for the front-end and the bench ladder. The
slot-contiguous `DecodeEngine` stays available behind
``PT_SERVE_ENGINE=contiguous`` (or ``engine="contiguous"``): it still
serves prompts longer than the paged prefill's largest bucket, and it
is the sampling-policy surface (temperature/top-k live there).

``make_engine(model)`` is the one construction path the serving
front-end, the smoke tools and the bench ladder share — flipping the
fleet between engines is one env var, not a code edit.
"""

import math
import os
from typing import Optional

from paddle_tpu.inference.decode_engine import DecodeEngine
from paddle_tpu.inference.paged_engine import PagedDecodeEngine

__all__ = ["make_engine", "default_engine_kind"]


def default_engine_kind() -> str:
    """The serving default: ``PT_SERVE_ENGINE`` ('paged' unless
    overridden; 'contiguous' keeps the slot-contiguous engine)."""
    kind = os.environ.get("PT_SERVE_ENGINE", "paged")
    if kind not in ("paged", "contiguous"):
        raise ValueError(
            f"PT_SERVE_ENGINE must be 'paged' or 'contiguous', "
            f"got {kind!r}")
    return kind


def make_engine(model, engine: Optional[str] = None, *,
                max_slots: int = 8, max_len: Optional[int] = None,
                n_pages: Optional[int] = None, page_size: int = 128,
                steps_per_call: int = 1, **kw):
    """Build the serving engine for ``model``: ``engine`` (explicit)
    beats ``PT_SERVE_ENGINE`` beats the paged default.

    Paged sizing default: enough pages for every slot to hold a
    full-length sequence (``max_slots * ceil(max_len / page_size)``) —
    the no-surprises envelope; real deployments size the pool to the
    LIVE-token budget instead (that over-commit is the engine's whole
    point) and pass ``n_pages`` explicitly. Remaining kwargs pass
    through to the chosen engine's constructor (``speculative_k`` works
    on both)."""
    kind = engine if engine is not None else default_engine_kind()
    if engine is not None and engine not in ("paged", "contiguous"):
        raise ValueError(
            f"engine must be 'paged' or 'contiguous', got {engine!r}")
    cap = max_len or model.cfg.max_seq_len
    if kind == "paged":
        if n_pages is None:
            n_pages = max_slots * math.ceil(cap / page_size)
        return PagedDecodeEngine(
            model, n_pages=n_pages, max_slots=max_slots,
            page_size=page_size, steps_per_call=steps_per_call, **kw)
    return DecodeEngine(model, max_slots=max_slots, max_len=cap,
                        steps_per_call=steps_per_call, **kw)
