"""Continuous-batching decode engine — the serving workhorse.

Reference analog: the fused cached-decode transformer serving path
(paddle/fluid/operators/fused/fused_multi_transformer_op.cu and its Python
layer python/paddle/incubate/nn/layer/fused_transformer.py:997), which
batches in-flight sequences of different ages into one kernel via a
per-sequence lengths tensor. The TPU re-design keeps that idea — one
program, ragged lengths — and adds the scheduling half the reference
leaves to paddle-serving:

- **Slot-based KV cache**: one preallocated head-major cache
  (L, S, H, T, D) for S slots. Admission assigns a request to a free slot;
  retirement frees it. All shapes are static, so the jitted decode step
  compiles exactly ONCE no matter how requests come and go (the
  no-recompile property tests assert on).
- **Ragged decode step**: every active slot advances one token per step
  at its own cache position. The caches ride the layer scan as READ-ONLY
  xs; each layer emits only its new KV rows (`GPTBlock.decode_rows`,
  which folds the current token's attention contribution in
  analytically), and the rows are written back as S small
  dynamic_update_slices after the scan — the old scan-ys formulation
  made XLA rebuild the entire (L, S, H, T, D) cache every token (~2x
  the cache size in pure copy traffic per step, the dominant overhead
  over the HBM roofline at serving cache lengths).
- **Bucketed chunked prefill**: prompts run through the cached forward in
  power-of-two buckets (bounded compile set); prompts longer than the
  largest bucket stream through it in chunks, and a tail chunk that would
  overrun the cache window slides back over already-written positions
  (deterministic recompute — identical K/V values land in place).
- **Continuous admission**: new requests join between decode steps —
  nothing waits for a "generation batch" to drain.
- **Chunked device-side stepping** (``steps_per_call > 1``): the decode
  loop runs as a lax.scan INSIDE one dispatch, with per-slot eos/budget
  early-stop computed on device; admissions happen between chunks. One
  host round-trip per chunk instead of per token — the serving loop
  belongs on the device (the reference's analog keeps its loop inside
  one CUDA graph).
- **Pipelined dispatch** (``PT_SERVE_INFLIGHT``, default 2): ``step()``
  is split into a dispatch half (enqueue the next jitted call on the
  still-on-device carry) and a harvest half (pull a PREVIOUS dispatch's
  packed results to host). JAX's async dispatch then overlaps the
  host-side bookkeeping of step N with the device execution of step
  N+1 — the eager ``np.asarray`` after every dispatch was the last
  host↔device sync in the hot loop (VERDICT r4 measured decode at ~43%
  of the HBM roofline with the TPU idling on host gaps). Each harvest
  costs exactly ONE transfer: tokens/emit-flags/non-finite flags ride
  one packed int32 array. Request budgets and eos ids live in
  persistent device arrays (``remaining``/``eos_ids``) so consecutive
  dispatches need no host marshalling at all; the host keeps a shadow
  of per-slot budgets only to decide when to stop dispatching.
  Admission rides the pipeline (prefill updates all per-slot device
  state inside the jitted call); deadline eviction — a host-side
  mutation of device state — drains it first. Long prompts' prefill
  chunks interleave with decode dispatches under a per-step token
  budget (``PT_SERVE_PREFILL_TOKENS``), so a long admission no longer
  stalls live slots for its whole prefill. docs/serving.md.
- **Speculative decoding** (``speculative_k > 0``, greedy only): each
  step verifies K candidate tokens per slot in ONE pass, so weights +
  KV prefix are read once per accepted run instead of once per token —
  decode can then beat the per-token HBM roofline. Drafts come from
  prompt-lookup (the last bigram's previous continuation in the slot's
  own history — no draft model) computed ON DEVICE from the engine's
  token-history buffer, and speculative stepping composes with
  ``steps_per_call``: a whole chunk of draft→verify→accept iterations
  runs in one dispatch with per-slot eos/budget early-stop, so the
  host never syncs mid-chunk (per-step host round-trips dominated the
  old implementation on remote PJRT). The scheme is LOSSLESS:
  acceptance keeps exactly the greedy stream of the verify pass's own
  forward math, whatever the acceptance rate (verify and the plain K=1
  step share ONE attention definition, `GPTBlock.decode_rows`). No
  reference analog; the reference decodes strictly one token per
  launch.

HBM note: the engine runs on a scan-stacked copy of the block weights,
passed to its jitted functions as arguments (never closure constants).
While the caller's unstacked `model` stays alive, weights exist twice —
drop the model after constructing the engine if HBM is tight.

`decode_roofline_tokens_per_sec` gives the HBM-bandwidth bound the engine
is judged against (decode reads every weight once per step plus each
active slot's KV prefix).
"""

import collections
import os
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.models import gpt as gpt_lib

__all__ = ["DecodeEngine", "Request", "decode_roofline_tokens_per_sec"]


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def resolve_engine_weights(model, share_weights_with):
    """The ONE donor-or-build protocol shared by the contiguous and the
    paged engines: returns (cfg, head dict, scan-stacked blocks). With a
    donor, weights alias the donor's (no second copy); otherwise they
    are built from ``model`` (which must be a dense stack)."""
    if model is None:
        if share_weights_with is None:
            raise ValueError(
                "model=None requires share_weights_with (the donor "
                "engine supplies config + weights)")
        cfg = share_weights_with.cfg
    else:
        cfg = model.cfg
        if any(model.blocks[i].moe is not None
               for i in range(cfg.n_layers)):
            raise NotImplementedError(
                "engines serve dense stacks (MoE decode goes through "
                "gpt.generate)")
    if share_weights_with is not None:
        if share_weights_with.cfg is not cfg:
            raise ValueError(
                "share_weights_with engine serves a different model")
        return (cfg, share_weights_with._head,
                share_weights_with._stacked)
    head = {"wte": model.wte, "wpe": model.wpe,
            "lnf_scale": model.lnf_scale,
            "lnf_bias": model.lnf_bias,
            "lm_head": model.lm_head}
    stacked = gpt_lib.stack_block_weights(
        [model.blocks[i] for i in range(cfg.n_layers)])
    return cfg, head, stacked


def _note_retrace(fn_name: str):
    """Trace-time (re)trace counter: called at the TOP of the engines'
    jitted bodies, so it runs exactly once per (re)trace and never per
    step — the dynamic complement to ptlint PT002's static retrace
    check. A rising ``compile/retrace/<fn>`` during steady-state
    serving is the recompile leak PT002 can only catch structurally."""
    from paddle_tpu import stats
    # ptlint: disable=PT003 -- deliberate trace-time side effect: the
    # counter must tick when tracing happens, exactly like the
    # collective wrappers' issue-time byte counters (PR 7)
    stats.add("compile/retrace")
    # ptlint: disable=PT003 -- same deliberate trace-time counter
    stats.add(f"compile/retrace/{fn_name}")


def prompt_lookup_draft(toks, lengths, last, K):
    """On-device prompt-lookup drafts, shared by both engines'
    speculative paths: continuation of the most recent earlier
    occurrence of the trailing bigram in the slot's own history — no
    draft model, no host sync. ``toks[s, i]`` is token i for
    i <= lengths[s] (history length lengths+1, pending token at index
    lengths). Returns cand (S, K) with cand[:, 0] = last. Slots
    without a match draft zeros (they still verify+accept the one
    correction token, exactly like the host-draft version)."""
    S, T = toks.shape
    idx = jnp.arange(T)[None, :]
    a = jnp.take_along_axis(
        toks, jnp.maximum(lengths - 1, 0)[:, None], axis=1)[:, 0]
    nxt_t = jnp.concatenate(
        [toks[:, 1:], jnp.zeros((S, 1), jnp.int32)], axis=1)
    ok = ((toks == a[:, None]) & (nxt_t == last[:, None])
          & (idx <= (lengths - 2)[:, None]))
    has = jnp.any(ok, axis=1)
    i_best = jnp.argmax(jnp.where(ok, idx, -1), axis=1)
    offs = (i_best + 2)[:, None] + jnp.arange(K - 1)[None, :]
    vals = jnp.take_along_axis(toks, jnp.clip(offs, 0, T - 1), axis=1)
    valid = offs <= lengths[:, None]   # within history [0, lengths]
    tail = jnp.where(has[:, None] & valid, vals, 0)
    return jnp.concatenate([last[:, None], tail], axis=1)


def spec_accept(pred, n_acc, bad, active, remaining, eos, last):
    """Shared greedy-speculative acceptance: turn one verify's
    predictions (S, K), accepted-prefix counts and non-finite flags
    into the per-slot emitted-token count ``n_eff`` (0..K, after eos
    and budget truncation), the advanced ``last`` token, the
    active-masked ``bad`` flag and the per-slot emitted-eos flag. The
    caller charges ``remaining``/``lengths`` by n_eff and recomputes
    ``active`` — identical math on the contiguous and paged
    engines (the lossless-acceptance contract lives here once)."""
    K = pred.shape[1]
    # inactive slots keep computing from stale state inside the chunk;
    # a non-finite there must not retroactively fail a request that
    # already completed (same mask as the plain-path _one_token)
    bad = bad & active
    n_raw = jnp.where(bad, 0, n_acc + 1)
    # eos truncation: keep tokens up to and including the first eos
    # among the accepted run
    j = jnp.arange(K)[None, :]
    is_eos = ((pred == eos[:, None]) & (eos >= 0)[:, None]
              & (j < n_raw[:, None]))
    any_eos = jnp.any(is_eos, axis=1)
    first_eos = jnp.argmax(is_eos, axis=1)
    n_eff = jnp.where(any_eos, first_eos + 1, n_raw)
    n_eff = jnp.minimum(n_eff, remaining)
    n_eff = jnp.where(active, n_eff, 0)
    new_last = jnp.take_along_axis(
        pred, jnp.maximum(n_eff - 1, 0)[:, None], axis=1)[:, 0]
    last = jnp.where(n_eff > 0, new_last, last)
    emitted_eos = any_eos & (first_eos < n_eff)
    return n_eff, last, bad, emitted_eos


class Request:
    """One in-flight generation request.

    ``deadline`` (monotonic, absolute) bounds the request's wall time in
    the engine; past it the scheduler evicts ONLY this request (slot
    freed, batch peers unaffected) with ``error`` set. ``error`` is also
    set when the non-finite-logit guard evicts a poisoned request —
    callers must check it before trusting ``tokens``.

    ``t_submit``/``t_first`` (perf_counter seconds, set by the engine)
    carry the serving-latency bookkeeping: TTFT = t_first - t_submit
    lands in the ``serve/ttft_s`` histogram (``serve/prefill_s`` on a
    prefill-only engine — the role-tagged split), and the completed
    request's submit→done lifetime is recorded as a ``serve/request``
    trace span.

    ``rid`` is the request's TRACE CONTEXT: the fleet-wide request id
    minted at front-end/router admission and carried through mailbox
    messages, handoff meta, and KV blobs. Every request-scoped span
    attaches it as ``rid=`` so per-replica trace files stitch into one
    cross-process timeline (observability/merge.stitch_trace_files);
    the flight recorder keys its event ring on it too. None for bare
    ``engine.submit()`` callers — spans then carry no rid and the
    request does not stitch (nothing else degrades)."""

    __slots__ = ("prompt", "max_new_tokens", "eos_id", "tokens", "done",
                 "deadline", "error", "t_submit", "t_first",
                 "_obs_ended", "rid")

    def __init__(self, prompt, max_new_tokens, eos_id, deadline=None,
                 rid=None):
        import time
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.tokens: List[int] = []   # generated only
        self.done = False
        self.deadline = deadline      # absolute time.monotonic() budget
        self.error: Optional[str] = None
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self._obs_ended = False
        self.rid = rid

    @property
    def ttft_s(self) -> Optional[float]:
        """Queue wait + prefill up to the first generated token."""
        return (None if self.t_first is None
                else self.t_first - self.t_submit)

    @property
    def failed(self) -> bool:
        return self.error is not None

    @property
    def output(self) -> List[int]:
        return self.prompt + self.tokens


class _Inflight:
    """One in-flight dispatch awaiting harvest: the live (slot, request)
    snapshot it covered, the packed on-device result array, and the
    dispatch timestamp. ``kind`` is 'prefill' (payload: the sampled
    first token), 'decode' (packed (3, chunk, S): tokens / emit flags /
    non-finite flags) or 'spec' (packed (chunk, S, K+2))."""

    __slots__ = ("kind", "live", "payload", "t")

    def __init__(self, kind, live, payload, t):
        self.kind, self.live, self.payload, self.t = kind, live, payload, t


class _HandoffRequest(Request):
    """A request whose KV state was built on another replica (a drain
    migration landing on a slot-contiguous engine): carries the wire
    KV rows, the tokens generated so far, and the valid-row count
    until admission installs them (``DecodeEngine._admit_handoff``)."""

    __slots__ = ("kv_rows", "kv_tokens", "kv_ntok", "kv_wire")


class ResilientScheduler:
    """Shared degradation bookkeeping for the serving engines: evict ONE
    request (deadline overrun or non-finite logits) without disturbing
    its batch peers. Engines override `_on_evict` to reclaim their own
    per-slot resources (the paged engine returns the slot's pages).

    Also the shared serving-observability surface (docs/observability.md
    ``serve/*``): per-request TTFT and lifetime, per-step queue depth and
    batch occupancy, per-token latency — the numbers a serving operator
    scrapes to answer "what is p99 TTFT and are we admission-bound".

    Service hooks (the continuous-batching front-end in
    ``paddle_tpu/serving/scheduler.py`` installs these; docs/serving.md
    "Front-end"):

    - ``on_token(req, token)`` — called the moment a harvested token is
      appended to ``req.tokens`` (streaming APIs fan tokens out from
      here; token order matches the request's stream exactly).
    - ``on_retire(req)`` — called exactly once when a request leaves
      the engine (retired, deadline-evicted, or poison-evicted; check
      ``req.error``). Fires from inside ``step()``'s harvest, i.e. the
      moment the slot frees — a front-end backfills the empty slot
      here so the next dispatch is never under-occupied.
    - ``bucket_policy(engine, remaining)`` — overrides prefill bucket
      selection (DecodeEngine's chunked prefill): return a bucket size
      from ``engine.buckets`` for a prefill chunk covering
      ``remaining`` prompt tokens. None keeps the built-in choice
      (smallest covering bucket)."""

    on_token = None
    on_retire = None
    bucket_policy = None
    # speculative depth (0 = off): engines that support speculative
    # decode set this in their ctor; the shared replay unpacks 'spec'
    # records (chunk, S, K+2) by it
    spec_k = 0
    # role-tagged first-token metric: a prefill-only engine's "first
    # token" is the END of prefill, not a client-visible TTFT — it
    # records serve/prefill_s instead (the paged ctor overrides), so
    # fleet-merged serve/ttft_s holds ONLY decode-side end-to-end
    # samples (the PR 12 bench pre-mark workaround, retired)
    _ttft_metric = "serve/ttft_s"

    @property
    def free_slots(self) -> int:
        """Slots with no request bound (admission capacity right now)."""
        return sum(r is None for r in self._slot_req)

    @property
    def queued(self) -> int:
        """Requests submitted but not yet assigned a slot."""
        return len(self._waiting)

    @property
    def kv_bytes(self) -> int:
        """Outstanding KV bytes across live slots — the load gauge
        role-aware routing places decode work by. Slot-contiguous
        engines charge the full per-slot cache window per live slot;
        the paged engine overrides with pages actually held."""
        live = sum(r is not None for r in self._slot_req)
        cfg = self.cfg
        per_slot = (2 * cfg.n_layers * cfg.kv_heads * self.T
                    * cfg.head_dim * np.dtype(self.kc.dtype).itemsize)
        return live * per_slot

    def _on_evict(self, slot: int):
        self.active = self.active.at[slot].set(False)

    def _fail(self, req: Request, reason: str, slot: Optional[int] = None,
              stat: str = "serve/deadline_evictions"):
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        req.done = True
        req.error = reason
        if slot is not None:
            self._slot_req[slot] = None
            self._on_evict(slot)
            self._disp_rem[slot] = 0
        stats.add(stat)
        # terminal failure: dump the request's flight record NOW — the
        # postmortem (which bucket, which evictions, which handoff
        # hops) must not require a re-run under tracing
        flight.record(req.rid, "evicted", reason=reason, stat=stat,
                      slot=slot, tokens=len(req.tokens))
        flight.dump(req.rid, reason)
        self._obs_request_end(req)

    # -- pipelined dispatch (shared by both engines) ------------------------
    def _init_pipeline(self, inflight):
        """In-flight depth (how many dispatches may be enqueued before
        the oldest is harvested): ctor arg beats PT_SERVE_INFLIGHT beats
        the default 2. Depth 1 is the fully synchronous baseline the
        bit-identity tests compare against."""
        depth = (int(inflight) if inflight is not None
                 else int(os.environ.get("PT_SERVE_INFLIGHT", "2")))
        if depth < 1:
            raise ValueError(f"in-flight depth must be >= 1, got {depth}")
        self.depth = depth
        self._pending: collections.deque = collections.deque()
        # host shadow of per-slot dispatch budgets: how many more tokens
        # are worth dispatching for, given the dispatches already in
        # flight. Decides ONLY when to stop dispatching — the truth
        # (remaining/eos/active) lives on device.
        self._disp_rem = np.zeros((self.S,), np.int64)
        self._t_disp_end: Optional[float] = None

    def _pending_cover(self):
        """slot -> number of in-flight DECODE dispatches covering it."""
        cover: dict = {}
        for rec in self._pending:
            if rec.kind != "prefill":
                for s, _ in rec.live:
                    cover[s] = cover.get(s, 0) + 1
        return cover

    def _resync_budgets(self, live, cover=None):
        """Re-anchor the host budget shadow to harvested truth: the
        request's true remaining minus the guaranteed progress (at
        least ``chunk`` tokens each) of dispatches still in flight.
        Exact for the plain/chunked paths; a safe lower bound for
        speculative (whose per-dispatch yield varies), where a few
        no-op dispatches at the tail are bounded by the depth."""
        if cover is None:
            cover = self._pending_cover()
        for slot, req in live:
            if req.done or self._slot_req[slot] is not req:
                continue
            rem = req.max_new_tokens - len(req.tokens)
            self._disp_rem[slot] = max(
                0, rem - self.chunk * cover.get(slot, 0))

    def _obs_host_gap(self):
        """Host-side bubble between finishing one dispatch enqueue and
        issuing the next — the time the device risks idling on the host
        at depth 1; the pipeline's job is to hide it."""
        import time
        from paddle_tpu import stats
        if self._t_disp_end is not None:
            stats.observe("serve/host_gap_s",
                          time.perf_counter() - self._t_disp_end)

    def _finish_dispatch(self, kind, live, payload):
        """Post-enqueue bookkeeping shared by both engines: charge the
        budget shadows, queue the in-flight record, stamp the gap
        timer, publish the gauge and the per-path launch counters (the
        launch-tax numbers ROADMAP item 1's r06 recapture needs
        attributable on-chip: serve/dispatch_launches total plus
        serve/dispatches/<kind>)."""
        import time
        from paddle_tpu import stats
        for s, _ in live:
            self._disp_rem[s] = max(0, self._disp_rem[s] - self.chunk)
        self._pending.append(_Inflight(kind, live, payload,
                                       time.perf_counter()))
        self._t_disp_end = time.perf_counter()
        stats.add("serve/dispatch_launches")
        stats.add(f"serve/dispatches/{kind}")
        stats.set_value("serve/inflight", len(self._pending))

    def _pump(self, dispatched: bool):
        """The harvest policy: keep at most ``depth`` dispatches in
        flight after a dispatch (depth 1 = fully synchronous), pop one
        when there was nothing to dispatch (drain tail). An idle step
        also resets the host-gap timer so traffic gaps never pollute
        serve/host_gap_s."""
        if dispatched:
            while len(self._pending) >= self.depth:
                self._harvest_one()
        else:
            self._t_disp_end = None
            if self._pending:
                self._harvest_one()

    def _harvest_one(self) -> int:
        """Pull the OLDEST in-flight dispatch's packed results to host
        (ONE transfer) and replay them into Requests. While the
        transfer blocks, younger dispatches keep the device busy — that
        overlap is the pipeline's entire win."""
        from paddle_tpu import stats
        from paddle_tpu.observability import trace
        rec = self._pending.popleft()
        with trace.span("serve/harvest", kind=rec.kind,
                        inflight=len(self._pending)) as sp:
            # ptlint: disable=PT001 -- THE one deliberate sync: the lag-one
            # harvest's single packed device→host transfer (docs/serving.md)
            arr = np.asarray(rec.payload)
            emitted = self._replay(rec, arr)
            sp.attrs["tokens"] = emitted
        stats.set_value("serve/inflight", len(self._pending))
        self.tokens_emitted += emitted
        return emitted

    def _drain(self):
        """Harvest every in-flight dispatch — the hard pipeline
        boundary: a host-side mutation of device state (deadline
        eviction) must see fully-applied results first."""
        while self._pending:
            self._harvest_one()

    def _replay(self, rec, arr) -> int:
        """Apply one harvested dispatch's packed results to its live
        snapshot ('prefill', 'decode' and 'spec' records — both engines
        dispatch the same record kinds, so the replay lives here once).
        Requests retired or evicted since the dispatch are skipped —
        the device had already deactivated their slots, so their flags
        in ``arr`` are all False. Engines customize via ``_apply_token``
        (what one emitted token does) and ``_after_replay`` (post-loop
        retirement)."""
        if rec.kind == "prefill":
            slot, req = rec.live[0]
            if not req.done and self._slot_req[slot] is req:
                # the prefill's sampled token is the first generated one
                self._emit(slot, req, int(arr))
            self._resync_budgets(rec.live)
            return 0
        if rec.kind == "spec":
            return self._replay_spec(rec, arr)
        toks = arr[0]
        flags = arr[1].astype(bool)
        bads = arr[2].astype(bool)
        total = 0
        for slot, req in rec.live:
            if req.done or self._slot_req[slot] is not req:
                continue
            for j in range(self.chunk):
                if flags[j, slot] and not req.done:
                    self._apply_token(slot, req, int(toks[j, slot]))
                    total += 1
            if bads[:, slot].any() and not req.done:
                self._fail(req, "non-finite logits", slot=slot,
                           stat="serve/nonfinite_evictions")
        self._after_replay(rec)
        self._resync_budgets(rec.live)
        return total

    def _replay_spec(self, rec, arr) -> int:
        """Speculative records unpack (chunk, S, K+2): K predictions,
        the accepted count n_eff, the non-finite flag — the first
        n_eff predictions of each chunk step are the emitted tokens."""
        K = self.spec_k
        preds, effs = arr[..., :K], arr[..., K]
        bads = arr[..., K + 1].astype(bool)
        total = 0
        for slot, req in rec.live:
            if req.done or self._slot_req[slot] is not req:
                continue
            for j in range(self.chunk):
                for t in range(int(effs[j, slot])):
                    self._apply_token(slot, req, int(preds[j, slot, t]))
                    total += 1
            if bads[:, slot].any():
                self._fail(req, "non-finite logits", slot=slot,
                           stat="serve/nonfinite_evictions")
        self._after_replay(rec)
        self._resync_budgets(rec.live)
        return total

    def _apply_token(self, slot: int, req: Request, token: int):
        raise NotImplementedError

    def _after_replay(self, rec):
        pass

    def drain(self) -> None:
        """Block until every in-flight dispatch is harvested and applied
        (the pipeline analog of jax.block_until_ready). Request state
        (``tokens``/``done``) is exact after this returns."""
        self._drain()

    # -- serving metrics (shared by both engines) ---------------------------
    def _obs_first_token(self, req: Request):
        """Called at the request's FIRST generated token. Role-tagged:
        decode-capable engines record ``serve/ttft_s``; a prefill-only
        engine records ``serve/prefill_s`` (its first token marks the
        end of prefill, and a prefill-side sample in the TTFT histogram
        would halve the fleet's effective p99)."""
        import time
        from paddle_tpu import stats
        if req.t_first is None:
            req.t_first = time.perf_counter()
            stats.observe(self._ttft_metric, req.t_first - req.t_submit)

    def _obs_request_end(self, req: Request):
        """Request left the engine (done or evicted): close its span —
        an after-the-fact submit→now interval on the rank timeline —
        and record its TPOT (decode-phase per-token latency, the SLO
        bench's second axis next to TTFT). Idempotent: eviction and
        retirement may both see the request. The ``on_retire`` service
        hook fires here (same exactly-once guard)."""
        import time
        from paddle_tpu import stats
        from paddle_tpu.observability import trace
        if req._obs_ended:
            return
        req._obs_ended = True
        now = time.perf_counter()
        if req.t_first is not None and len(req.tokens) > 1:
            stats.observe("serve/tpot_s",
                          (now - req.t_first) / (len(req.tokens) - 1))
        trace.complete("serve/request", req.t_submit,
                       rid=req.rid, prompt=len(req.prompt),
                       tokens=len(req.tokens), error=req.error)
        if (req.t_first is not None
                and self._ttft_metric == "serve/ttft_s"):
            # the request's DECODE phase (first token → end) as its own
            # rid-tagged span: the stitched per-request lane's decode
            # segment (prefill-only engines have no decode phase)
            trace.complete("serve/decode", req.t_first, rid=req.rid,
                           tokens=len(req.tokens))
        if self.on_retire is not None:
            self.on_retire(req)

    def _obs_step(self, t0: float, emitted: int, live: int):
        """Per-step serving telemetry: queue depth / batch occupancy
        histograms and the per-token latency histogram (step wall time
        amortized over the tokens it emitted)."""
        import time
        from paddle_tpu import stats
        stats.observe("serve/queue_depth", len(self._waiting))
        stats.observe("serve/batch_occupancy", live / max(1, self.S))
        if emitted > 0:
            stats.observe("serve/token_s",
                          (time.perf_counter() - t0) / emitted)

    def _evict_expired(self):
        """Deadline sweep (queue + live slots) run at each step entry.
        Evicting a LIVE slot mutates device state mid-pipeline (active
        flags, the paged engine's pages), so the pipeline drains first:
        in-flight results are applied, then whatever is still expired
        is evicted. Queued evictions touch no device state and need no
        drain."""
        import time
        now = time.monotonic()
        for req in [r for r in self._waiting
                    if r.deadline is not None and now > r.deadline]:
            self._waiting.remove(req)
            # distinct from the mid-decode counter: a queue reject
            # wasted no device work, an eviction abandoned some — the
            # admission-control dashboards must tell them apart
            self._fail(req, "deadline exceeded while queued",
                       stat="serve/queue_deadline_rejects")
        if any(req is not None and req.deadline is not None
               and now > req.deadline for req in self._slot_req):
            self._drain()
            now = time.monotonic()
            for slot, req in enumerate(self._slot_req):
                if (req is not None and req.deadline is not None
                        and now > req.deadline):
                    self._fail(req, "deadline exceeded", slot=slot)

    def _poison_mask(self):
        """Injection mask for this dispatch (site engine.poison_logits).
        With no fault plan installed this returns one cached all-False
        device array — the production hot path pays no per-step host
        allocation or transfer."""
        from paddle_tpu.testing import faults
        if not faults.enabled():
            mask = getattr(self, "_no_poison", None)
            if mask is None:
                mask = self._no_poison = jnp.zeros((self.S,), bool)
            return mask
        return jnp.asarray(faults.slot_mask("engine.poison_logits",
                                            self.S))


class DecodeEngine(ResilientScheduler):
    """Continuous-batching generation over a dense GPT model.

        eng = DecodeEngine(model, max_slots=8, max_len=512)
        r1 = eng.submit(prompt_a, max_new_tokens=32)
        r2 = eng.submit(prompt_b, max_new_tokens=8)   # joins mid-flight
        eng.run()                                     # drains everything
        r1.tokens, r2.tokens

    Greedy by default; temperature/top-k/top-p mirror `gpt.generate`.
    Pass ``mesh`` (a tp-axis Mesh) for tensor-parallel serving: weights
    place per PARTITION_RULES, caches shard over heads, and GSPMD
    partitions the jitted bodies (≙ HybridParallelInference).
    """

    def __init__(self, model, max_slots: int = 8,
                 max_len: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 temperature: float = 0.0, top_p: float = 1.0,
                 top_k: int = 0, seed: int = 0, cache_dtype=None,
                 speculative_k: int = 0, steps_per_call: int = 1,
                 share_weights_with: "Optional[DecodeEngine]" = None,
                 weight_dtype: Optional[str] = None, mesh=None,
                 inflight: Optional[int] = None, warmup: bool = False,
                 prefill_tokens: Optional[int] = None):
        from paddle_tpu import compile_cache
        compile_cache.guard()
        cfg, head, stacked = resolve_engine_weights(model,
                                                    share_weights_with)
        self.cfg = cfg
        # prefer a 128-multiple cache length (keeps the flash-decode kernel
        # engaged) but never exceed the position table — jnp.take would
        # clamp out-of-range positions silently
        cap = cfg.max_seq_len
        self.T = min(_round_up(min(max_len or cap, cap), 128), cap)
        self.S = int(max_slots)
        self.sample = (float(temperature), float(top_p), int(top_k))
        if buckets is None:
            buckets = [b for b in (16, 32, 64, 128, 256, 512)
                       if b <= self.T] or [self.T]
        self.buckets = sorted(set(int(b) for b in buckets))
        self._bucket_set = set(self.buckets)
        if self.buckets[-1] > self.T:
            raise ValueError(
                f"bucket {self.buckets[-1]} exceeds cache length {self.T}")

        # the weights the jitted bodies actually touch: the embedding /
        # final-ln / head leaves, and ONE scan-stacked copy of the
        # blocks (passed as arguments, so nothing is baked into
        # executables). A second engine over the same model shares the
        # stacked copy via share_weights_with — at 1.3B a redundant
        # copy is 2.4GB of HBM (resolved by resolve_engine_weights).
        self._head, self._stacked = head, stacked
        if weight_dtype == "int8":
            # weight-only int8 serving: decode is HBM-bandwidth bound,
            # so halving the dominant read (block matmul weights stream
            # as int8, dequantized per-tile at the MXU) raises
            # throughput toward 2x the bf16 roofline. Per-(layer,
            # out-channel) scales; embeddings / norms / the (tied) LM
            # head stay in float. Composes with share_weights_with:
            # the quantized copy is built FROM the shared stack without
            # mutating the donor's.
            self._quantize_stacked_int8()
        elif weight_dtype is not None:
            raise ValueError(
                f"weight_dtype must be None or 'int8', "
                f"got {weight_dtype!r}")
        self.mesh = mesh
        if mesh is not None:
            if share_weights_with is not None:
                raise NotImplementedError(
                    "mesh + share_weights_with: the placement would "
                    "duplicate the shared stack on the mesh — place one "
                    "engine and share FROM it instead")
            if weight_dtype is not None:
                raise NotImplementedError("mesh + weight_dtype")

        dt = cache_dtype or cfg.dtype
        shape = (cfg.n_layers, self.S, cfg.kv_heads, self.T,
                 cfg.head_dim)
        self.kc = jnp.zeros(shape, dt)
        self.vc = jnp.zeros(shape, dt)
        self.lengths = jnp.zeros((self.S,), jnp.int32)
        self.last = jnp.zeros((self.S,), jnp.int32)
        self.active = jnp.zeros((self.S,), bool)
        # device-side token history (prompt + generated, one row per
        # slot): toks[s, i] is token i for i <= lengths[s] (the pending
        # `last` token sits at index lengths[s]). Feeds the on-device
        # prompt-lookup drafts — speculative stepping never syncs the
        # host mid-chunk.
        self.toks = jnp.zeros((self.S, self.T), jnp.int32)
        # per-slot token budgets + eos ids as PERSISTENT device state:
        # set by the prefill dispatch, decremented by the decode
        # dispatches — consecutive dispatches need no host marshalling,
        # which is what lets them pipeline
        self.remaining = jnp.zeros((self.S,), jnp.int32)
        self.eos_ids = jnp.full((self.S,), -1, jnp.int32)
        if mesh is not None:
            self._place_on_mesh(model, mesh)
        self._rng = jax.random.PRNGKey(seed)

        self._slot_req: List[Optional[Request]] = [None] * self.S
        self._waiting: collections.deque = collections.deque()

        self.spec_k = int(speculative_k)
        if self.spec_k:
            if self.spec_k < 2:
                raise ValueError("speculative_k must be >= 2 (one input "
                                 "token + at least one candidate)")
            if temperature != 0.0:
                raise NotImplementedError(
                    "speculative decoding is greedy-only (lossless "
                    "acceptance needs argmax determinism)")
        self.chunk = int(steps_per_call)
        if self.chunk < 1:
            raise ValueError("steps_per_call must be >= 1")
        self.steps = 0          # device round-trips (the spec-decode win)
        self.tokens_emitted = 0

        # caches donated: the engine rebinds them every call, and donation
        # lets XLA update the multi-GB buffers in place. The plain path
        # is the chunk=1 instance of _multi_impl — every decode dispatch
        # goes through it (or the speculative wrapper), so eos/budget
        # early-stop always lives on device and results always come
        # back as one packed array.
        self._multi_fn = jax.jit(self._multi_impl, donate_argnums=(2, 3))
        self._prefill_fn = jax.jit(self._prefill_impl,
                                   donate_argnums=(2, 3, 4))
        self._verify_fn = jax.jit(self._spec_multi_impl,
                                  donate_argnums=(2, 3, 4))

        self._init_pipeline(inflight)
        self._admitting: collections.deque = collections.deque()
        if prefill_tokens is None:
            prefill_tokens = int(os.environ.get(
                "PT_SERVE_PREFILL_TOKENS", "0")) or self.buckets[-1]
        # per-step prompt-token budget for interleaved prefill (at least
        # one bucket so an open admission always progresses)
        self._prefill_budget = max(int(prefill_tokens), self.buckets[0])
        if warmup:
            self.warmup()

    def _place_on_mesh(self, model, mesh):
        """Tensor-parallel serving (≙ HybridParallelInference,
        fleet/utils/hybrid_parallel_inference.py): place the stacked
        weights per PARTITION_RULES (leading layer axis replicated) and
        the KV caches head-sharded over 'tp'; GSPMD then partitions the
        jitted decode bodies and inserts the attention/MLP psums. Only
        the 'tp' axis may exceed 1 — slots stay whole so admission's
        per-slot cache slicing never crosses a shard boundary."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        shape = dict(mesh.shape)
        tp = shape.get("tp", 1)
        extra = {k: v for k, v in shape.items() if k != "tp" and v > 1}
        if extra:
            raise ValueError(
                f"DecodeEngine mesh supports a tp axis only, got {extra}")
        if self.cfg.n_heads % tp or self.cfg.kv_heads % tp:
            raise ValueError(
                f"tp={tp} must divide heads "
                f"({self.cfg.n_heads}/{self.cfg.kv_heads})")
        sleaves, treedef, specs = gpt_lib.stacked_partition_specs(
            self._stacked, model.blocks[0])
        placed = [jax.device_put(
            leaf, NamedSharding(mesh, gpt_lib.mesh_safe_spec(spec, mesh)))
            for leaf, spec in zip(sleaves, specs)]
        self._stacked = jax.tree_util.tree_unflatten(treedef, placed)
        self._head = {
            k: (None if v is None else jax.device_put(
                jnp.asarray(v),
                NamedSharding(mesh, gpt_lib.mesh_safe_spec(
                    gpt_lib.partition_spec(k), mesh))))
            for k, v in self._head.items()}
        kv_spec = NamedSharding(mesh, P(None, None, "tp", None, None))
        self.kc = jax.device_put(self.kc, kv_spec)
        self.vc = jax.device_put(self.vc, kv_spec)
        rep = NamedSharding(mesh, P())
        self.lengths = jax.device_put(self.lengths, rep)
        self.last = jax.device_put(self.last, rep)
        self.active = jax.device_put(self.active, rep)
        self.toks = jax.device_put(self.toks, rep)
        self.remaining = jax.device_put(self.remaining, rep)
        self.eos_ids = jax.device_put(self.eos_ids, rep)

    def _quantize_stacked_int8(self):
        """Replace the stacked blocks' matmul weights with int8
        QuantTensors (symmetric absmax, per-layer-per-output-channel
        scales). The QuantTensor rides the block pytree in the weight's
        registered slot, so the scanned layer body sees a per-layer
        (in, out) int8 weight and its ``x @ w`` routes through
        QuantTensor.__rmatmul__ (Pallas int8 matmul on TPU)."""
        from paddle_tpu.quantization import QuantTensor
        # rebuild the Module object first (leaves shared, container
        # fresh) so a stack borrowed via share_weights_with is never
        # mutated under the donor engine
        stacked = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self._stacked),
            jax.tree_util.tree_leaves(self._stacked))
        self._stacked = stacked
        for name in ("wqkv", "wo", "wup", "wdown"):
            w = getattr(stacked, name, None)
            if w is None or isinstance(w, QuantTensor):
                continue
            wf = jnp.asarray(w).astype(jnp.float32)   # (L, in, out)
            absmax = jnp.max(jnp.abs(wf), axis=1, keepdims=True)
            scale = jnp.maximum(absmax, 1e-8) / 127.0
            q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
            object.__setattr__(stacked, name,
                               QuantTensor(q, scale, w.dtype))

    # -- jitted bodies ------------------------------------------------------

    def _lm_head(self, head, x):
        """Final LN + (tied) LM projection on (S, L, d) → (S, L, V)."""
        x = gpt_lib.final_ln(x, head["lnf_scale"], head["lnf_bias"])
        w = (head["wte"].T if head["lm_head"] is None
             else head["lm_head"])
        return x @ w

    def _write_rows(self, kc, vc, k_rows, v_rows, lengths, active):
        """Write each ACTIVE slot's K new KV rows at its own cache
        position: S small dynamic_update_slices on the carried buffers
        instead of the full-cache rebuild the old scan-ys formulation
        paid (~2x the cache size in copy traffic per step).

        An INACTIVE slot rewrites its existing row (a read-select-write
        identity — the contiguous analog of the paged engine's scratch
        page): its device ``lengths`` is stale, and with interleaved
        admission a decode dispatch enqueued between prefill chunks
        would otherwise clobber a prompt row the prefill already wrote.

        k_rows/v_rows: (L, S, K, Hkv, D) stacked layer outputs."""
        kr = jnp.transpose(k_rows, (0, 1, 3, 2, 4))   # (L, S, Hkv, K, D)
        vr = jnp.transpose(v_rows, (0, 1, 3, 2, 4))
        for s in range(self.S):
            pos = lengths[s]
            win = (0, s, 0, pos, 0)
            old_k = lax.dynamic_slice(kc, win, kr[:, s:s + 1].shape)
            old_v = lax.dynamic_slice(vc, win, vr[:, s:s + 1].shape)
            kc = lax.dynamic_update_slice(
                kc, jnp.where(active[s], kr[:, s:s + 1], old_k), win)
            vc = lax.dynamic_update_slice(
                vc, jnp.where(active[s], vr[:, s:s + 1], old_v), win)
        return kc, vc

    def _one_token(self, head, stacked, kc, vc, lengths, last, active,
                   rng, poison):
        """Advance every active slot one token: the shared body of the
        single-step and chunked-step entry points. The caches ride the
        layer scan as READ-ONLY xs; each layer emits only its new KV
        rows (`GPTBlock.decode_rows`), written back in one batch after
        the scan.

        Degradation guard: per-slot ``bad`` flags any non-finite logits
        (a poisoned request — NaN/Inf from a numerical blowup or fault
        injection via ``poison``). A bad slot emits nothing and does not
        advance; the host evicts only that request from the batch."""
        temperature, top_p, top_k = self.sample
        x = jnp.take(head["wte"], last, axis=0)
        if head["wpe"] is not None:   # rope models position in attention
            x = x + jnp.take(head["wpe"], lengths, axis=0)
        x = x[:, None, :]

        def layer(x, blk_kv):
            blk, k_l, v_l = blk_kv
            y, k_rows, v_rows = blk.decode_rows(
                x, (k_l, v_l), lengths,
                allow_kernel=self.mesh is None)
            return y, (k_rows, v_rows)

        x, (k_rows, v_rows) = lax.scan(layer, x, (stacked, kc, vc))
        kc, vc = self._write_rows(kc, vc, k_rows, v_rows, lengths,
                                  active)
        logits = self._lm_head(head, x)[:, 0]
        logits = jnp.where(poison[:, None], jnp.nan, logits)
        bad = active & ~jnp.all(jnp.isfinite(logits), axis=-1)
        rng, k = jax.random.split(rng)
        nxt = gpt_lib._sample_token(logits.astype(jnp.float32), k,
                                    temperature, top_p, top_k)
        nxt = jnp.where(active & ~bad, nxt, last)
        lengths = lengths + (active & ~bad).astype(jnp.int32)
        return kc, vc, lengths, nxt, rng, bad

    def _multi_impl(self, head, stacked, kc, vc, lengths, last, active,
                    remaining, eos, rng, poison):
        """``chunk`` decode steps in ONE dispatch (lax.scan over
        _one_token), with per-slot early stop device-side: a slot stops
        advancing when it hits its eos id or exhausts its token budget,
        and a slot whose logits go non-finite stops emitting immediately
        (its ``bad`` flag tells the host to evict the request).

        Serving loops belong on the device — host round-trip latency
        (worst over a remote PJRT tunnel, still microseconds locally)
        otherwise bounds tokens/sec regardless of model speed. The
        reference's analog is the fused-multi-transformer loop staying
        inside one CUDA graph. Emits the (chunk, S) tokens, emit flags
        and non-finite flags PACKED into one int32 array so the lagged
        harvest pays exactly one device→host transfer."""
        _note_retrace("decode_multi")

        def one(carry, _):
            kc, vc, lengths, last, active, remaining, rng = carry
            kc, vc, lengths, nxt, rng, bad = self._one_token(
                head, stacked, kc, vc, lengths, last, active, rng, poison)
            emit = active & ~bad
            remaining = remaining - emit.astype(jnp.int32)
            hit_eos = (nxt == eos) & (eos >= 0)
            active = active & ~bad & ~hit_eos & (remaining > 0)
            return (kc, vc, lengths, nxt, active, remaining, rng), \
                (nxt, emit, bad)

        (kc, vc, lengths, last, active, remaining, rng), \
            (toks, flags, bads) = \
            lax.scan(one, (kc, vc, lengths, last, active, remaining, rng),
                     None, length=self.chunk)
        packed = jnp.stack([toks, flags.astype(jnp.int32),
                            bads.astype(jnp.int32)])
        return (kc, vc, lengths, last, active, remaining, rng, packed)

    def _verify_impl(self, head, stacked, kc, vc, lengths, cand, active,
                     poison):
        """One speculative verify: K candidate tokens per slot through
        one pass. Returns the model's predictions (S, K), the
        accepted-prefix length n_acc (0..K-1), and the per-slot
        non-finite ``bad`` flag; the chunked wrapper applies eos/budget
        truncation and advances the state."""
        S, K = cand.shape
        x = jnp.take(head["wte"], cand, axis=0)
        if head["wpe"] is not None:
            x = x + jnp.take(head["wpe"],
                             lengths[:, None] + jnp.arange(K), axis=0)

        def layer(x, blk_kv):
            blk, k_l, v_l = blk_kv
            y, k_rows, v_rows = blk.decode_rows(
                x, (k_l, v_l), lengths,
                allow_kernel=self.mesh is None)
            return y, (k_rows, v_rows)

        x, (k_rows, v_rows) = lax.scan(layer, x, (stacked, kc, vc))
        kc, vc = self._write_rows(kc, vc, k_rows, v_rows, lengths,
                                  active)
        logits = self._lm_head(head, x).astype(jnp.float32)  # (S, K, V)
        logits = jnp.where(poison[:, None, None], jnp.nan, logits)
        bad = ~jnp.all(jnp.isfinite(logits), axis=(1, 2))
        pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # candidate j (cand[:, j], j>=1) is accepted iff it equals the
        # model's prediction at the previous position — cumulative
        match = jnp.cumprod(
            (cand[:, 1:] == pred[:, :-1]).astype(jnp.int32), axis=1)
        n_acc = jnp.sum(match, axis=1)                 # 0..K-1
        return kc, vc, pred, n_acc, bad

    def _draft_device(self, toks, lengths, last):
        """On-device prompt-lookup drafts — the shared module-level
        `prompt_lookup_draft` at this engine's K (the paged engine's
        speculative path drafts through the same helper)."""
        return prompt_lookup_draft(toks, lengths, last, self.spec_k)

    def _spec_multi_impl(self, head, stacked, kc, vc, toks, lengths,
                         last, active, remaining, eos, poison):
        """``chunk`` speculative steps in ONE dispatch: draft on device
        from the history buffer, verify K candidates per slot in one
        pass, accept the longest greedy-matching run, early-stop per
        slot on eos/budget — the host never syncs mid-chunk (the old
        one-step-per-dispatch version paid 2+ tunnel round-trips per
        verify, which dominated the measurement on remote PJRT).

        Emits the (chunk, S, K) predictions, (chunk, S) accepted counts
        and non-finite flags packed into ONE (chunk, S, K+2) int32
        array — one transfer per lagged harvest."""
        _note_retrace("decode_spec")
        K = self.spec_k

        def one(carry, _):
            kc, vc, toks, lengths, last, active, remaining = carry
            cand = self._draft_device(toks, lengths, last)
            kc, vc, pred, n_acc, bad = self._verify_impl(
                head, stacked, kc, vc, lengths, cand, active, poison)
            n_eff, last, bad, emitted_eos = spec_accept(
                pred, n_acc, bad, active, remaining, eos, last)
            # history append: pred[j] is the token at absolute position
            # lengths+1+j. All K values are written (garbage beyond
            # n_eff is overwritten by the next step's window or masked
            # by lengths on read); at the very end of a slot's budget
            # the window can touch [T-K, T) via DUS clamping — the slot
            # is retiring, its history is never read again. INACTIVE
            # slots rewrite their existing window (same guard as
            # _write_rows): a mid-admission slot's stale lengths would
            # otherwise clobber prompt history a prefill chunk already
            # wrote, corrupting the prompt-lookup drafts.
            for s in range(self.S):
                win = (s, lengths[s] + 1)
                old = lax.dynamic_slice(toks, win, (1, K))
                toks = lax.dynamic_update_slice(
                    toks, jnp.where(active[s], pred[s:s + 1], old), win)
            remaining = remaining - n_eff
            lengths = lengths + n_eff
            active = active & ~bad & ~emitted_eos & (remaining > 0)
            return (kc, vc, toks, lengths, last, active, remaining), \
                (pred, n_eff, bad)

        (kc, vc, toks, lengths, last, active, remaining), \
            (preds, effs, bads) \
            = lax.scan(one, (kc, vc, toks, lengths, last, active,
                             remaining), None, length=self.chunk)
        packed = jnp.concatenate(
            [preds, effs[..., None], bads[..., None].astype(jnp.int32)],
            axis=-1)
        return (kc, vc, toks, lengths, last, active, remaining, packed)

    def _prefill_impl(self, head, stacked, kc, vc, toks, lengths, last,
                      active, remaining, eos_ids, slot, tokens, start,
                      true_total, is_final, rem0, eos0, rng):
        """Run one prompt chunk through the slot's cache slice; on the
        final chunk, sample the first generated token, activate the
        slot, and install its token budget (``rem0``, the budget net of
        this first token) and eos id into the persistent device arrays
        — so decode dispatches already enqueued behind this prefill
        pick the slot up with NO host round-trip. `tokens` is
        (1, bucket) — one compile per bucket size. The chunk is also
        recorded in the device history buffer (the speculative path
        drafts from it). Returns the sampled token as an extra output;
        the scheduler harvests it lag-one like any other dispatch."""
        _note_retrace("decode_prefill")
        cfg = self.cfg
        L, bucket = cfg.n_layers, tokens.shape[1]
        sl = (L, 1, cfg.kv_heads, self.T, cfg.head_dim)
        kcs = lax.dynamic_slice(kc, (0, slot, 0, 0, 0), sl)
        vcs = lax.dynamic_slice(vc, (0, slot, 0, 0, 0), sl)

        x = jnp.take(head["wte"], tokens, axis=0)
        if head["wpe"] is not None:
            x = x + lax.dynamic_slice_in_dim(head["wpe"], start, bucket)

        def layer(x, blk_kv):
            blk, k_l, v_l = blk_kv
            x, (k_l, v_l) = blk.forward_cached(x, (k_l, v_l), start)
            return x, (k_l, v_l)

        x, (kcs, vcs) = lax.scan(layer, x, (stacked, kcs, vcs))
        kc = lax.dynamic_update_slice(kc, kcs, (0, slot, 0, 0, 0))
        vc = lax.dynamic_update_slice(vc, vcs, (0, slot, 0, 0, 0))

        idx = jnp.clip(true_total - 1 - start, 0, bucket - 1)
        logits = self._lm_head(head, x[:, idx][:, None])[:, 0]
        temperature, top_p, top_k = self.sample
        rng, k = jax.random.split(rng)
        nxt = gpt_lib._sample_token(logits.astype(jnp.float32), k,
                                    temperature, top_p, top_k)[0]
        # history: the prompt chunk at [start, start+bucket) (zero pads
        # beyond the prompt are never read), and on the final chunk the
        # pending first generated token at index true_total
        toks = lax.dynamic_update_slice(toks, tokens, (slot, start))
        toks = jnp.where(
            is_final,
            lax.dynamic_update_slice(toks, nxt.reshape(1, 1),
                                     (slot, true_total)), toks)
        onehot = jnp.arange(self.S) == slot
        upd = jnp.logical_and(onehot, is_final)
        # a request whose whole budget was the first token, or whose
        # first token IS its eos, never activates — the device-side
        # analog of the host _emit retiring at admission
        alive = jnp.logical_and(
            rem0 > 0, jnp.logical_or(eos0 < 0, nxt != eos0))
        lengths = jnp.where(upd, true_total, lengths)
        last = jnp.where(upd, nxt, last)
        active = jnp.logical_or(active, jnp.logical_and(upd, alive))
        remaining = jnp.where(upd, rem0, remaining)
        eos_ids = jnp.where(upd, eos0, eos_ids)
        return (kc, vc, toks, lengths, last, active, remaining, eos_ids,
                rng, nxt)

    # -- scheduler ----------------------------------------------------------

    def check_request(self, prompt_len: int, max_new_tokens: int):
        """Admission feasibility check WITHOUT enqueueing (the serving
        front-end rejects infeasible requests at its API edge instead
        of surfacing the error from a later pump). Raises ValueError."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len + max_new_tokens > self.T:
            raise ValueError(
                f"{prompt_len} prompt + {max_new_tokens} new tokens "
                f"exceed cache length {self.T}")
        if self.spec_k and (prompt_len + max_new_tokens
                            + self.spec_k - 1 > self.T):
            raise ValueError(
                f"speculative window: prompt + new + K-1 "
                f"({prompt_len}+{max_new_tokens}+{self.spec_k - 1}) "
                f"exceed cache length {self.T}")

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               req_id: Optional[str] = None) -> Request:
        """``deadline_s``: wall-time budget for this request (queue wait
        included). A request past its deadline is evicted alone — the
        batch keeps serving its peers. ``req_id`` is the fleet-wide
        trace context (front-end/router request id) carried into every
        request-scoped span and flight-recorder event."""
        import time
        prompt = list(np.asarray(prompt).reshape(-1))
        self.check_request(len(prompt), max_new_tokens)
        req = Request(prompt, max_new_tokens, eos_id,
                      deadline=(None if deadline_s is None
                                else time.monotonic() + deadline_s),
                      rid=req_id)
        self._waiting.append(req)
        return req

    def _free_slot(self) -> Optional[int]:
        for s, r in enumerate(self._slot_req):
            if r is None:
                return s
        return None

    def _admit_next(self) -> bool:
        """Move the next waiting request into a free slot as an
        INCREMENTAL prefill job: its chunks dispatch under the per-step
        token budget, interleaved with decode dispatches, so a long
        prompt no longer stalls live slots for its whole prefill."""
        import time
        from paddle_tpu.observability import flight, trace
        slot = self._free_slot()
        if slot is None or not self._waiting:
            return False
        req = self._waiting.popleft()
        # the queue-wait phase ends HERE: the stitched per-request lane
        # derives queue-wait from submission to prefill start
        trace.complete("serve/queue", req.t_submit, rid=req.rid,
                       slot=slot)
        if isinstance(req, _HandoffRequest):
            # no prefill to run: install the transferred rows directly
            self._admit_handoff(req, slot)
            return True
        flight.record(req.rid, "admit", slot=slot,
                      prompt=len(req.prompt))
        self._slot_req[slot] = req      # reserve; decode skips it until
        self._disp_rem[slot] = 0        # the final chunk flips it live
        self._admitting.append({
            "req": req, "slot": slot, "start": 0,
            # ptlint: disable=PT001 -- req.prompt is a host int list
            # (submit coerced it); this is an upload, never a sync
            "prompt": np.asarray(req.prompt, np.int32),
            "t0": time.perf_counter()})
        return True

    def _dispatch_prefill_chunk(self, job):
        """Dispatch ONE bucket-sized prompt chunk. On the final chunk
        the jitted body flips the slot live on device (lengths / last /
        active / remaining / eos_ids) and the sampled first token rides
        the harvest queue as a 'prefill' record. Returns (bucket tokens
        consumed, finished)."""
        import time
        from paddle_tpu import stats
        from paddle_tpu.observability import flight, trace
        req, slot = job["req"], job["slot"]
        prompt, start = job["prompt"], job["start"]
        total = len(prompt)
        remaining = total - start
        if self.bucket_policy is not None:
            bucket = int(self.bucket_policy(self, remaining))
            if bucket not in self._bucket_set:
                raise ValueError(
                    f"bucket_policy returned {bucket}, not one of "
                    f"{self.buckets}")
        else:
            bucket = next((x for x in self.buckets if x >= remaining),
                          self.buckets[-1])
        s0 = start
        if s0 + bucket > self.T:
            # tail window would overrun the cache: slide it back over
            # already-prefilled positions — same tokens at the same
            # positions recompute the identical K/V, so the overlapped
            # rewrite is a no-op and the write stays in bounds
            s0 = self.T - bucket
        n = min(total - s0, bucket)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = prompt[s0:s0 + n]
        is_final = s0 + n >= total
        rem0 = req.max_new_tokens - 1
        eos0 = -1 if req.eos_id is None else int(req.eos_id)
        stats.add("serve/dispatch_launches")
        stats.add("serve/dispatches/prefill")
        flight.record(req.rid, "prefill-chunk", bucket=bucket,
                      start=int(s0), final=bool(is_final))
        with trace.span("serve/prefill", bucket=bucket, slot=slot,
                        rid=req.rid):
            (self.kc, self.vc, self.toks, self.lengths, self.last,
             self.active, self.remaining, self.eos_ids, self._rng,
             nxt) = self._prefill_fn(
                self._head, self._stacked, self.kc, self.vc, self.toks,
                self.lengths, self.last, self.active, self.remaining,
                self.eos_ids, jnp.int32(slot), jnp.asarray(padded),
                jnp.int32(s0), jnp.int32(total), jnp.asarray(is_final),
                jnp.int32(rem0), jnp.int32(eos0), self._rng)
        job["start"] = s0 + n
        if is_final:
            self._disp_rem[slot] = rem0
            self._pending.append(_Inflight("prefill", [(slot, req)], nxt,
                                           time.perf_counter()))
            trace.complete("serve/admit", job["t0"], slot=slot,
                           prompt=total, rid=req.rid)
        return bucket, is_final

    def _advance_admissions(self):
        """Dispatch up to ``_prefill_budget`` prompt tokens of waiting
        requests' prefill chunks (always at least one chunk when a job
        is open), pulling new requests into free slots as jobs
        finish."""
        if self._admitting:
            # a job whose request was deadline-evicted mid-admission is
            # abandoned (its slot is already free and may be re-used by
            # the next job; the partial prefill is inert — the slot
            # never activated and a successor overwrites it)
            self._admitting = collections.deque(
                j for j in self._admitting if not j["req"].done)
        budget = self._prefill_budget
        while budget > 0:
            if not self._admitting and not self._admit_next():
                return
            if not self._admitting:
                # handoff admission: rows installed directly, no
                # prefill job to chunk — pull the next waiter
                continue
            used, finished = self._dispatch_prefill_chunk(
                self._admitting[0])
            budget -= used
            if finished:
                self._admitting.popleft()

    # -- mid-decode handoff (ISSUE 16 drain migration) ----------------------

    def detach_handoff(self, req: Request):
        """Extract an in-flight request's KV rows + decode state and
        retire it locally WITHOUT finishing — the sending half of a
        drain migration on a slot-contiguous engine. The pipeline
        drains first, so rows ``[0, lengths)`` hold prompt +
        generated[:-1] and ``meta["tokens"]`` carries every token
        generated so far; the receiver re-emits the last one and
        continues bit-for-bit (fp32 wire).

        Returns ``(meta, k, v)`` with ``k``/``v`` presented as ONE
        wire page of ``n_tokens`` rows — (L, 1, Hkv, n_tokens, D) —
        so ``kv_transfer.encode_kv_pages`` and any ``submit_handoff``
        (dense or paged with matching geometry) accept them."""
        if req.failed:
            raise ValueError(f"request failed before detach: "
                             f"{req.error}")
        if not req.tokens:
            raise ValueError("no generated token yet — pump step() "
                             "until the request holds one")
        self._drain()
        if req.done:
            raise ValueError("request completed during drain — "
                             "publish its result directly")
        try:
            slot = self._slot_req.index(req)
        except ValueError:
            raise ValueError("request no longer holds a slot")
        # ptlint: disable=PT001 -- deliberate device→host sync: this IS
        # the migration payload leaving the draining replica
        n = int(self.lengths[slot])
        if n != len(req.prompt) + len(req.tokens) - 1:
            raise ValueError(
                f"slot {slot} length {n} inconsistent with prompt "
                f"{len(req.prompt)} + generated {len(req.tokens)} - 1")
        # ptlint: disable=PT001 -- same deliberate payload transfer
        rows_k = np.asarray(self.kc[:, slot, :, :n, :])
        rows_v = np.asarray(self.vc[:, slot, :, :n, :])
        k = rows_k[:, None]            # (L, 1, Hkv, n, D): one page
        v = rows_v[:, None]
        meta = {"prompt": list(req.prompt), "n_tokens": n,
                "first": int(req.tokens[0]),
                "tokens": [int(t) for t in req.tokens],
                "max_new_tokens": int(req.max_new_tokens),
                "eos_id": req.eos_id, "rid": req.rid}
        from paddle_tpu.observability import flight
        flight.record(req.rid, "handoff-detach", n_tokens=n,
                      generated=len(req.tokens))
        self._slot_req[slot] = None
        self.active = self.active.at[slot].set(False)
        self._disp_rem[slot] = 0
        req.done = True
        self._obs_request_end(req)
        return meta, k, v

    def submit_handoff(self, meta: dict, k, v,
                       deadline_s: Optional[float] = None) -> Request:
        """Receiving half of a migration: enqueue a request whose KV
        rows were built elsewhere. Accepts any page layout — (L, npg,
        Hkv, page, D) with ``npg*page >= n_tokens`` — so both dense
        (one page) and paged senders with matching (L, Hkv, D)
        geometry land here. Admission installs the rows and
        reconstructs the exact sender-side device state; the last
        sender-emitted token rides the harvest queue like a local
        prefill's first token."""
        import time
        prompt = [int(t) for t in meta["prompt"]]
        tokens = [int(t) for t in meta.get("tokens",
                                           [meta["first"]])]
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if not tokens:
            raise ValueError("handoff meta carries no tokens")
        max_new = int(meta["max_new_tokens"])
        if len(tokens) > max_new:
            raise ValueError("handoff carries more generated tokens "
                             "than its budget")
        n = int(meta.get("n_tokens", len(prompt) + len(tokens) - 1))
        if n != len(prompt) + len(tokens) - 1:
            raise ValueError(
                f"handoff meta inconsistent: n_tokens={n} != prompt "
                f"{len(prompt)} + generated {len(tokens)} - 1")
        if len(prompt) + max_new > self.T:
            raise ValueError(
                f"{len(prompt)} prompt + {max_new} new tokens exceed "
                f"cache length {self.T}")
        cfg = self.cfg
        k, v = np.asarray(k), np.asarray(v)
        for name, arr in (("k", k), ("v", v)):
            ok = (arr.ndim == 5 and arr.shape[0] == cfg.n_layers
                  and arr.shape[2] == cfg.kv_heads
                  and arr.shape[4] == cfg.head_dim
                  and arr.shape[1] * arr.shape[3] >= n)
            if not ok:
                raise ValueError(
                    f"handoff {name} pages shaped {tuple(arr.shape)} "
                    f"do not fit this engine's geometry (n_layers="
                    f"{cfg.n_layers}, kv_heads={cfg.kv_heads}, "
                    f"head_dim={cfg.head_dim}, rows>={n})")
        req = _HandoffRequest(
            prompt, max_new, meta["eos_id"],
            deadline=(None if deadline_s is None
                      else time.monotonic() + deadline_s),
            rid=meta.get("rid"))
        req.kv_tokens = tokens
        req.kv_ntok = n
        req.kv_wire = str(meta.get("wire", "lossy"))

        def rows(arr):
            L, npg, H, page, D = arr.shape
            return arr.transpose(0, 2, 1, 3, 4).reshape(
                L, H, npg * page, D)[:, :, :n, :]
        req.kv_rows = (rows(k), rows(v))
        self._waiting.append(req)
        return req

    def _admit_handoff(self, req: "_HandoffRequest", slot: int):
        """Install migrated rows instead of prefilling, then
        reconstruct the device state the sender's drained pipeline
        held: rows [0, n) live, ``tokens[-1]`` pending as ``last``
        (its KV is the next dispatch's write), token history row
        rebuilt so on-device drafts see the same window."""
        import time
        from paddle_tpu.observability import flight
        n = req.kv_ntok
        flight.record(req.rid, "handoff-install", n_tokens=n,
                      slot=slot, wire=req.kv_wire,
                      generated=len(req.kv_tokens))
        rows_k, rows_v = req.kv_rows
        self.kc = self.kc.at[:, slot, :, :n, :].set(
            jnp.asarray(rows_k, self.kc.dtype))
        self.vc = self.vc.at[:, slot, :, :n, :].set(
            jnp.asarray(rows_v, self.vc.dtype))
        req.kv_rows = None             # free the host copy
        seq = np.zeros((self.T,), np.int32)
        hist = req.prompt + req.kv_tokens      # n + 1 tokens
        seq[:len(hist)] = hist
        # ptlint: disable=PT001 -- seq is a host-built row; upload only
        self.toks = self.toks.at[slot].set(jnp.asarray(seq))
        req.tokens = list(req.kv_tokens[:-1])
        nxt = req.kv_tokens[-1]
        rem0 = req.max_new_tokens - len(req.kv_tokens)
        eos0 = -1 if req.eos_id is None else int(req.eos_id)
        alive = rem0 > 0 and (eos0 < 0 or nxt != eos0)
        self.lengths = self.lengths.at[slot].set(n)
        self.last = self.last.at[slot].set(jnp.int32(nxt))
        self.active = self.active.at[slot].set(bool(alive))
        self.remaining = self.remaining.at[slot].set(rem0)
        self.eos_ids = self.eos_ids.at[slot].set(eos0)
        self._slot_req[slot] = req
        self._disp_rem[slot] = rem0
        self._pending.append(_Inflight("prefill", [(slot, req)],
                                       np.int32(nxt),
                                       time.perf_counter()))

    def _emit(self, slot: int, req: Request, token: int):
        req.tokens.append(token)
        self._obs_first_token(req)
        if self.on_token is not None:
            self.on_token(req, token)
        hit_eos = req.eos_id is not None and token == req.eos_id
        if hit_eos or len(req.tokens) >= req.max_new_tokens:
            req.done = True
            self._slot_req[slot] = None
            self.active = self.active.at[slot].set(False)
            self._obs_request_end(req)

    def step(self) -> int:
        """Advance the serving pipeline: evict expired requests (a hard
        drain boundary), dispatch waiting prefill chunks and one decode
        dispatch, then harvest the OLDEST in-flight dispatch once the
        pipeline holds ``depth`` of them — lag-one at the default depth
        2, fully synchronous at depth 1. Returns tokens applied to
        Requests this call; at depth>1 they come from an earlier
        dispatch, so drain with run() (or ``drain()``) before reading
        final Request state."""
        import time
        from paddle_tpu.observability import trace
        t0 = time.perf_counter()
        base = self.tokens_emitted
        with trace.span("serve/step") as sp:
            self._evict_expired()
            self._advance_admissions()
            self._pump(self._dispatch_decode())
            live = self.num_active
            n = self.tokens_emitted - base
            sp.attrs["active"] = live
            sp.attrs["tokens"] = n
        if live or n:
            self._obs_step(t0, n, live)
        return n

    def _dispatch_decode(self) -> bool:
        """Enqueue ONE decode dispatch over every live slot (chunked or
        speculative; the plain path is the chunk=1 instance). Pure
        enqueue — nothing is pulled back to host here; the packed
        results join the harvest queue."""
        from paddle_tpu.observability import trace
        live = [(s, r) for s, r in enumerate(self._slot_req)
                if r is not None and self._disp_rem[s] > 0]
        if not live:
            return False
        self.steps += 1
        self._obs_host_gap()
        if self.spec_k:
            with trace.span("serve/dispatch", kind="spec", k=self.spec_k,
                            chunk=self.chunk,
                            inflight=len(self._pending)):
                (self.kc, self.vc, self.toks, self.lengths, self.last,
                 self.active, self.remaining, packed) = self._verify_fn(
                    self._head, self._stacked, self.kc, self.vc,
                    self.toks, self.lengths, self.last, self.active,
                    self.remaining, self.eos_ids, self._poison_mask())
            kind = "spec"
        else:
            with trace.span("serve/dispatch", kind="chunk",
                            chunk=self.chunk,
                            inflight=len(self._pending)):
                (self.kc, self.vc, self.lengths, self.last, self.active,
                 self.remaining, self._rng, packed) = self._multi_fn(
                    self._head, self._stacked, self.kc, self.vc,
                    self.lengths, self.last, self.active, self.remaining,
                    self.eos_ids, self._rng, self._poison_mask())
            kind = "decode"
        self._finish_dispatch(kind, live, packed)
        return True

    def _retire_done(self, live):
        """Free slots whose request hit its budget or eos (mirrors the
        device-side early-stop) — shared by both harvest paths. Guards
        against stale snapshots: a slot already freed and re-admitted
        must not be clobbered by an older dispatch's record."""
        for slot, req in live:
            if req.done or self._slot_req[slot] is not req:
                continue
            if len(req.tokens) >= req.max_new_tokens or (
                    req.eos_id is not None and req.tokens
                    and req.tokens[-1] == req.eos_id):
                req.done = True
                self._slot_req[slot] = None
                self._disp_rem[slot] = 0
                self._obs_request_end(req)

    def _apply_token(self, slot: int, req: Request, token: int):
        # the FIRST generated token always rides a 'prefill' record
        # (_emit), so TTFT needs no check here — only the stream hook
        req.tokens.append(token)
        if self.on_token is not None:
            self.on_token(req, token)

    def _after_replay(self, rec):
        self._retire_done(rec.live)

    def warmup(self):
        """Pre-trace and compile every jitted function this engine can
        dispatch — one prefill per bucket plus the decode path — on
        throwaway state mirrors, so the first requests pay no compile
        latency. The KV caches transiently exist twice while warming
        (the mirrors are donated through the chain and freed at the
        end); with a persistent compilation cache the compiles
        themselves are amortized across processes."""
        import time
        from paddle_tpu import stats
        t0 = time.perf_counter()
        kc, vc = jnp.zeros_like(self.kc), jnp.zeros_like(self.vc)
        toks = jnp.zeros_like(self.toks)
        lengths = jnp.zeros_like(self.lengths)
        last = jnp.zeros_like(self.last)
        active = jnp.zeros_like(self.active)
        remaining = jnp.zeros_like(self.remaining)
        eos_ids = jnp.zeros_like(self.eos_ids)
        rng = jax.random.PRNGKey(0)
        for b in self.buckets:
            (kc, vc, toks, lengths, last, active, remaining, eos_ids,
             rng, _) = self._prefill_fn(
                self._head, self._stacked, kc, vc, toks, lengths, last,
                active, remaining, eos_ids, jnp.int32(0),
                jnp.zeros((1, b), jnp.int32), jnp.int32(0), jnp.int32(1),
                jnp.asarray(False), jnp.int32(0), jnp.int32(-1), rng)
        poison = jnp.zeros((self.S,), bool)
        if self.spec_k:
            out = self._verify_fn(self._head, self._stacked, kc, vc,
                                  toks, lengths, last, active, remaining,
                                  eos_ids, poison)
        else:
            out = self._multi_fn(self._head, self._stacked, kc, vc,
                                 lengths, last, active, remaining,
                                 eos_ids, rng, poison)
        jax.block_until_ready(out)
        stats.observe("serve/warmup_s", time.perf_counter() - t0)

    def run(self) -> None:
        """Drain: run steps until every submitted request is done, then
        harvest any trailing no-op dispatches (all requests can retire
        while younger dispatches are still in flight — their flags are
        all False, but their device buffers must not outlive the
        work)."""
        while self._waiting or any(r is not None for r in self._slot_req):
            self.step()
        self._drain()

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self._slot_req)

    def dispatch_cost(self, name=None):
        """ISSUE 15 roofline capture: AOT cost/memory analysis of ONE
        decode dispatch at the CURRENT geometry — XLA's FLOPs and HBM
        bytes for the exact program the serving loop launches (the
        spec-verify program when ``speculative_k`` is set). Lowers
        without executing, so donated buffers stay live; compilation
        rides the jit cache on a warmed engine. Records ``prof/flops``
        / ``prof/hbm_bytes`` / ``mem/compiled_*`` under ``name``
        (default: the path name)."""
        from paddle_tpu.observability import devprof
        if self.spec_k:
            return devprof.capture_jit(
                self._verify_fn, self._head, self._stacked, self.kc,
                self.vc, self.toks, self.lengths, self.last,
                self.active, self.remaining, self.eos_ids,
                self._poison_mask(), name=name or "spec")
        return devprof.capture_jit(
            self._multi_fn, self._head, self._stacked, self.kc,
            self.vc, self.lengths, self.last, self.active,
            self.remaining, self.eos_ids, self._rng,
            self._poison_mask(), name=name or "decode")

    def dispatch_fn_args(self):
        """The jitted decode dispatch and the exact argument tuple the
        serving loop calls it with (the spec-verify program when
        ``speculative_k`` is set) — for launch accounting
        (``devprof.count_pallas_launches`` /
        ``count_hlo_custom_calls``) without executing anything."""
        if self.spec_k:
            return (self._verify_fn,
                    (self._head, self._stacked, self.kc, self.vc,
                     self.toks, self.lengths, self.last, self.active,
                     self.remaining, self.eos_ids, self._poison_mask()))
        return (self._multi_fn,
                (self._head, self._stacked, self.kc, self.vc,
                 self.lengths, self.last, self.active, self.remaining,
                 self.eos_ids, self._rng, self._poison_mask()))


def decode_roofline_tokens_per_sec(cfg, batch: int, context: int,
                                   hbm_gbps: float,
                                   weight_bytes: int = 2,
                                   cache_bytes: int = 2) -> float:
    """HBM-bandwidth upper bound on decode throughput.

    Per decode step the chip must read every weight once (batch-amortized)
    plus each sequence's KV prefix: steps/s = BW / (W + B * kv_bytes),
    tok/s = B * steps/s. This is the number BENCH compares achieved decode
    against (VERDICT r4: r02 decode sat at ~43% of this bound).
    """
    n = cfg.num_params()
    kv_heads = getattr(cfg, "kv_heads", cfg.n_heads)  # GQA shrinks this
    kv = 2 * cfg.n_layers * kv_heads * cfg.head_dim * context
    step_bytes = n * weight_bytes + batch * kv * cache_bytes
    return batch * hbm_gbps * 1e9 / step_bytes
