"""Continuous-batching decode engine — the serving workhorse.

Reference analog: the fused cached-decode transformer serving path
(paddle/fluid/operators/fused/fused_multi_transformer_op.cu and its Python
layer python/paddle/incubate/nn/layer/fused_transformer.py:997), which
batches in-flight sequences of different ages into one kernel via a
per-sequence lengths tensor. The TPU re-design keeps that idea — one
program, ragged lengths — and adds the scheduling half the reference
leaves to paddle-serving:

- **Slot-based KV cache**: one preallocated head-major cache
  (L, S, H, T, D) for S slots. Admission assigns a request to a free slot;
  retirement frees it. All shapes are static, so the jitted decode step
  compiles exactly ONCE no matter how requests come and go (the
  no-recompile property tests assert on).
- **Ragged decode step**: every active slot advances one token per step
  at its own cache position. The caches ride the layer scan as READ-ONLY
  xs; each layer emits only its new KV rows (`GPTBlock.decode_rows`,
  which folds the current token's attention contribution in
  analytically), and the rows are written back as S small
  dynamic_update_slices after the scan — the old scan-ys formulation
  made XLA rebuild the entire (L, S, H, T, D) cache every token (~2x
  the cache size in pure copy traffic per step, the dominant overhead
  over the HBM roofline at serving cache lengths).
- **Bucketed chunked prefill**: prompts run through the cached forward in
  power-of-two buckets (bounded compile set); prompts longer than the
  largest bucket stream through it in chunks, and a tail chunk that would
  overrun the cache window slides back over already-written positions
  (deterministic recompute — identical K/V values land in place).
- **Continuous admission**: new requests join between decode steps —
  nothing waits for a "generation batch" to drain.
- **Chunked device-side stepping** (``steps_per_call > 1``): the decode
  loop runs as a lax.scan INSIDE one dispatch, with per-slot eos/budget
  early-stop computed on device; admissions happen between chunks. One
  host round-trip per chunk instead of per token — the serving loop
  belongs on the device (the reference's analog keeps its loop inside
  one CUDA graph).
- **Speculative decoding** (``speculative_k > 0``, greedy only): each
  step verifies K candidate tokens per slot in ONE pass, so weights +
  KV prefix are read once per accepted run instead of once per token —
  decode can then beat the per-token HBM roofline. Drafts come from
  prompt-lookup (the last bigram's previous continuation in the slot's
  own history — no draft model) computed ON DEVICE from the engine's
  token-history buffer, and speculative stepping composes with
  ``steps_per_call``: a whole chunk of draft→verify→accept iterations
  runs in one dispatch with per-slot eos/budget early-stop, so the
  host never syncs mid-chunk (per-step host round-trips dominated the
  old implementation on remote PJRT). The scheme is LOSSLESS:
  acceptance keeps exactly the greedy stream of the verify pass's own
  forward math, whatever the acceptance rate (verify and the plain K=1
  step share ONE attention definition, `GPTBlock.decode_rows`). No
  reference analog; the reference decodes strictly one token per
  launch.

HBM note: the engine runs on a scan-stacked copy of the block weights,
passed to its jitted functions as arguments (never closure constants).
While the caller's unstacked `model` stays alive, weights exist twice —
drop the model after constructing the engine if HBM is tight.

`decode_roofline_tokens_per_sec` gives the HBM-bandwidth bound the engine
is judged against (decode reads every weight once per step plus each
active slot's KV prefix).
"""

import collections
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.models import gpt as gpt_lib

__all__ = ["DecodeEngine", "Request", "decode_roofline_tokens_per_sec"]


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def resolve_engine_weights(model, share_weights_with):
    """The ONE donor-or-build protocol shared by the contiguous and the
    paged engines: returns (cfg, head dict, scan-stacked blocks). With a
    donor, weights alias the donor's (no second copy); otherwise they
    are built from ``model`` (which must be a dense stack)."""
    if model is None:
        if share_weights_with is None:
            raise ValueError(
                "model=None requires share_weights_with (the donor "
                "engine supplies config + weights)")
        cfg = share_weights_with.cfg
    else:
        cfg = model.cfg
        if any(model.blocks[i].moe is not None
               for i in range(cfg.n_layers)):
            raise NotImplementedError(
                "engines serve dense stacks (MoE decode goes through "
                "gpt.generate)")
    if share_weights_with is not None:
        if share_weights_with.cfg is not cfg:
            raise ValueError(
                "share_weights_with engine serves a different model")
        return (cfg, share_weights_with._head,
                share_weights_with._stacked)
    head = {"wte": model.wte, "wpe": model.wpe,
            "lnf_scale": model.lnf_scale,
            "lnf_bias": model.lnf_bias,
            "lm_head": model.lm_head}
    stacked = gpt_lib.stack_block_weights(
        [model.blocks[i] for i in range(cfg.n_layers)])
    return cfg, head, stacked


class Request:
    """One in-flight generation request.

    ``deadline`` (monotonic, absolute) bounds the request's wall time in
    the engine; past it the scheduler evicts ONLY this request (slot
    freed, batch peers unaffected) with ``error`` set. ``error`` is also
    set when the non-finite-logit guard evicts a poisoned request —
    callers must check it before trusting ``tokens``.

    ``t_submit``/``t_first`` (perf_counter seconds, set by the engine)
    carry the serving-latency bookkeeping: TTFT = t_first - t_submit
    lands in the ``serve/ttft_s`` histogram, and the completed request's
    submit→done lifetime is recorded as a ``serve/request`` trace span."""

    __slots__ = ("prompt", "max_new_tokens", "eos_id", "tokens", "done",
                 "deadline", "error", "t_submit", "t_first", "_obs_ended")

    def __init__(self, prompt, max_new_tokens, eos_id, deadline=None):
        import time
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.tokens: List[int] = []   # generated only
        self.done = False
        self.deadline = deadline      # absolute time.monotonic() budget
        self.error: Optional[str] = None
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self._obs_ended = False

    @property
    def ttft_s(self) -> Optional[float]:
        """Queue wait + prefill up to the first generated token."""
        return (None if self.t_first is None
                else self.t_first - self.t_submit)

    @property
    def failed(self) -> bool:
        return self.error is not None

    @property
    def output(self) -> List[int]:
        return self.prompt + self.tokens


class ResilientScheduler:
    """Shared degradation bookkeeping for the serving engines: evict ONE
    request (deadline overrun or non-finite logits) without disturbing
    its batch peers. Engines override `_on_evict` to reclaim their own
    per-slot resources (the paged engine returns the slot's pages).

    Also the shared serving-observability surface (docs/observability.md
    ``serve/*``): per-request TTFT and lifetime, per-step queue depth and
    batch occupancy, per-token latency — the numbers a serving operator
    scrapes to answer "what is p99 TTFT and are we admission-bound"."""

    def _on_evict(self, slot: int):
        self.active = self.active.at[slot].set(False)

    def _fail(self, req: Request, reason: str, slot: Optional[int] = None,
              stat: str = "serve/deadline_evictions"):
        from paddle_tpu import stats
        req.done = True
        req.error = reason
        if slot is not None:
            self._slot_req[slot] = None
            self._on_evict(slot)
        stats.add(stat)
        self._obs_request_end(req)

    # -- serving metrics (shared by both engines) ---------------------------
    def _obs_first_token(self, req: Request):
        """Called at the request's FIRST generated token."""
        import time
        from paddle_tpu import stats
        if req.t_first is None:
            req.t_first = time.perf_counter()
            stats.observe("serve/ttft_s", req.t_first - req.t_submit)

    def _obs_request_end(self, req: Request):
        """Request left the engine (done or evicted): close its span —
        an after-the-fact submit→now interval on the rank timeline.
        Idempotent: eviction and retirement may both see the request."""
        from paddle_tpu.observability import trace
        if req._obs_ended:
            return
        req._obs_ended = True
        trace.complete("serve/request", req.t_submit,
                       prompt=len(req.prompt), tokens=len(req.tokens),
                       error=req.error)

    def _obs_step(self, t0: float, emitted: int, live: int):
        """Per-step serving telemetry: queue depth / batch occupancy
        histograms and the per-token latency histogram (step wall time
        amortized over the tokens it emitted)."""
        import time
        from paddle_tpu import stats
        stats.observe("serve/queue_depth", len(self._waiting))
        stats.observe("serve/batch_occupancy", live / max(1, self.S))
        if emitted > 0:
            stats.observe("serve/token_s",
                          (time.perf_counter() - t0) / emitted)

    def _evict_expired(self):
        """Deadline sweep (queue + live slots) run at each step entry."""
        import time
        now = time.monotonic()
        for req in [r for r in self._waiting
                    if r.deadline is not None and now > r.deadline]:
            self._waiting.remove(req)
            self._fail(req, "deadline exceeded while queued")
        for slot, req in enumerate(self._slot_req):
            if (req is not None and req.deadline is not None
                    and now > req.deadline):
                self._fail(req, "deadline exceeded", slot=slot)

    def _poison_mask(self):
        """Injection mask for this dispatch (site engine.poison_logits).
        With no fault plan installed this returns one cached all-False
        device array — the production hot path pays no per-step host
        allocation or transfer."""
        from paddle_tpu.testing import faults
        if not faults.enabled():
            mask = getattr(self, "_no_poison", None)
            if mask is None:
                mask = self._no_poison = jnp.zeros((self.S,), bool)
            return mask
        return jnp.asarray(faults.slot_mask("engine.poison_logits",
                                            self.S))


class DecodeEngine(ResilientScheduler):
    """Continuous-batching generation over a dense GPT model.

        eng = DecodeEngine(model, max_slots=8, max_len=512)
        r1 = eng.submit(prompt_a, max_new_tokens=32)
        r2 = eng.submit(prompt_b, max_new_tokens=8)   # joins mid-flight
        eng.run()                                     # drains everything
        r1.tokens, r2.tokens

    Greedy by default; temperature/top-k/top-p mirror `gpt.generate`.
    Pass ``mesh`` (a tp-axis Mesh) for tensor-parallel serving: weights
    place per PARTITION_RULES, caches shard over heads, and GSPMD
    partitions the jitted bodies (≙ HybridParallelInference).
    """

    def __init__(self, model, max_slots: int = 8,
                 max_len: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 temperature: float = 0.0, top_p: float = 1.0,
                 top_k: int = 0, seed: int = 0, cache_dtype=None,
                 speculative_k: int = 0, steps_per_call: int = 1,
                 share_weights_with: "Optional[DecodeEngine]" = None,
                 weight_dtype: Optional[str] = None, mesh=None):
        cfg, head, stacked = resolve_engine_weights(model,
                                                    share_weights_with)
        self.cfg = cfg
        # prefer a 128-multiple cache length (keeps the flash-decode kernel
        # engaged) but never exceed the position table — jnp.take would
        # clamp out-of-range positions silently
        cap = cfg.max_seq_len
        self.T = min(_round_up(min(max_len or cap, cap), 128), cap)
        self.S = int(max_slots)
        self.sample = (float(temperature), float(top_p), int(top_k))
        if buckets is None:
            buckets = [b for b in (16, 32, 64, 128, 256, 512)
                       if b <= self.T] or [self.T]
        self.buckets = sorted(set(int(b) for b in buckets))
        if self.buckets[-1] > self.T:
            raise ValueError(
                f"bucket {self.buckets[-1]} exceeds cache length {self.T}")

        # the weights the jitted bodies actually touch: the embedding /
        # final-ln / head leaves, and ONE scan-stacked copy of the
        # blocks (passed as arguments, so nothing is baked into
        # executables). A second engine over the same model shares the
        # stacked copy via share_weights_with — at 1.3B a redundant
        # copy is 2.4GB of HBM (resolved by resolve_engine_weights).
        self._head, self._stacked = head, stacked
        if weight_dtype == "int8":
            # weight-only int8 serving: decode is HBM-bandwidth bound,
            # so halving the dominant read (block matmul weights stream
            # as int8, dequantized per-tile at the MXU) raises
            # throughput toward 2x the bf16 roofline. Per-(layer,
            # out-channel) scales; embeddings / norms / the (tied) LM
            # head stay in float. Composes with share_weights_with:
            # the quantized copy is built FROM the shared stack without
            # mutating the donor's.
            self._quantize_stacked_int8()
        elif weight_dtype is not None:
            raise ValueError(
                f"weight_dtype must be None or 'int8', "
                f"got {weight_dtype!r}")
        self.mesh = mesh
        if mesh is not None:
            if share_weights_with is not None:
                raise NotImplementedError(
                    "mesh + share_weights_with: the placement would "
                    "duplicate the shared stack on the mesh — place one "
                    "engine and share FROM it instead")
            if weight_dtype is not None:
                raise NotImplementedError("mesh + weight_dtype")

        dt = cache_dtype or cfg.dtype
        shape = (cfg.n_layers, self.S, cfg.kv_heads, self.T,
                 cfg.head_dim)
        self.kc = jnp.zeros(shape, dt)
        self.vc = jnp.zeros(shape, dt)
        self.lengths = jnp.zeros((self.S,), jnp.int32)
        self.last = jnp.zeros((self.S,), jnp.int32)
        self.active = jnp.zeros((self.S,), bool)
        # device-side token history (prompt + generated, one row per
        # slot): toks[s, i] is token i for i <= lengths[s] (the pending
        # `last` token sits at index lengths[s]). Feeds the on-device
        # prompt-lookup drafts — speculative stepping never syncs the
        # host mid-chunk.
        self.toks = jnp.zeros((self.S, self.T), jnp.int32)
        if mesh is not None:
            self._place_on_mesh(model, mesh)
        self._rng = jax.random.PRNGKey(seed)

        self._slot_req: List[Optional[Request]] = [None] * self.S
        self._waiting: collections.deque = collections.deque()

        self.spec_k = int(speculative_k)
        if self.spec_k:
            if self.spec_k < 2:
                raise ValueError("speculative_k must be >= 2 (one input "
                                 "token + at least one candidate)")
            if temperature != 0.0:
                raise NotImplementedError(
                    "speculative decoding is greedy-only (lossless "
                    "acceptance needs argmax determinism)")
        self.chunk = int(steps_per_call)
        if self.chunk < 1:
            raise ValueError("steps_per_call must be >= 1")
        self.steps = 0          # device round-trips (the spec-decode win)
        self.tokens_emitted = 0

        # caches donated: the engine rebinds them every call, and donation
        # lets XLA update the multi-GB buffers in place
        self._step_fn = jax.jit(self._one_token, donate_argnums=(2, 3))
        self._multi_fn = jax.jit(self._multi_impl, donate_argnums=(2, 3))
        self._prefill_fn = jax.jit(self._prefill_impl,
                                   donate_argnums=(2, 3, 4))
        self._verify_fn = jax.jit(self._spec_multi_impl,
                                  donate_argnums=(2, 3, 4))

    def _place_on_mesh(self, model, mesh):
        """Tensor-parallel serving (≙ HybridParallelInference,
        fleet/utils/hybrid_parallel_inference.py): place the stacked
        weights per PARTITION_RULES (leading layer axis replicated) and
        the KV caches head-sharded over 'tp'; GSPMD then partitions the
        jitted decode bodies and inserts the attention/MLP psums. Only
        the 'tp' axis may exceed 1 — slots stay whole so admission's
        per-slot cache slicing never crosses a shard boundary."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        shape = dict(mesh.shape)
        tp = shape.get("tp", 1)
        extra = {k: v for k, v in shape.items() if k != "tp" and v > 1}
        if extra:
            raise ValueError(
                f"DecodeEngine mesh supports a tp axis only, got {extra}")
        if self.cfg.n_heads % tp or self.cfg.kv_heads % tp:
            raise ValueError(
                f"tp={tp} must divide heads "
                f"({self.cfg.n_heads}/{self.cfg.kv_heads})")
        sleaves, treedef, specs = gpt_lib.stacked_partition_specs(
            self._stacked, model.blocks[0])
        placed = [jax.device_put(
            leaf, NamedSharding(mesh, gpt_lib.mesh_safe_spec(spec, mesh)))
            for leaf, spec in zip(sleaves, specs)]
        self._stacked = jax.tree_util.tree_unflatten(treedef, placed)
        self._head = {
            k: (None if v is None else jax.device_put(
                jnp.asarray(v),
                NamedSharding(mesh, gpt_lib.mesh_safe_spec(
                    gpt_lib.partition_spec(k), mesh))))
            for k, v in self._head.items()}
        kv_spec = NamedSharding(mesh, P(None, None, "tp", None, None))
        self.kc = jax.device_put(self.kc, kv_spec)
        self.vc = jax.device_put(self.vc, kv_spec)
        rep = NamedSharding(mesh, P())
        self.lengths = jax.device_put(self.lengths, rep)
        self.last = jax.device_put(self.last, rep)
        self.active = jax.device_put(self.active, rep)
        self.toks = jax.device_put(self.toks, rep)

    def _quantize_stacked_int8(self):
        """Replace the stacked blocks' matmul weights with int8
        QuantTensors (symmetric absmax, per-layer-per-output-channel
        scales). The QuantTensor rides the block pytree in the weight's
        registered slot, so the scanned layer body sees a per-layer
        (in, out) int8 weight and its ``x @ w`` routes through
        QuantTensor.__rmatmul__ (Pallas int8 matmul on TPU)."""
        from paddle_tpu.quantization import QuantTensor
        # rebuild the Module object first (leaves shared, container
        # fresh) so a stack borrowed via share_weights_with is never
        # mutated under the donor engine
        stacked = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self._stacked),
            jax.tree_util.tree_leaves(self._stacked))
        self._stacked = stacked
        for name in ("wqkv", "wo", "wup", "wdown"):
            w = getattr(stacked, name, None)
            if w is None or isinstance(w, QuantTensor):
                continue
            wf = jnp.asarray(w).astype(jnp.float32)   # (L, in, out)
            absmax = jnp.max(jnp.abs(wf), axis=1, keepdims=True)
            scale = jnp.maximum(absmax, 1e-8) / 127.0
            q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
            object.__setattr__(stacked, name,
                               QuantTensor(q, scale, w.dtype))

    # -- jitted bodies ------------------------------------------------------

    def _lm_head(self, head, x):
        """Final LN + (tied) LM projection on (S, L, d) → (S, L, V)."""
        x = gpt_lib.final_ln(x, head["lnf_scale"], head["lnf_bias"])
        w = (head["wte"].T if head["lm_head"] is None
             else head["lm_head"])
        return x @ w

    def _write_rows(self, kc, vc, k_rows, v_rows, lengths):
        """Write each slot's K new KV rows at its own cache position:
        S small dynamic_update_slices on the carried buffers instead of
        the full-cache rebuild the old scan-ys formulation paid (~2x the
        cache size in copy traffic per step).

        k_rows/v_rows: (L, S, K, Hkv, D) stacked layer outputs."""
        kr = jnp.transpose(k_rows, (0, 1, 3, 2, 4))   # (L, S, Hkv, K, D)
        vr = jnp.transpose(v_rows, (0, 1, 3, 2, 4))
        for s in range(self.S):
            pos = lengths[s]
            kc = lax.dynamic_update_slice(kc, kr[:, s:s + 1],
                                          (0, s, 0, pos, 0))
            vc = lax.dynamic_update_slice(vc, vr[:, s:s + 1],
                                          (0, s, 0, pos, 0))
        return kc, vc

    def _one_token(self, head, stacked, kc, vc, lengths, last, active,
                   rng, poison):
        """Advance every active slot one token: the shared body of the
        single-step and chunked-step entry points. The caches ride the
        layer scan as READ-ONLY xs; each layer emits only its new KV
        rows (`GPTBlock.decode_rows`), written back in one batch after
        the scan.

        Degradation guard: per-slot ``bad`` flags any non-finite logits
        (a poisoned request — NaN/Inf from a numerical blowup or fault
        injection via ``poison``). A bad slot emits nothing and does not
        advance; the host evicts only that request from the batch."""
        temperature, top_p, top_k = self.sample
        x = jnp.take(head["wte"], last, axis=0)
        if head["wpe"] is not None:   # rope models position in attention
            x = x + jnp.take(head["wpe"], lengths, axis=0)
        x = x[:, None, :]

        def layer(x, blk_kv):
            blk, k_l, v_l = blk_kv
            y, k_rows, v_rows = blk.decode_rows(
                x, (k_l, v_l), lengths,
                allow_kernel=self.mesh is None)
            return y, (k_rows, v_rows)

        x, (k_rows, v_rows) = lax.scan(layer, x, (stacked, kc, vc))
        kc, vc = self._write_rows(kc, vc, k_rows, v_rows, lengths)
        logits = self._lm_head(head, x)[:, 0]
        logits = jnp.where(poison[:, None], jnp.nan, logits)
        bad = active & ~jnp.all(jnp.isfinite(logits), axis=-1)
        rng, k = jax.random.split(rng)
        nxt = gpt_lib._sample_token(logits.astype(jnp.float32), k,
                                    temperature, top_p, top_k)
        nxt = jnp.where(active & ~bad, nxt, last)
        lengths = lengths + (active & ~bad).astype(jnp.int32)
        return kc, vc, lengths, nxt, rng, bad

    def _multi_impl(self, head, stacked, kc, vc, lengths, last, active,
                    remaining, eos, rng, poison):
        """``chunk`` decode steps in ONE dispatch (lax.scan over
        _one_token), with per-slot early stop device-side: a slot stops
        advancing when it hits its eos id or exhausts its token budget,
        and a slot whose logits go non-finite stops emitting immediately
        (its ``bad`` flag tells the host to evict the request).

        Serving loops belong on the device — host round-trip latency
        (worst over a remote PJRT tunnel, still microseconds locally)
        otherwise bounds tokens/sec regardless of model speed. The
        reference's analog is the fused-multi-transformer loop staying
        inside one CUDA graph. Emits (chunk, S) tokens + emit flags;
        the host applies them in order between dispatches."""

        def one(carry, _):
            kc, vc, lengths, last, active, remaining, rng = carry
            kc, vc, lengths, nxt, rng, bad = self._one_token(
                head, stacked, kc, vc, lengths, last, active, rng, poison)
            emit = active & ~bad
            remaining = remaining - emit.astype(jnp.int32)
            hit_eos = (nxt == eos) & (eos >= 0)
            active = active & ~bad & ~hit_eos & (remaining > 0)
            return (kc, vc, lengths, nxt, active, remaining, rng), \
                (nxt, emit, bad)

        (kc, vc, lengths, last, active, remaining, rng), \
            (toks, flags, bads) = \
            lax.scan(one, (kc, vc, lengths, last, active, remaining, rng),
                     None, length=self.chunk)
        return (kc, vc, lengths, last, active, remaining, rng, toks,
                flags, bads)

    def _verify_impl(self, head, stacked, kc, vc, lengths, cand, poison):
        """One speculative verify: K candidate tokens per slot through
        one pass. Returns the model's predictions (S, K), the
        accepted-prefix length n_acc (0..K-1), and the per-slot
        non-finite ``bad`` flag; the chunked wrapper applies eos/budget
        truncation and advances the state."""
        S, K = cand.shape
        x = jnp.take(head["wte"], cand, axis=0)
        if head["wpe"] is not None:
            x = x + jnp.take(head["wpe"],
                             lengths[:, None] + jnp.arange(K), axis=0)

        def layer(x, blk_kv):
            blk, k_l, v_l = blk_kv
            y, k_rows, v_rows = blk.decode_rows(
                x, (k_l, v_l), lengths,
                allow_kernel=self.mesh is None)
            return y, (k_rows, v_rows)

        x, (k_rows, v_rows) = lax.scan(layer, x, (stacked, kc, vc))
        kc, vc = self._write_rows(kc, vc, k_rows, v_rows, lengths)
        logits = self._lm_head(head, x).astype(jnp.float32)  # (S, K, V)
        logits = jnp.where(poison[:, None, None], jnp.nan, logits)
        bad = ~jnp.all(jnp.isfinite(logits), axis=(1, 2))
        pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # candidate j (cand[:, j], j>=1) is accepted iff it equals the
        # model's prediction at the previous position — cumulative
        match = jnp.cumprod(
            (cand[:, 1:] == pred[:, :-1]).astype(jnp.int32), axis=1)
        n_acc = jnp.sum(match, axis=1)                 # 0..K-1
        return kc, vc, pred, n_acc, bad

    def _draft_device(self, toks, lengths, last):
        """On-device prompt-lookup drafts: continuation of the most
        recent earlier occurrence of the trailing bigram in the slot's
        own history — no draft model, no host sync. toks[s, i] is token
        i for i <= lengths[s] (history length lengths+1, pending token
        at index lengths). Returns cand (S, K) with cand[:, 0] = last.
        Slots without a match draft zeros (they still verify+accept the
        one correction token, exactly like the host-draft version)."""
        S, K, T = self.S, self.spec_k, self.T
        idx = jnp.arange(T)[None, :]
        a = jnp.take_along_axis(
            toks, jnp.maximum(lengths - 1, 0)[:, None], axis=1)[:, 0]
        nxt_t = jnp.concatenate(
            [toks[:, 1:], jnp.zeros((S, 1), jnp.int32)], axis=1)
        ok = ((toks == a[:, None]) & (nxt_t == last[:, None])
              & (idx <= (lengths - 2)[:, None]))
        has = jnp.any(ok, axis=1)
        i_best = jnp.argmax(jnp.where(ok, idx, -1), axis=1)
        offs = (i_best + 2)[:, None] + jnp.arange(K - 1)[None, :]
        vals = jnp.take_along_axis(toks, jnp.clip(offs, 0, T - 1), axis=1)
        valid = offs <= lengths[:, None]   # within history [0, lengths]
        tail = jnp.where(has[:, None] & valid, vals, 0)
        return jnp.concatenate([last[:, None], tail], axis=1)

    def _spec_multi_impl(self, head, stacked, kc, vc, toks, lengths,
                         last, active, remaining, eos, poison):
        """``chunk`` speculative steps in ONE dispatch: draft on device
        from the history buffer, verify K candidates per slot in one
        pass, accept the longest greedy-matching run, early-stop per
        slot on eos/budget — the host never syncs mid-chunk (the old
        one-step-per-dispatch version paid 2+ tunnel round-trips per
        verify, which dominated the measurement on remote PJRT).

        Emits (chunk, S, K) predictions + (chunk, S) accepted counts;
        the host applies them in order after the dispatch."""
        K = self.spec_k

        def one(carry, _):
            kc, vc, toks, lengths, last, active, remaining = carry
            cand = self._draft_device(toks, lengths, last)
            kc, vc, pred, n_acc, bad = self._verify_impl(
                head, stacked, kc, vc, lengths, cand, poison)
            # inactive slots keep computing from stale state inside the
            # chunk; a non-finite there must not retroactively fail a
            # request that already completed (same mask as _one_token)
            bad = bad & active
            n_raw = jnp.where(bad, 0, n_acc + 1)
            # eos truncation: keep tokens up to and including the first
            # eos among the accepted run
            j = jnp.arange(K)[None, :]
            is_eos = ((pred == eos[:, None]) & (eos >= 0)[:, None]
                      & (j < n_raw[:, None]))
            any_eos = jnp.any(is_eos, axis=1)
            first_eos = jnp.argmax(is_eos, axis=1)
            n_eff = jnp.where(any_eos, first_eos + 1, n_raw)
            n_eff = jnp.minimum(n_eff, remaining)
            n_eff = jnp.where(active, n_eff, 0)
            new_last = jnp.take_along_axis(
                pred, jnp.maximum(n_eff - 1, 0)[:, None], axis=1)[:, 0]
            last = jnp.where(n_eff > 0, new_last, last)
            # history append: pred[j] is the token at absolute position
            # lengths+1+j. All K values are written (garbage beyond
            # n_eff is overwritten by the next step's window or masked
            # by lengths on read); at the very end of a slot's budget
            # the window can touch [T-K, T) via DUS clamping — the slot
            # is retiring, its history is never read again.
            for s in range(self.S):
                toks = lax.dynamic_update_slice(
                    toks, pred[s:s + 1], (s, lengths[s] + 1))
            remaining = remaining - n_eff
            lengths = lengths + n_eff
            emitted_eos = any_eos & (first_eos < n_eff)
            active = active & ~bad & ~emitted_eos & (remaining > 0)
            return (kc, vc, toks, lengths, last, active, remaining), \
                (pred, n_eff, bad)

        (kc, vc, toks, lengths, last, active, remaining), \
            (preds, effs, bads) \
            = lax.scan(one, (kc, vc, toks, lengths, last, active,
                             remaining), None, length=self.chunk)
        return (kc, vc, toks, lengths, last, active, remaining, preds,
                effs, bads)

    def _prefill_impl(self, head, stacked, kc, vc, toks, lengths, last,
                      active, slot, tokens, start, true_total, is_final,
                      rng):
        """Run one prompt chunk through the slot's cache slice; on the
        final chunk, sample the first generated token and activate the
        slot. `tokens` is (1, bucket) — one compile per bucket size.
        The chunk is also recorded in the device history buffer (the
        speculative path drafts from it)."""
        cfg = self.cfg
        L, bucket = cfg.n_layers, tokens.shape[1]
        sl = (L, 1, cfg.kv_heads, self.T, cfg.head_dim)
        kcs = lax.dynamic_slice(kc, (0, slot, 0, 0, 0), sl)
        vcs = lax.dynamic_slice(vc, (0, slot, 0, 0, 0), sl)

        x = jnp.take(head["wte"], tokens, axis=0)
        if head["wpe"] is not None:
            x = x + lax.dynamic_slice_in_dim(head["wpe"], start, bucket)

        def layer(x, blk_kv):
            blk, k_l, v_l = blk_kv
            x, (k_l, v_l) = blk.forward_cached(x, (k_l, v_l), start)
            return x, (k_l, v_l)

        x, (kcs, vcs) = lax.scan(layer, x, (stacked, kcs, vcs))
        kc = lax.dynamic_update_slice(kc, kcs, (0, slot, 0, 0, 0))
        vc = lax.dynamic_update_slice(vc, vcs, (0, slot, 0, 0, 0))

        idx = jnp.clip(true_total - 1 - start, 0, bucket - 1)
        logits = self._lm_head(head, x[:, idx][:, None])[:, 0]
        temperature, top_p, top_k = self.sample
        rng, k = jax.random.split(rng)
        nxt = gpt_lib._sample_token(logits.astype(jnp.float32), k,
                                    temperature, top_p, top_k)[0]
        # history: the prompt chunk at [start, start+bucket) (zero pads
        # beyond the prompt are never read), and on the final chunk the
        # pending first generated token at index true_total
        toks = lax.dynamic_update_slice(toks, tokens, (slot, start))
        toks = jnp.where(
            is_final,
            lax.dynamic_update_slice(toks, nxt.reshape(1, 1),
                                     (slot, true_total)), toks)
        onehot = jnp.arange(self.S) == slot
        upd = jnp.logical_and(onehot, is_final)
        lengths = jnp.where(upd, true_total, lengths)
        last = jnp.where(upd, nxt, last)
        active = jnp.logical_or(active, upd)
        return kc, vc, toks, lengths, last, active, rng

    # -- scheduler ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None) -> Request:
        """``deadline_s``: wall-time budget for this request (queue wait
        included). A request past its deadline is evicted alone — the
        batch keeps serving its peers."""
        import time
        prompt = list(np.asarray(prompt).reshape(-1))
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.T:
            raise ValueError(
                f"{len(prompt)} prompt + {max_new_tokens} new tokens "
                f"exceed cache length {self.T}")
        if self.spec_k and (len(prompt) + max_new_tokens
                            + self.spec_k - 1 > self.T):
            raise ValueError(
                f"speculative window: prompt + new + K-1 "
                f"({len(prompt)}+{max_new_tokens}+{self.spec_k - 1}) "
                f"exceed cache length {self.T}")
        req = Request(prompt, max_new_tokens, eos_id,
                      deadline=(None if deadline_s is None
                                else time.monotonic() + deadline_s))
        self._waiting.append(req)
        return req

    def _free_slot(self) -> Optional[int]:
        for s, r in enumerate(self._slot_req):
            if r is None:
                return s
        return None

    def _admit(self, req: Request, slot: int):
        from paddle_tpu.observability import trace
        prompt = np.asarray(req.prompt, np.int32)
        total = len(prompt)
        start = 0
        with trace.span("serve/admit", slot=slot, prompt=total):
            while start < total:
                remaining = total - start
                bucket = next((x for x in self.buckets if x >= remaining),
                              self.buckets[-1])
                s0 = start
                if s0 + bucket > self.T:
                    # tail window would overrun the cache: slide it back
                    # over already-prefilled positions — same tokens at the
                    # same positions recompute the identical K/V, so the
                    # overlapped rewrite is a no-op and the write stays in
                    # bounds
                    s0 = self.T - bucket
                n = min(total - s0, bucket)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :n] = prompt[s0:s0 + n]
                is_final = s0 + n >= total
                with trace.span("serve/prefill", bucket=bucket):
                    (self.kc, self.vc, self.toks, self.lengths, self.last,
                     self.active, self._rng) = self._prefill_fn(
                        self._head, self._stacked, self.kc, self.vc,
                        self.toks, self.lengths, self.last, self.active,
                        jnp.int32(slot), jnp.asarray(padded),
                        jnp.int32(s0), jnp.int32(total),
                        jnp.asarray(is_final), self._rng)
                start = s0 + n
        self._slot_req[slot] = req
        # the prefill's sampled token is the first generated token
        self._emit(slot, req, int(np.asarray(self.last)[slot]))

    def _emit(self, slot: int, req: Request, token: int):
        req.tokens.append(token)
        self._obs_first_token(req)
        hit_eos = req.eos_id is not None and token == req.eos_id
        if hit_eos or len(req.tokens) >= req.max_new_tokens:
            req.done = True
            self._slot_req[slot] = None
            self.active = self.active.at[slot].set(False)
            self._obs_request_end(req)

    def step(self) -> int:
        """Evict past-deadline requests, admit what fits, then advance
        every active slot (one token, or up to K with speculative
        decoding). Returns tokens emitted."""
        import time
        from paddle_tpu.observability import trace
        t0 = time.perf_counter()
        with trace.span("serve/step") as sp:
            self._evict_expired()
            while self._waiting:
                slot = self._free_slot()
                if slot is None:
                    break
                self._admit(self._waiting.popleft(), slot)
            live = [(s, r) for s, r in enumerate(self._slot_req)
                    if r is not None]
            if not live:
                return 0
            self.steps += 1
            if self.spec_k:
                n = self._spec_step(live)
            elif self.chunk > 1:
                n = self._chunk_step(live)
            else:
                with trace.span("serve/dispatch", kind="single"):
                    (self.kc, self.vc, self.lengths, self.last,
                     self._rng, bad) = self._step_fn(
                        self._head, self._stacked, self.kc, self.vc,
                        self.lengths, self.last, self.active, self._rng,
                        self._poison_mask())
                emitted = np.asarray(self.last)
                bad = np.asarray(bad)
                n = 0
                for slot, req in live:
                    if bad[slot]:
                        self._fail(req, "non-finite logits", slot=slot,
                                   stat="serve/nonfinite_evictions")
                    else:
                        self._emit(slot, req, int(emitted[slot]))
                        n += 1
            sp.attrs["active"] = len(live)
            sp.attrs["tokens"] = n
        self._obs_step(t0, n, len(live))
        self.tokens_emitted += n
        return n

    def _marshal_limits(self, live):
        """Per-slot token budgets + eos ids for a chunked dispatch."""
        remaining = np.zeros((self.S,), np.int32)
        eos = np.full((self.S,), -1, np.int32)
        for slot, req in live:
            remaining[slot] = req.max_new_tokens - len(req.tokens)
            if req.eos_id is not None:
                eos[slot] = req.eos_id
        return jnp.asarray(remaining), jnp.asarray(eos)

    def _retire_done(self, live):
        """Free slots whose request hit its budget or eos (mirrors the
        device-side early-stop) — shared by both chunked paths."""
        for slot, req in live:
            if len(req.tokens) >= req.max_new_tokens or (
                    req.eos_id is not None and req.tokens
                    and req.tokens[-1] == req.eos_id):
                req.done = True
                self._slot_req[slot] = None
                self._obs_request_end(req)

    def _chunk_step(self, live) -> int:
        """One dispatch advancing every live slot up to ``chunk`` tokens,
        early-stopping per slot device-side (eos / budget / non-finite
        logits — the last evicting only the poisoned request)."""
        from paddle_tpu.observability import trace
        remaining, eos = self._marshal_limits(live)
        with trace.span("serve/dispatch", kind="chunk", chunk=self.chunk):
            (self.kc, self.vc, self.lengths, self.last, self.active,
             _, self._rng, toks, flags, bads) = self._multi_fn(
                self._head, self._stacked, self.kc, self.vc, self.lengths,
                self.last, self.active, remaining, eos, self._rng,
                self._poison_mask())
        toks = np.asarray(toks)
        flags = np.asarray(flags)
        bads = np.asarray(bads)
        total = 0
        for slot, req in live:
            for j in range(self.chunk):
                if flags[j, slot]:
                    req.tokens.append(int(toks[j, slot]))
                    total += 1
            if bads[:, slot].any():
                self._fail(req, "non-finite logits", slot=slot,
                           stat="serve/nonfinite_evictions")
        self._retire_done(live)
        return total

    def _spec_step(self, live) -> int:
        """One dispatch of ``chunk`` speculative steps: drafts, verify,
        acceptance, eos/budget early-stop all on device; the host only
        replays the emitted (step, slot, count) runs into Requests."""
        from paddle_tpu.observability import trace
        remaining, eos = self._marshal_limits(live)
        with trace.span("serve/dispatch", kind="spec", k=self.spec_k,
                        chunk=self.chunk):
            (self.kc, self.vc, self.toks, self.lengths, self.last,
             self.active, _, preds, effs, bads) = self._verify_fn(
                self._head, self._stacked, self.kc, self.vc, self.toks,
                self.lengths, self.last, self.active, remaining, eos,
                self._poison_mask())
        preds = np.asarray(preds)      # (chunk, S, K)
        effs = np.asarray(effs)        # (chunk, S)
        bads = np.asarray(bads)        # (chunk, S)
        total = 0
        for slot, req in live:
            for j in range(self.chunk):
                for t in range(int(effs[j, slot])):
                    req.tokens.append(int(preds[j, slot, t]))
                    total += 1
            if bads[:, slot].any():
                self._fail(req, "non-finite logits", slot=slot,
                           stat="serve/nonfinite_evictions")
        self._retire_done(live)
        return total

    def run(self) -> None:
        """Drain: run steps until every submitted request is done."""
        while self._waiting or any(r is not None for r in self._slot_req):
            self.step()

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self._slot_req)


def decode_roofline_tokens_per_sec(cfg, batch: int, context: int,
                                   hbm_gbps: float,
                                   weight_bytes: int = 2,
                                   cache_bytes: int = 2) -> float:
    """HBM-bandwidth upper bound on decode throughput.

    Per decode step the chip must read every weight once (batch-amortized)
    plus each sequence's KV prefix: steps/s = BW / (W + B * kv_bytes),
    tok/s = B * steps/s. This is the number BENCH compares achieved decode
    against (VERDICT r4: r02 decode sat at ~43% of this bound).
    """
    n = cfg.num_params()
    kv_heads = getattr(cfg, "kv_heads", cfg.n_heads)  # GQA shrinks this
    kv = 2 * cfg.n_layers * kv_heads * cfg.head_dim * context
    step_bytes = n * weight_bytes + batch * kv * cache_bytes
    return batch * hbm_gbps * 1e9 / step_bytes
