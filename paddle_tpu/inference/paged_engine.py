"""Paged continuous-batching decode engine: serving over a shared page
pool (the memory model half of vLLM-style serving; no reference analog —
the reference's fused_multi_transformer serves one contiguous CacheKV
per sequence).

Where `DecodeEngine` reserves max_len cache for every slot, this engine
holds ceil(len/page) pages per sequence from one pool and frees them at
retirement — HBM scales with the sum of LIVE tokens, so many more
sequences fit in flight at mixed lengths.

TPU design decisions:

- **Layer-folded pool**: the per-layer pools are one (L*P, Hkv, page, D)
  array; layer l's view of page p is id ``l*P + p``. The paged kernel
  receives the WHOLE pool and the per-layer table (``l*P + table``)
  selects its pages at DMA-schedule time — no per-layer slicing of the
  pool (a lax.dynamic_slice there would copy the full layer pool every
  step).
- **Fused append+attend decode step** (default; ``PT_PAGED_FUSED=0``
  falls back): each layer calls `paged_append_attend`, which folds the
  current token's fresh KV row into the online softmax AND writes it
  into its pool page inside the same kernel launch
  (``input_output_aliases`` on the layer-folded pools; the write target
  is derived from the block table + per-slot length, inactive slots
  write the scratch page). The separate one-batched-scatter-per-cache-
  per-token the read-only formulation paid (`_write_token_rows`) is
  gone from the dispatch path. History: the original write-first form
  (per-layer scatter with the pools as layer-scan carry) measured
  ~0.05x of the HBM roofline on hardware; the read-only-pool form
  (`paged_decode_attention(return_stats=True)` + `fold_fresh_row` +
  one scatter per token) measured 0.17x; fusing the write removes the
  remaining extra pool traffic per token (ISSUE 6).
- **Prefix/radix caching** (default; ``PT_PAGED_PREFIX=0`` disables):
  the page pool doubles as a shared radix store
  (`inference/prefix_cache.py`). ``submit``'s admission looks up the
  longest cached prefix by page-aligned token-hash chain, maps those
  pages into the slot's table READ-ONLY (refcounted; copy-on-write on
  the first partial page when an exact-multiple prompt matches in
  full), and prefills ONLY the suffix — each layer writes the suffix
  KV rows into the slot's pages and attends over [cached prefix +
  suffix causal] via the paged kernel with one query row per suffix
  position. Retirement decrements refcounts instead of freeing;
  refcount-zero prefix pages sit in an LRU and are reclaimed under
  pool pressure.
- **One-pass bucketed prefill**: a prompt attends only to itself
  (causal), so prefill needs NO cache reads — the whole prompt runs
  through the dense forward at a power-of-two bucket and the valid KV
  rows bulk-write into the sequence's pages per page-run. Prompts are
  therefore capped at the largest bucket (512) — longer prompts belong
  to the slot-contiguous `DecodeEngine`, which chunk-prefills.
- **Chunked device-side stepping**: like `DecodeEngine`, ``chunk``
  tokens per dispatch with per-slot eos/budget early-stop; pages for
  the whole chunk are reserved up front so the table is static inside
  the dispatch.
- **Pipelined dispatch** (``PT_SERVE_INFLIGHT``, default 2): dispatch
  and harvest halves exactly as in `DecodeEngine` — each harvested
  dispatch costs ONE packed device→host transfer (the old `_step_inner`
  materialized lengths, tokens, flags and bads separately), budgets/eos
  ids persist on device, and page reservation runs against a host
  shadow of per-slot lengths (`_host_len` exact at harvest, `_proj_len`
  an upper bound over in-flight dispatches, capped at the request's
  prompt+budget so projection never over-reserves the pool). The page
  table uploads only when a reservation actually grows a table.
  docs/serving.md.

Greedy only (the paged pool is a serving-memory feature; sampling policy
work stays in `DecodeEngine`).
"""

import collections
import math
import os
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.models import gpt as gpt_lib
from paddle_tpu.inference.decode_engine import (Request,
                                                ResilientScheduler,
                                                _Inflight,
                                                _note_retrace,
                                                prompt_lookup_draft,
                                                spec_accept)
from paddle_tpu.inference.prefix_cache import PrefixCache
from paddle_tpu.ops.pallas.decode_attention import fold_fresh_row
from paddle_tpu.ops.pallas.decode_megakernel import (_WEIGHT_ORDER,
                                                     mega_decode_layers,
                                                     mega_logits_sample)
from paddle_tpu.ops.pallas.paged_attention import (paged_append_attend,
                                                   paged_decode_attention)

__all__ = ["PagedDecodeEngine"]


class _HandoffRequest(Request):
    """A request whose KV state was built on another replica: carries
    the wire pages, the tokens generated so far (one, right after
    prefill; more when a draining replica migrated it mid-decode), and
    the valid-row count until admission installs them
    (``PagedDecodeEngine._admit_handoff``)."""

    __slots__ = ("kv_first", "kv_pages", "kv_wire", "kv_tokens",
                 "kv_ntok")


class PagedDecodeEngine(ResilientScheduler):
    """Continuous-batching greedy generation over a paged KV pool.

        eng = PagedDecodeEngine(model, n_pages=64, max_slots=8)
        r = eng.submit(prompt, max_new_tokens=64, eos_id=2)
        eng.run()                                  # r.tokens

    Status (r5 hardware): output is bit-identical to ``gpt.generate``
    across page/chunk geometries and serving HBM scales with live
    tokens. The first on-chip exercise of the write-first form (pools
    as layer-scan carry, one scatter per layer per token) measured
    ~0.05x of the HBM roofline; the current read-only-pool
    formulation (analytic fresh-row fold + one scatter per token)
    measured 0.17x on the same workload, vs 0.53x for the contiguous
    DecodeEngine. The remaining known gap: one pallas launch per
    layer per token over a mostly-masked fixed-width table is
    dispatch-heavy at short cache lengths — a table-width-bucketed
    kernel or a dense fallback below ~page_size tokens is the next
    optimization."""

    def __init__(self, model, n_pages: int, max_slots: int = 8,
                 page_size: int = 128, steps_per_call: int = 1,
                 buckets=(16, 32, 64, 128, 256, 512),
                 share_weights_with=None, inflight=None,
                 warmup: bool = False, fused: Optional[bool] = None,
                 prefix: Optional[bool] = None,
                 prefill_only: bool = False,
                 mega: Optional[bool] = None,
                 speculative_k: int = 0):
        from paddle_tpu import compile_cache
        from paddle_tpu.inference.decode_engine import (
            resolve_engine_weights)
        compile_cache.guard()
        cfg, head, stacked = resolve_engine_weights(model,
                                                    share_weights_with)
        if page_size % 128:
            raise ValueError("page_size must be a multiple of 128")
        self.cfg = cfg
        self.S = int(max_slots)
        self.page = int(page_size)
        self.P = int(n_pages)
        self.chunk = int(steps_per_call)
        self.buckets = sorted(b for b in buckets
                              if b <= cfg.max_seq_len)
        for b in self.buckets:
            if b > self.page and b % self.page:
                # the prefill page-run copy slices fixed page windows
                # out of the bucket; a non-dividing page size would
                # clamp the source start and copy the wrong rows
                raise ValueError(
                    f"page_size {self.page} must divide every bucket "
                    f"above it (bucket {b})")
        self._head, self._stacked = head, stacked
        L = cfg.n_layers
        # layer-folded pools: page p of layer l lives at row l*P + p.
        # ONE extra row at the very end is the scratch page: idle slots'
        # step writes land there instead of corrupting pool page 0
        # (their padded tables point at page id 0).
        shape = (L * self.P + 1, cfg.kv_heads, self.page, cfg.head_dim)
        self.kp = jnp.zeros(shape, cfg.dtype)
        self.vp = jnp.zeros(shape, cfg.dtype)
        self._scratch = L * self.P
        from paddle_tpu.ops.pallas.paged_attention import PageAllocator
        self._alloc = PageAllocator(self.P, self.page)
        # fused append+attend is the default; PT_PAGED_FUSED=0 restores
        # the read-only-pool + one-scatter-per-token formulation (the
        # parity reference the fused path is tested against)
        self.fused = (os.environ.get("PT_PAGED_FUSED", "1") != "0"
                      if fused is None else bool(fused))
        # single-dispatch decode (docs/serving.md "Single-dispatch
        # decode"): the layer-folded megakernel + fused sampling
        # epilogue collapse each decode step to TWO kernel launches
        # (vs one paged launch per layer). Requires the fused path —
        # the per-layer fused kernel stays as the bit-parity reference
        # (PT_PAGED_MEGA=0 or mega=False falls back to it).
        self.mega = ((os.environ.get("PT_PAGED_MEGA", "1") != "0"
                      if mega is None else bool(mega)) and self.fused)
        # speculative decode rides the paged step (r05 retired the
        # contiguous-only row): drafts come from the shared on-device
        # prompt-lookup helper, and with mega on, verify/accept run as
        # the SAME single-dispatch program at K rows per slot
        self.spec_k = int(speculative_k)
        if self.spec_k and self.spec_k < 2:
            raise ValueError("speculative_k must be >= 2 (one input "
                             "token + at least one candidate)")
        prefix_on = (os.environ.get("PT_PAGED_PREFIX", "1") != "0"
                     if prefix is None else bool(prefix))
        self._prefix = (PrefixCache(self._alloc, self.page)
                        if prefix_on else None)
        # disaggregated serving (docs/serving.md): a prefill-only
        # engine admits + prefills but never activates decode — the
        # finished pages leave via detach_handoff; fleet is an optional
        # FleetPrefixDirectory (serving/disagg.py) consulted at
        # admission when the local prefix cache misses
        self.prefill_only = bool(prefill_only)
        if self.prefill_only:
            # role-tagged first-token metric: this engine's "first
            # token" marks the END of prefill, never a client TTFT —
            # fleet-merged serve/ttft_s stays decode-side only
            self._ttft_metric = "serve/prefill_s"
        self.fleet = None
        # pages whose KV arrived over a LOSSY wire (int8/fp8 handoff or
        # fleet fetch): fine to serve and to share locally, but never
        # re-published to the fleet under the original content digest —
        # re-quantizing already-quantized pages would compound the
        # half-step error without bound across hops
        self._lossy_pids: set = set()
        self._tables: List[List[int]] = [[] for _ in range(self.S)]
        # slots evicted for non-finite logits: their pages are scrubbed
        # (zeroed) as they return to the free list (see _release)
        self._tainted: set = set()
        self.lengths = jnp.zeros((self.S,), jnp.int32)
        self.last = jnp.zeros((self.S,), jnp.int32)
        self.active = jnp.zeros((self.S,), bool)
        # budgets / eos ids persist on device across dispatches (set at
        # admission) — pipelined dispatches need no host marshalling
        self.remaining = jnp.zeros((self.S,), jnp.int32)
        self.eos_ids = jnp.full((self.S,), -1, jnp.int32)
        # device-side token history (prompt + generated) feeding the
        # on-device prompt-lookup drafts — speculative only (the plain
        # paged step never reads it)
        self.toks = (jnp.zeros((self.S, cfg.max_seq_len), jnp.int32)
                     if self.spec_k else None)
        self._slot_req: List[Optional[Request]] = [None] * self.S
        self._waiting: collections.deque = collections.deque()
        self.steps = 0
        self.tokens_emitted = 0
        self._prefill_fn = jax.jit(self._prefill_impl,
                                   donate_argnums=(2, 3))
        self._prefill_sfx_fn = jax.jit(self._prefill_suffix_impl,
                                       donate_argnums=(2, 3))
        self._multi_fn = jax.jit(self._multi_impl, donate_argnums=(2, 3))
        # table (arg 4) is NEVER donated: the cached device copy
        # (_table_dev) is reused across dispatches
        self._verify_fn = jax.jit(self._spec_multi_impl,
                                  donate_argnums=(2, 3, 5))
        self._init_pipeline(inflight)
        # host shadows for page reservation: _host_len is the harvested
        # (exact) device length; _proj_len an upper bound including
        # in-flight dispatches (each grows a slot by <= chunk tokens)
        self._host_len = np.zeros((self.S,), np.int64)
        self._proj_len = np.zeros((self.S,), np.int64)
        self._table_dev = None       # cached device page table
        self._table_dirty = True
        self._update_pool_gauges()
        if os.environ.get("PT_PAGED_TUNE", "0") == "1":
            # tune BEFORE any trace: the kernels read the tuned
            # (pages_per_program, head_block) from the autotune cache
            # at trace time, so warmup traces pick it up
            self.autotune()
        if warmup:
            self.warmup()

    # -- pool bookkeeping ---------------------------------------------------

    @property
    def free_pages(self) -> int:
        return self._alloc.free_pages

    @property
    def kv_bytes(self) -> int:
        """Outstanding KV bytes (pages mapped by slots, both pools) —
        the decode-placement load gauge the disaggregated router reads
        from the heartbeat (membership.heartbeat(load=...))."""
        per_page = (2 * self.cfg.n_layers * self.cfg.kv_heads
                    * self.page * self.cfg.head_dim
                    * np.dtype(self.kp.dtype).itemsize)
        return sum(len(t) for t in self._tables) * per_page

    def _update_pool_gauges(self):
        from paddle_tpu import stats
        stats.set_value("serve/pool_pages_free", self._alloc.free_pages)
        if self._prefix is not None:
            stats.set_value("serve/pool_pages_shared",
                            self._prefix.shared_pages)
            stats.set_value("serve/pool_pages_cached",
                            self._prefix.cached_pages)

    def _reserve(self, slot: int, n_tokens: int):
        before = len(self._tables[slot])
        tab = self._tables[slot]
        try:
            self._alloc.reserve(tab, n_tokens)
        except MemoryError:
            # pool pressure: reclaim LRU refcount-zero prefix pages
            # (warm cache, not live sequences) before giving up
            need = (n_tokens + self.page - 1) // self.page - len(tab)
            if (self._prefix is None or self._prefix.reclaim(
                    need - self._alloc.free_pages) == 0):
                raise
            self._alloc.reserve(tab, n_tokens)
        if len(tab) != before:
            self._table_dirty = True
            self._update_pool_gauges()

    def _release(self, slot: int):
        tab = self._tables[slot]
        if tab:
            self._table_dirty = True
        scrub: List[int] = []
        if self._prefix is not None:
            # cached (trie-held) pages are refcounted, not freed: at
            # zero they move to the reclaimable LRU with their KV warm.
            # (Filter on the PRE-unref keep set: unref of an invalidated
            # page frees it and drops ownership, and re-testing owns()
            # afterwards would double-release it to the allocator.)
            keep = [p for p in tab if self._prefix.owns(p)]
            for p in keep:
                if self._prefix.unref(p) is not None:
                    scrub.append(p)
            kept = set(keep)
            tab[:] = [p for p in tab if p not in kept]
        if slot in self._tainted:
            # non-finite eviction: the slot's private pages hold KV
            # computed from poisoned activations — scrub them on the
            # way back to the free list, or the nan rows resurface as
            # masked-row residue in whatever sequence reuses the page
            # (additive attention masking keeps nan alive: nan+bias=nan)
            self._tainted.discard(slot)
            scrub.extend(tab)
        self._alloc.release(tab)
        self._lossy_pids.difference_update(tab)
        self._lossy_pids.difference_update(scrub)
        if scrub:
            self._scrub_pages(scrub)
        self._update_pool_gauges()

    def _scrub_pages(self, pids):
        """Zero ``pids``' KV rows in both pools (every layer's view).
        Only the poison path pays this: the pool is recycled without
        zero-on-alloc, so pages freed from a non-finite-evicted slot or
        an invalidated prefix must not carry their nan rows into the
        next sequence that maps them."""
        # ptlint: disable=PT001 -- pids is a host int list (slot table
        # entries); this builds an index upload, never a device sync
        pid_rows = np.asarray(pids, np.int32)[None, :]
        ids = (np.arange(self.cfg.n_layers)[:, None] * self.P
               + pid_rows).ravel()
        self.kp = self.kp.at[ids].set(0)
        self.vp = self.vp.at[ids].set(0)

    def autotune(self, iters: int = 3, candidates=None):
        """Measure paged-kernel geometry candidates on this engine's
        shape family ((page, Hkv, D, dtype, group)) and persist the
        winner in the autotune cache (`ops/pallas/autotune.py`). Run
        BEFORE ``warmup()`` / the first request: Pallas grids are
        trace-time constants, so already-traced dispatch functions keep
        whatever config they saw. ``PT_PAGED_TUNE=1`` runs this from
        the constructor automatically."""
        from paddle_tpu.ops.pallas.paged_attention import (
            tune_paged_attention)
        cfg = self.cfg
        mx = (cfg.max_seq_len + self.page - 1) // self.page
        # representative shapes: full batch, mid-length rows, distinct
        # in-range pages (page ids only steer DMA addresses; the values
        # don't change the kernel's work)
        q = jnp.zeros((self.S, cfg.n_heads, cfg.head_dim), cfg.dtype)
        table = jnp.asarray(
            np.arange(self.S * mx, dtype=np.int32).reshape(self.S, mx)
            % self.P)
        lengths = jnp.full((self.S,), max(1, cfg.max_seq_len // 2),
                           jnp.int32)
        res = tune_paged_attention(q, self.kp, self.vp, table, lengths,
                                   fused=self.fused, iters=iters,
                                   candidates=candidates)
        if self.mega:
            # the megakernel's sampling epilogue has its own knob (the
            # vocab-tile width) keyed on the FOLDED geometry
            from paddle_tpu.ops.pallas.decode_megakernel import (
                tune_mega_epilogue)
            head = self._head
            x = jnp.zeros((self.S, cfg.d_model), cfg.dtype)
            w = (head["wte"].T if head["lm_head"] is None
                 else head["lm_head"])
            tune_mega_epilogue(x, head["lnf_scale"], head["lnf_bias"],
                               w, layers=cfg.n_layers, page=self.page,
                               iters=iters)
        return res

    def _table_array(self) -> jnp.ndarray:
        """(S, max_pages) padded page table at a FIXED width
        (ceil(max_seq_len/page)) so the chunked step never recompiles
        as sequences grow; zeros beyond each slot's pages are never
        dereferenced thanks to the kernel's clamp."""
        mx = (self.cfg.max_seq_len + self.page - 1) // self.page
        out = np.zeros((self.S, mx), np.int32)
        for s, t in enumerate(self._tables):
            out[s, :len(t)] = t
        return jnp.asarray(out)

    def _table(self) -> jnp.ndarray:
        """The device page table, re-uploaded only when a reservation or
        release actually changed a table — steady-state decode reuses
        the cached device copy instead of paying a host→device transfer
        per dispatch."""
        if self._table_dirty or self._table_dev is None:
            self._table_dev = self._table_array()
            self._table_dirty = False
        return self._table_dev

    # -- jitted bodies ------------------------------------------------------

    def _lm_head(self, head, x):
        x = gpt_lib.final_ln(x, head["lnf_scale"], head["lnf_bias"])
        w = (head["wte"].T if head["lm_head"] is None
             else head["lm_head"])
        return x @ w

    def _write_token_rows(self, kp, vp, k_rows, v_rows, table, lengths,
                          active):
        """Write one decode step's new KV rows for EVERY layer at once:
        k_rows/v_rows (L, S, Hkv, D) land at position lengths[s] of
        slot s (page ids are layer-folded), in a single batched scatter
        per cache. Writing once per token OUTSIDE the layer scan keeps
        the pools read-only inside it — carrying the pools through the
        layer scan with a per-layer scatter is what the first hardware
        exercise measured at ~0.05x roofline. Inactive slots scatter
        into the scratch page — their padded tables point at pool page
        0, which a live sequence may own."""
        L = k_rows.shape[0]
        offs = lengths % self.page
        pidx = lengths // self.page
        base = jnp.take_along_axis(table, pidx[:, None], axis=1)[:, 0]
        pids = (jnp.arange(L, dtype=jnp.int32)[:, None] * self.P
                + base[None, :])
        pids = jnp.where(active[None, :], pids, self._scratch)
        offs_all = jnp.broadcast_to(offs[None, :], pids.shape)
        S = k_rows.shape[1]
        kp = kp.at[pids.reshape(-1), :, offs_all.reshape(-1), :].set(
            k_rows.reshape(L * S, *k_rows.shape[2:]))
        vp = vp.at[pids.reshape(-1), :, offs_all.reshape(-1), :].set(
            v_rows.reshape(L * S, *v_rows.shape[2:]))
        return kp, vp

    def _one_token(self, head, stacked, kp, vp, table, lengths, last,
                   active, poison):
        """Advance every active slot one token. Per-slot ``bad`` flags
        non-finite logits (numerical blowup or injected poison) — the
        slot stops advancing and the host evicts only that request.

        FUSED path (default): each layer calls `paged_append_attend` —
        the fresh KV row is folded into the online softmax AND written
        into its pool page inside the kernel (input/output-aliased
        pools carried through the layer scan; inactive slots' writes
        target the scratch page). No per-token scatter remains in the
        dispatch.

        Fallback (``PT_PAGED_FUSED=0``): the pools stay READ-ONLY
        inside the layer scan — `paged_decode_attention(return_stats)`
        plus the analytic `fold_fresh_row`, per-layer rows out as scan
        ys, ONE batched scatter per cache per token after the scan
        (the 0.17x-roofline formulation the fused path is parity-tested
        against)."""
        x = jnp.take(head["wte"], last, axis=0)
        if head["wpe"] is not None:
            x = x + jnp.take(head["wpe"], lengths, axis=0)
        x = x[:, None, :]
        L = self.cfg.n_layers
        scale = 1.0 / math.sqrt(self.cfg.head_dim)

        if self.fused:
            pidx = jnp.minimum(lengths // self.page, table.shape[1] - 1)
            base = jnp.take_along_axis(table, pidx[:, None],
                                       axis=1)[:, 0]

            def layer_body_fused(carry, blk_i):
                h, kp, vp = carry
                blk, i = blk_i
                q, k, v = blk._qkv(h, lengths)
                k_row = k[:, 0].astype(kp.dtype)
                v_row = v[:, 0].astype(vp.dtype)
                wpids = jnp.where(active, i * self.P + base,
                                  self._scratch)
                o, kp, vp = paged_append_attend(
                    q[:, 0].astype(kp.dtype), kp, vp, k_row, v_row,
                    i * self.P + table, wpids, lengths, scale=scale)
                attn = o.astype(h.dtype).reshape(h.shape)
                h = blk._block_tail(h, attn)
                return (h, kp, vp), None

            (x, kp, vp), _ = lax.scan(layer_body_fused, (x, kp, vp),
                                      (stacked, jnp.arange(L)))
        else:
            def layer_body(h, blk_i):
                blk, i = blk_i
                q, k, v = blk._qkv(h, lengths)
                k_row = k[:, 0].astype(kp.dtype)
                v_row = v[:, 0].astype(vp.dtype)
                o, m, l = paged_decode_attention(
                    q[:, 0].astype(kp.dtype), kp, vp, i * self.P + table,
                    lengths, scale=scale, return_stats=True)
                attn = fold_fresh_row(o, m, l, q[:, 0], k_row, v_row,
                                      scale, blk.n_heads // blk.kv_heads)
                attn = attn.astype(h.dtype).reshape(h.shape)
                h = blk._block_tail(h, attn)
                return h, (k_row, v_row)

            x, (k_rows, v_rows) = lax.scan(
                layer_body, x, (stacked, jnp.arange(L)))
            kp, vp = self._write_token_rows(kp, vp, k_rows, v_rows,
                                            table, lengths, active)
        logits = self._lm_head(head, x)[:, 0]
        logits = jnp.where(poison[:, None], jnp.nan, logits)
        bad = active & ~jnp.all(jnp.isfinite(logits), axis=-1)
        nxt = jnp.argmax(logits.astype(jnp.float32), -1).astype(jnp.int32)
        nxt = jnp.where(active & ~bad, nxt, last)
        lengths = lengths + (active & ~bad).astype(jnp.int32)
        return kp, vp, lengths, nxt, bad

    def _mega_rows(self, head, stacked, kp, vp, table, pos, row_slot,
                   row_write, tokens, poison_rows):
        """One megakernel pass over a flat row batch: embed ``tokens``
        at ``pos``, run every layer + the fused final-norm → logits →
        greedy-sampling epilogue as TWO kernel launches total, and
        return the sampled token + non-finite flag per row. The plain
        step is one row per slot; the speculative verify is K rows per
        slot through the SAME program (write-then-attend is causal:
        row t's attention bound pos+1 masks rows t' > t)."""
        cfg = self.cfg
        x = jnp.take(head["wte"], tokens, axis=0)
        if head["wpe"] is not None:
            x = x + jnp.take(head["wpe"], pos, axis=0)
        weights = {n: getattr(stacked, n) for n in _WEIGHT_ORDER}
        x, kp, vp = mega_decode_layers(
            x, weights, kp, vp, table, pos, row_slot, row_write,
            page=self.page, n_pages=self.P, n_heads=cfg.n_heads,
            kv_heads=cfg.kv_heads, head_dim=cfg.head_dim,
            rope=cfg.rope, rope_theta=cfg.rope_theta,
            scale=1.0 / math.sqrt(cfg.head_dim))
        w = (head["wte"].T if head["lm_head"] is None
             else head["lm_head"])
        tok, nf = mega_logits_sample(
            x, head["lnf_scale"], head["lnf_bias"], w, poison_rows,
            layers=cfg.n_layers, page=self.page)
        return kp, vp, tok, nf

    def _one_token_mega(self, head, stacked, kp, vp, table, lengths,
                        last, active, poison):
        """Single-dispatch variant of `_one_token` (``PT_PAGED_MEGA``):
        same signature, same greedy stream, ≤2 kernel launches. The
        per-layer fused path above stays as the bit-parity reference
        (token streams identical; pool rows agree to last-ulp — the
        megakernel folds the fresh KV row in page order while the
        per-layer kernel folds it after all pages, so layer>=1 rows
        may differ in the final bit of the accumulation)."""
        kp, vp, tok, nf = self._mega_rows(
            head, stacked, kp, vp, table, lengths,
            jnp.arange(self.S, dtype=jnp.int32),
            active.astype(jnp.int32), last, poison)
        bad = active & (nf > 0)
        nxt = jnp.where(active & ~bad, tok, last)
        lengths = lengths + (active & ~bad).astype(jnp.int32)
        return kp, vp, lengths, nxt, bad

    def _multi_impl(self, head, stacked, kp, vp, table, lengths, last,
                    active, remaining, eos, poison):
        """``chunk`` decode steps in one dispatch, per-slot eos/budget/
        non-finite early-stop device-side (pages for the whole chunk are
        reserved before the dispatch, so ``table`` is static here).
        Tokens, emit flags and non-finite flags come back PACKED into
        one (3, chunk, S) int32 array — the lagged harvest pays exactly
        one device→host transfer."""
        _note_retrace("paged_multi")
        one_tok = self._one_token_mega if self.mega else self._one_token

        def one(carry, _):
            kp, vp, lengths, last, active, remaining = carry
            kp, vp, lengths, nxt, bad = one_tok(
                head, stacked, kp, vp, table, lengths, last, active,
                poison)
            emit = active & ~bad
            remaining = remaining - emit.astype(jnp.int32)
            hit_eos = (nxt == eos) & (eos >= 0)
            active = active & ~bad & ~hit_eos & (remaining > 0)
            return (kp, vp, lengths, nxt, active, remaining), \
                (nxt, emit, bad)

        (kp, vp, lengths, last, active, remaining), (toks, flags, bads) = \
            lax.scan(one, (kp, vp, lengths, last, active, remaining),
                     None, length=self.chunk)
        packed = jnp.stack([toks, flags.astype(jnp.int32),
                            bads.astype(jnp.int32)])
        return kp, vp, lengths, last, active, remaining, packed

    def _verify_paged(self, head, stacked, kp, vp, table, lengths,
                      cand, active, poison):
        """One speculative verify over the page pool: K candidate
        tokens per slot in one pass. With mega on, the K rows per slot
        ride the SAME single-dispatch megakernel program as the plain
        step (flat (S*K) row batch, per-row position/slot); otherwise
        a per-layer XLA reference (batched pool scatter + the paged
        read kernel at one query row per candidate) — the parity
        target the mega verify is tested against. Returns the model's
        predictions (S, K), the accepted-prefix length n_acc (0..K-1)
        and the per-slot non-finite flag, exactly like
        `DecodeEngine._verify_impl`."""
        S, K = cand.shape
        cfg = self.cfg
        pos = lengths[:, None] + jnp.arange(K)              # (S, K)
        if self.mega:
            kp, vp, tok, nf = self._mega_rows(
                head, stacked, kp, vp, table, pos.reshape(-1),
                jnp.repeat(jnp.arange(S, dtype=jnp.int32), K),
                jnp.repeat(active.astype(jnp.int32), K),
                cand.reshape(-1), jnp.repeat(poison, K))
            pred = tok.reshape(S, K)
            bad = jnp.any((nf > 0).reshape(S, K), axis=1)
        else:
            x = jnp.take(head["wte"], cand, axis=0)
            if head["wpe"] is not None:
                x = x + jnp.take(head["wpe"], pos, axis=0)
            scale = 1.0 / math.sqrt(cfg.head_dim)
            mx = table.shape[1]
            pidx = jnp.minimum(pos // self.page, mx - 1)
            pages = jnp.take_along_axis(table, pidx, axis=1)
            offs = (pos % self.page).reshape(-1)
            lens_t = (pos + 1).reshape(-1)

            def layer(carry, blk_i):
                x, kp, vp = carry
                blk, i = blk_i
                q, k, v = blk._qkv(x, lengths)
                rows = jnp.where(active[:, None], i * self.P + pages,
                                 self._scratch).reshape(-1)
                kp = kp.at[rows, :, offs, :].set(
                    k.reshape(S * K, cfg.kv_heads,
                              cfg.head_dim).astype(kp.dtype))
                vp = vp.at[rows, :, offs, :].set(
                    v.reshape(S * K, cfg.kv_heads,
                              cfg.head_dim).astype(vp.dtype))
                o = paged_decode_attention(
                    q.reshape(S * K, cfg.n_heads,
                              cfg.head_dim).astype(kp.dtype),
                    kp, vp, jnp.repeat(i * self.P + table, K, axis=0),
                    lens_t, scale=scale)
                attn = o.astype(x.dtype).reshape(x.shape)
                return (blk._block_tail(x, attn), kp, vp), None

            (x, kp, vp), _ = lax.scan(
                layer, (x, kp, vp), (stacked, jnp.arange(cfg.n_layers)))
            logits = self._lm_head(head, x).astype(jnp.float32)
            logits = jnp.where(poison[:, None, None], jnp.nan, logits)
            bad = ~jnp.all(jnp.isfinite(logits), axis=(1, 2))
            pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        match = jnp.cumprod(
            (cand[:, 1:] == pred[:, :-1]).astype(jnp.int32), axis=1)
        n_acc = jnp.sum(match, axis=1)                      # 0..K-1
        return kp, vp, pred, n_acc, bad

    def _spec_multi_impl(self, head, stacked, kp, vp, table, toks,
                         lengths, last, active, remaining, eos, poison):
        """``chunk`` speculative steps in ONE dispatch over the page
        pool — draft on device (shared prompt-lookup helper), verify K
        candidates per slot, accept via the shared greedy-acceptance
        helper, early-stop per slot on eos/budget. Pages for the whole
        chunk (chunk * K rows) are reserved before the dispatch.
        Packed output (chunk, S, K+2) matches `DecodeEngine`'s spec
        records — the shared scheduler replay applies both."""
        _note_retrace("paged_spec")
        K = self.spec_k

        def one(carry, _):
            kp, vp, toks, lengths, last, active, remaining = carry
            cand = prompt_lookup_draft(toks, lengths, last, K)
            kp, vp, pred, n_acc, bad = self._verify_paged(
                head, stacked, kp, vp, table, lengths, cand, active,
                poison)
            n_eff, last, bad, emitted_eos = spec_accept(
                pred, n_acc, bad, active, remaining, eos, last)
            # history append (same DUS-window idiom as DecodeEngine's
            # spec chunk: garbage beyond n_eff is overwritten or masked
            # by lengths on read; inactive slots rewrite their window)
            for s in range(self.S):
                win = (s, lengths[s] + 1)
                old = lax.dynamic_slice(toks, win, (1, K))
                toks = lax.dynamic_update_slice(
                    toks, jnp.where(active[s], pred[s:s + 1], old), win)
            remaining = remaining - n_eff
            lengths = lengths + n_eff
            active = active & ~bad & ~emitted_eos & (remaining > 0)
            return (kp, vp, toks, lengths, last, active, remaining), \
                (pred, n_eff, bad)

        (kp, vp, toks, lengths, last, active, remaining), \
            (preds, effs, bads) \
            = lax.scan(one, (kp, vp, toks, lengths, last, active,
                             remaining), None, length=self.chunk)
        packed = jnp.concatenate(
            [preds, effs[..., None], bads[..., None].astype(jnp.int32)],
            axis=-1)
        return kp, vp, toks, lengths, last, active, remaining, packed

    def _prefill_impl(self, head, stacked, kp, vp, tokens, true_len,
                      write_segments):
        """One-pass prefill of ONE prompt (1, bucket): the prompt
        attends only to itself (causal), so no cache reads; the valid
        KV rows bulk-write into the sequence's pages per page-run.
        ``write_segments``: (n_seg, L, 3) int32 rows (dst_page_row,
        src_start, run) per layer — page-run copies resolved host-side
        (statically shaped per bucket: n_seg = ceil(bucket/page) + 1,
        padded with run=0)."""
        _note_retrace("paged_prefill")
        cfg = self.cfg
        x = jnp.take(head["wte"], tokens, axis=0)
        if head["wpe"] is not None:
            x = x + head["wpe"][None, :tokens.shape[1]]

        rows = []

        def layer_body(h, blk):
            q, k, v = blk._qkv(h, jnp.zeros((1,), jnp.int32))
            attn = gpt_lib.F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=0.0)
            attn = attn.reshape(h.shape).astype(h.dtype)
            return blk._block_tail(h, attn), (k[0], v[0])

        x, (ks, vs) = lax.scan(layer_body, x, stacked)
        # ks: (L, bucket, Hkv, D) -> (L, Hkv, bucket, D); pad the token
        # dim to at least one page so every page-window copy below has a
        # full source window (segments start page-aligned, so windows
        # never straddle the padded end)
        ks = jnp.swapaxes(ks, 1, 2).astype(kp.dtype)
        vs = jnp.swapaxes(vs, 1, 2).astype(vp.dtype)
        if ks.shape[2] < self.page:
            pad = self.page - ks.shape[2]
            ks = jnp.pad(ks, ((0, 0), (0, 0), (0, pad), (0, 0)))
            vs = jnp.pad(vs, ((0, 0), (0, 0), (0, pad), (0, 0)))

        def write_seg(i, kvp):
            kp, vp = kvp

            def write_layer(l, kvp):
                kp, vp = kvp
                dst, src, run = (write_segments[i, l, 0],
                                 write_segments[i, l, 1],
                                 write_segments[i, l, 2])
                # a zero-run segment writes a zero-length slice (no-op
                # via clamped dynamic_slice of size page then masked
                # merge): instead gate on run>0 with lax.cond
                def do(kvp):
                    kp, vp = kvp
                    # run is traced; copy a full page window and merge
                    # the first `run` rows (static window, masked merge)
                    ksrc = lax.dynamic_slice(
                        ks, (l, 0, src, 0),
                        (1, self.cfg.kv_heads, self.page,
                         self.cfg.head_dim))
                    vsrc = lax.dynamic_slice(
                        vs, (l, 0, src, 0),
                        (1, self.cfg.kv_heads, self.page,
                         self.cfg.head_dim))
                    old_k = lax.dynamic_slice(
                        kp, (dst, 0, 0, 0),
                        (1, self.cfg.kv_heads, self.page,
                         self.cfg.head_dim))
                    old_v = lax.dynamic_slice(
                        vp, (dst, 0, 0, 0),
                        (1, self.cfg.kv_heads, self.page,
                         self.cfg.head_dim))
                    m = (jnp.arange(self.page) < run)[None, None, :,
                                                      None]
                    km = jnp.where(m, ksrc, old_k)
                    vm = jnp.where(m, vsrc, old_v)
                    kp2 = lax.dynamic_update_slice(kp, km,
                                                   (dst, 0, 0, 0))
                    vp2 = lax.dynamic_update_slice(vp, vm,
                                                   (dst, 0, 0, 0))
                    return kp2, vp2

                return lax.cond(run > 0, do, lambda kvp: kvp, (kp, vp))

            return lax.fori_loop(0, self.cfg.n_layers, write_layer,
                                 (kp, vp))

        n_seg = write_segments.shape[0]
        kp, vp = lax.fori_loop(0, n_seg, write_seg, (kp, vp))
        idx = jnp.clip(true_len - 1, 0, tokens.shape[1] - 1)
        logits = self._lm_head(head, x[:, idx][:, None])[:, 0]
        nxt = jnp.argmax(logits.astype(jnp.float32), -1).astype(
            jnp.int32)[0]
        return kp, vp, nxt

    def _prefill_suffix_impl(self, head, stacked, kp, vp, tokens, sp,
                             true_n, segs, cow_src, cow_dst, table_row):
        """Suffix-only prefill over a CACHED prefix (one prompt whose
        first ``sp`` tokens' KV already sit in shared pages mapped into
        ``table_row``). The cached prefix's forward is never recomputed:
        per layer, the suffix tokens' KV rows are written into the
        slot's pages FIRST (page-run segments ``segs``: (pid, dst_off,
        src, run) int32, run=0 padding), then the paged kernel runs with
        ONE QUERY ROW PER SUFFIX POSITION — row t's length is
        ``sp + t + 1``, so it attends over [cached prefix + suffix
        causal] exactly (its own row included, already written).

        ``cow_src``/``cow_dst`` (-1 = none) implement copy-on-write for
        the exact-page-multiple full match: the last matched page is
        copied into a private page before the final token's KV row is
        written inside it.

        tokens: (1, bucket) suffix zero-padded; sp/true_n scalars
        (suffix = prompt[sp:true_n]); table_row: (max_pages,) this
        slot's UNFOLDED page table row."""
        _note_retrace("paged_prefill_suffix")
        cfg = self.cfg
        bucket = tokens.shape[1]
        L = cfg.n_layers
        scale = 1.0 / math.sqrt(cfg.head_dim)
        mx = table_row.shape[0]

        def do_cow(kvp):
            kp, vp = kvp
            src = jnp.arange(L, dtype=jnp.int32) * self.P + cow_src
            dst = jnp.arange(L, dtype=jnp.int32) * self.P + cow_dst
            return kp.at[dst].set(kp[src]), vp.at[dst].set(vp[src])

        kp, vp = lax.cond(cow_src >= 0, do_cow, lambda kvp: kvp,
                          (kp, vp))

        x = jnp.take(head["wte"], tokens, axis=0)
        if head["wpe"] is not None:
            # per-row clamped gather (not dynamic_slice: its clamped
            # START would shift REAL rows when sp + bucket overruns the
            # table; here only pad rows clamp, and they are unused)
            pos = jnp.clip(sp + jnp.arange(bucket), 0,
                           head["wpe"].shape[0] - 1)
            x = x + jnp.take(head["wpe"], pos, axis=0)[None]

        # row t of the suffix attends over min(sp + t + 1, n) tokens
        lens_t = jnp.minimum(
            sp + 1 + jnp.arange(bucket, dtype=jnp.int32), true_n)
        table_b = jnp.broadcast_to(table_row[None], (bucket, mx))

        def layer_body(carry, blk_i):
            h, kp, vp = carry
            blk, i = blk_i
            q, k, v = blk._qkv(h, jnp.reshape(sp, (1,)))
            # (1, bucket, Hkv, D) -> (Hkv, bucket, D), padded one page
            # on each side so every segment's full-page source window
            # (start = page + src - dst_off) stays in bounds
            ks = jnp.swapaxes(k, 1, 2)[0].astype(kp.dtype)
            vs = jnp.swapaxes(v, 1, 2)[0].astype(vp.dtype)
            ks = jnp.pad(ks, ((0, 0), (self.page, self.page), (0, 0)))
            vs = jnp.pad(vs, ((0, 0), (self.page, self.page), (0, 0)))

            def write_seg(j, kvp):
                kp, vp = kvp
                pid, off, src, run = (segs[j, 0], segs[j, 1],
                                      segs[j, 2], segs[j, 3])
                dst = i * self.P + pid

                def do(kvp):
                    kp, vp = kvp
                    start = self.page + src - off
                    kwin = lax.dynamic_slice(
                        ks, (0, start, 0),
                        (cfg.kv_heads, self.page, cfg.head_dim))
                    vwin = lax.dynamic_slice(
                        vs, (0, start, 0),
                        (cfg.kv_heads, self.page, cfg.head_dim))
                    old_k = lax.dynamic_slice(
                        kp, (dst, 0, 0, 0),
                        (1, cfg.kv_heads, self.page, cfg.head_dim))
                    old_v = lax.dynamic_slice(
                        vp, (dst, 0, 0, 0),
                        (1, cfg.kv_heads, self.page, cfg.head_dim))
                    ar = jnp.arange(self.page)
                    m = ((ar >= off) & (ar < off + run))[None, :, None]
                    km = jnp.where(m, kwin, old_k[0])[None]
                    vm = jnp.where(m, vwin, old_v[0])[None]
                    return (lax.dynamic_update_slice(kp, km,
                                                     (dst, 0, 0, 0)),
                            lax.dynamic_update_slice(vp, vm,
                                                     (dst, 0, 0, 0)))

                return lax.cond(run > 0, do, lambda kvp: kvp, (kp, vp))

            kp, vp = lax.fori_loop(0, segs.shape[0], write_seg,
                                   (kp, vp))
            o = paged_decode_attention(
                q[0].astype(kp.dtype), kp, vp, i * self.P + table_b,
                lens_t, scale=scale)
            attn = o.astype(h.dtype).reshape(h.shape)
            return (blk._block_tail(h, attn), kp, vp), None

        (x, kp, vp), _ = lax.scan(layer_body, (x, kp, vp),
                                  (stacked, jnp.arange(L)))
        idx = jnp.clip(true_n - sp - 1, 0, bucket - 1)
        logits = self._lm_head(head, x[:, idx][:, None])[:, 0]
        nxt = jnp.argmax(logits.astype(jnp.float32), -1).astype(
            jnp.int32)[0]
        return kp, vp, nxt

    # -- scheduler ----------------------------------------------------------

    def check_request(self, prompt_len: int, max_new_tokens: int):
        """Admission feasibility (see DecodeEngine.check_request)."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len > self.buckets[-1]:
            raise ValueError(
                f"paged prefill caps prompts at {self.buckets[-1]} "
                f"tokens (got {prompt_len}); use DecodeEngine for "
                f"longer prompts")
        if prompt_len + max_new_tokens > self.cfg.max_seq_len:
            raise ValueError("prompt + new tokens exceed max_seq_len")
        if self.spec_k and (prompt_len + max_new_tokens
                            + self.spec_k - 1 > self.cfg.max_seq_len):
            # the last accepted token's verify window wrote K-1 rows
            # past it — those positions must exist in the page table
            raise ValueError(
                f"prompt + new tokens + speculative window "
                f"({prompt_len}+{max_new_tokens}+{self.spec_k - 1}) "
                f"exceed max_seq_len {self.cfg.max_seq_len}")

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               req_id: Optional[str] = None) -> Request:
        import time
        prompt = list(np.asarray(prompt).reshape(-1))
        self.check_request(len(prompt), max_new_tokens)
        req = Request(prompt, max_new_tokens, eos_id,
                      deadline=(None if deadline_s is None
                                else time.monotonic() + deadline_s),
                      rid=req_id)
        self._waiting.append(req)
        return req

    def _free_slot(self) -> Optional[int]:
        for s, r in enumerate(self._slot_req):
            if r is None:
                return s
        return None

    def _on_evict(self, slot: int):
        """Eviction also returns the slot's pages to the pool (the dead
        sequence's memory is reclaimable at once)."""
        self._release(slot)
        super()._on_evict(slot)

    def _fail(self, req, reason, slot=None,
              stat="serve/deadline_evictions"):
        if slot is not None and stat == "serve/nonfinite_evictions":
            # a non-finite eviction means this slot's KV is suspect:
            # taint it so _release scrubs its private pages, and drop
            # every trie node its table maps, or a poisoned prefix
            # stays canonical and every future submit of the same
            # (popular) prompt maps the bad pages and fails — forever.
            # Current sharers keep their refs and fail loudly at their
            # own harvest; the next submit prefills cold into scrubbed
            # pages and re-registers a healthy copy.
            self._tainted.add(slot)
            if self._prefix is not None:
                for p in self._tables[slot]:
                    # never frees here — the slot's own mapping keeps
                    # refs >= 1, so the page dies (and is scrubbed)
                    # at this slot's _release via the unref path
                    self._prefix.invalidate(p)
        super()._fail(req, reason, slot, stat)

    def _match_prefix(self, prompt, slot):
        """Longest-cached-prefix lookup at admission: maps the matched
        pages into the slot's (empty) table read-only and returns
        ``(sp, cow_src, chain)`` — the suffix start (tokens served from
        cache), the COW source page (-1 = none), and the prompt's
        digest chain (reused by ``register`` so admission hashes the
        prompt exactly once). An exact-page-multiple full match keeps
        all but the last page: the final token must re-run for
        first-token logits and its KV row lands INSIDE the last matched
        page, so that page is copied to a private one (copy-on-write on
        the first partial page). Counters for the lookup land in
        ``_admit`` AFTER the reservation succeeds — a MemoryError-
        retried admission must not double-count its hit tokens.

        With a fleet directory attached, a LOCAL miss extends through
        the fleet: pages another replica registered are fetched over
        the KV wire, installed into private pages, ADOPTED into the
        local cache (so the retry path and every later submit see them
        as local hits), and the match continues — a prefix warm on any
        replica skips that prefill here too."""
        chain = self._prefix.chain(prompt)
        matched = self._prefix.lookup(prompt, chain=chain)
        if self.fleet is not None and len(matched) < len(chain):
            matched.extend(self._fleet_extend(chain, len(matched)))
        n = len(prompt)
        sp, cow_src = 0, -1
        if matched and len(matched) * self.page >= n:
            cow_src = matched[-1]
            self._prefix.unref(matched[-1])
            matched = matched[:-1]
            sp = n - 1
        elif matched:
            sp = len(matched) * self.page
        self._tables[slot][:] = matched
        if matched:
            self._table_dirty = True
        return sp, cow_src, chain

    def attach_fleet(self, fleet):
        """Wire a ``serving/disagg.FleetPrefixDirectory`` into this
        engine: admission lookups extend through the fleet on a local
        miss, newly-registered prefixes publish, and local
        invalidation/reclaim withdraws fleet-wide (the prefix cache's
        ``on_drop`` hook — BEFORE the freed page can be remapped, so no
        sharer ever fetches a stale digest)."""
        if self._prefix is None:
            raise ValueError("fleet prefix directory needs the local "
                             "prefix cache (PT_PAGED_PREFIX=1)")
        self.fleet = fleet

        def _drop(digest, pid):
            fleet.withdraw(digest)
            self._lossy_pids.discard(pid)

        self._prefix.on_drop = _drop

    def _alloc_one_page(self):
        """One free page for a fleet-fetched prefix, reclaiming LRU
        refcount-zero cache pages under pressure (same policy as
        ``_reserve``); raises MemoryError when the pool is truly
        full."""
        tmp: List[int] = []
        try:
            self._alloc.reserve(tmp, self.page)
        except MemoryError:
            if self._prefix.reclaim(1) == 0:
                raise
            self._alloc.reserve(tmp, self.page)
        return tmp[0]

    def _fleet_extend(self, chain, start):
        """Continue a local prefix match through the fleet directory:
        fetch each next digest's page over the KV wire, install it into
        a private page, adopt it into the local cache (ref'd for this
        admission), stop at the first fleet miss / pool-full. Counters:
        one ``serve/fleet_prefix_lookup`` per consulted admission,
        ``serve/fleet_prefix_hit_tokens`` per page of prefill skipped
        fleet-wide."""
        from paddle_tpu import stats
        got: List[int] = []
        uploads: List[tuple] = []         # (pid, k_page, v_page)
        stats.add("serve/fleet_prefix_lookup")
        for digest in chain[start:]:
            # a stale DESCENDANT may still be canonical locally (its
            # parent was reclaimed; lookup broke at the hole): revive
            # it instead of re-fetching — adopt would refuse it
            pid = self._prefix.revive(digest)
            if pid is not None:
                got.append(pid)
                continue
            try:
                res = self.fleet.fetch(digest)
            except RuntimeError:
                # the wire guard tripped on this fleet page (owner
                # published before its own poison detection, or store
                # corruption): expunge the entry so the fleet heals,
                # and prefill this prefix cold — ONE request pays a
                # cold prefill, the replica never dies of it
                self.fleet.withdraw(digest, force=True)
                res = None
            except TimeoutError:
                res = None              # store hiccup: treat as miss
            if res is None:
                break
            k_page, v_page = res          # (L, 1, Hkv, page, D) host
            try:
                pid = self._alloc_one_page()
            except MemoryError:
                break                     # partial fleet hit is fine
            self._prefix.adopt(digest, pid)
            if self.fleet.wire != "fp32":
                self._lossy_pids.add(pid)
            got.append(pid)
            uploads.append((pid, k_page, v_page))
            stats.add("serve/fleet_prefix_hit_tokens", self.page)
        if uploads:
            # ONE batched pool update per pool for the whole fetch run
            # (each .at[].set materializes a full pool copy — per-page
            # updates would pay 2m copies for m pages)
            L = self.cfg.n_layers
            # ptlint: disable=PT001 -- uploads carries host ints and
            # already-host page arrays; this builds an index upload
            pids = np.asarray([u[0] for u in uploads], np.int32)
            ids = (np.arange(L, dtype=np.int32)[:, None] * self.P
                   + pids[None, :]).ravel()
            ks = np.stack([u[1][:, 0] for u in uploads],
                          axis=1).reshape(ids.size,
                                          *uploads[0][1].shape[2:])
            vs = np.stack([u[2][:, 0] for u in uploads],
                          axis=1).reshape(ids.size,
                                          *uploads[0][2].shape[2:])
            self.kp = self.kp.at[ids].set(jnp.asarray(ks,
                                                      self.kp.dtype))
            self.vp = self.vp.at[ids].set(jnp.asarray(vs,
                                                      self.vp.dtype))
        return got

    def _fleet_publish(self):
        """Publish the pages the LAST ``register`` made newly canonical
        to the fleet directory — content-addressed, so replicas racing
        on the same prefix converge on first-writer-wins."""
        newly = getattr(self._prefix, "last_registered", [])
        for _i, digest, pid in newly:
            if pid in self._lossy_pids:
                continue
            ids = (np.arange(self.cfg.n_layers, dtype=np.int32)
                   * self.P + pid)
            # ptlint: disable=PT001 -- deliberate device→host transfer:
            # this IS the fleet KV-page publication (admission cadence,
            # newly-registered pages only — never steady-state decode)
            k = np.asarray(self.kp[ids])[:, None]
            # ptlint: disable=PT001 -- same deliberate transfer (v pool)
            v = np.asarray(self.vp[ids])[:, None]
            self.fleet.publish(digest, k, v)

    def fleet_republish(self) -> int:
        """Re-publish every live prefix page to the fleet directory —
        the router-failover recovery hook (`serving.router.
        ReplicaSession`): a NEW router generation's store starts empty,
        so without this the fleet-wide prefix warmth this replica
        accumulated would silently vanish. The caller clears the
        directory's published-set first (``fleet.reset_published()``);
        lossy-wire adopted pages stay excluded exactly as in
        `_fleet_publish`. Returns the number of pages re-published."""
        if self.fleet is None:
            return 0
        n = 0
        for digest, pid in list(self._prefix._nodes.items()):
            if pid in self._lossy_pids:
                continue
            ids = (np.arange(self.cfg.n_layers, dtype=np.int32)
                   * self.P + pid)
            # ptlint: disable=PT001 -- deliberate device→host transfer:
            # failover re-publication of the live radix cache (once per
            # router generation — never steady-state decode)
            k = np.asarray(self.kp[ids])[:, None]
            # ptlint: disable=PT001 -- same deliberate transfer (v pool)
            v = np.asarray(self.vp[ids])[:, None]
            self.fleet.publish(digest, k, v)
            n += 1
        return n

    def _corrupt_shared_pages(self, shared):
        """Payload fault site ``paged.shared_page``: with a matching
        nan/bitflip rule installed, corrupt the FIRST shared page this
        admission mapped (all layers) — the blast-radius probe for
        prefix sharing: one poisoned page must fail EVERY sharer loudly
        (each hits the non-finite-logit guard), never silently. Inert
        (one boolean check) without a fault plan."""
        from paddle_tpu.testing import faults
        if not faults.enabled() or not shared:
            return
        ids = np.arange(self.cfg.n_layers) * self.P + shared[0]
        # ptlint: disable=PT001 -- test-only fault injection (gated on
        # faults.enabled()): reading the page back is the point
        page_k = np.asarray(self.kp[ids])
        out = faults.transform("paged.shared_page", page_k)
        if out is page_k:
            # byte-payload actions (bitflip) only fire on bytes values;
            # a nan rule already returned a fresh array above
            buf = page_k.tobytes()
            ob = faults.transform("paged.shared_page", buf)
            if isinstance(ob, (bytes, bytearray)) and bytes(ob) != buf:
                out = np.frombuffer(
                    bytearray(bytes(ob).ljust(len(buf), b"\0")),
                    page_k.dtype).reshape(page_k.shape)
        if out is not page_k:
            self.kp = self.kp.at[ids].set(
                jnp.asarray(out, self.kp.dtype))

    def _admit(self, req: Request, slot: int):
        """Reserve pages, dispatch the one-pass (or suffix-only)
        prefill, and flip the slot live — WITHOUT syncing on the
        sampled first token: it stays on device (`.at[].set(nxt)`) and
        rides the harvest queue as a 'prefill' record, so admission
        enqueues behind in-flight decode dispatches instead of draining
        them. With the prefix cache on, the longest cached prefix's
        pages are mapped read-only and only the suffix is prefilled."""
        import time
        from paddle_tpu import stats
        from paddle_tpu.observability import flight, trace
        # ptlint: disable=PT001 -- req.prompt is a host int list
        # (submit coerced it); this is an upload, never a sync
        prompt = np.asarray(req.prompt, np.int32)
        n = len(prompt)
        sp, cow_src, chain = (self._match_prefix(prompt, slot)
                              if self._prefix is not None
                              else (0, -1, None))
        self._reserve(slot, n)
        tab = self._tables[slot]
        if self._prefix is not None:
            if n >= self.page:
                # register this prompt's full pages (private ones
                # become canonical for future hits; already-cached
                # digests skip). NOTE: at this point the pages are
                # still EMPTY for a cold prompt — the prefill dispatch
                # below fills them; fleet publication therefore waits
                # for the dispatched prefill (after the trace.span
                # blocks), reading back only newly-canonical pages.
                self._prefix.register(prompt, tab, chain=chain)
                self._update_pool_gauges()
            # counters only once the reservation held — the
            # MemoryError-retry path re-runs this whole admission
            from paddle_tpu import stats
            stats.add("serve/prefix_lookup")
            if sp:
                stats.add("serve/prefix_hit_tokens", sp)
        self._corrupt_shared_pages(tab[:sp // self.page])
        bucket = next(b for b in self.buckets if b >= n - sp)
        # observability lands only once the reservation HELD — the
        # MemoryError-retried admission re-runs this whole method, and
        # a duplicate serve/queue span would put phantom queue-wait
        # intervals on the stitched per-request lane (same rationale as
        # the prefix counters above)
        trace.complete("serve/queue", req.t_submit, rid=req.rid,
                       slot=slot)
        stats.add("serve/dispatch_launches")
        stats.add("serve/dispatches/prefill")
        flight.record(req.rid, "admit", slot=slot, prompt=n,
                      bucket=bucket, cached=sp)
        if sp:
            suffix = np.zeros((1, bucket), np.int32)
            suffix[0, :n - sp] = prompt[sp:]
            # page-run plan over positions [sp, n): (pid, dst_off,
            # src-in-suffix, run), run=0 padding
            segs = np.zeros((bucket // self.page + 2, 4), np.int32)
            t, i = sp, 0
            while t < n:
                pid = tab[t // self.page]
                off = t % self.page
                run = min(n - t, self.page - off)
                segs[i] = (pid, off, t - sp, run)
                t += run
                i += 1
            cow_dst = tab[(n - 1) // self.page] if cow_src >= 0 else -1
            mx = (self.cfg.max_seq_len + self.page - 1) // self.page
            row = np.zeros((mx,), np.int32)
            row[:len(tab)] = tab
            with trace.span("serve/admit", slot=slot, prompt=n,
                            bucket=bucket, cached=sp, rid=req.rid):
                self.kp, self.vp, nxt = self._prefill_sfx_fn(
                    self._head, self._stacked, self.kp, self.vp,
                    jnp.asarray(suffix), jnp.int32(sp), jnp.int32(n),
                    jnp.asarray(segs), jnp.int32(cow_src),
                    jnp.int32(cow_dst), jnp.asarray(row))
        else:
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = prompt
            # page-run copy plan: valid rows [0, n) at page boundaries
            max_seg = bucket // self.page + 1
            segs = np.zeros((max_seg, self.cfg.n_layers, 3), np.int32)
            t, i = 0, 0
            while t < n:
                pid = tab[t // self.page]
                run = min(n - t, self.page - (t % self.page))
                for l in range(self.cfg.n_layers):
                    segs[i, l] = (l * self.P + pid, t, run)
                t += run
                i += 1
            with trace.span("serve/admit", slot=slot, prompt=n,
                            bucket=bucket, cached=0, rid=req.rid):
                self.kp, self.vp, nxt = self._prefill_fn(
                    self._head, self._stacked, self.kp, self.vp,
                    jnp.asarray(padded), jnp.int32(n),
                    jnp.asarray(segs))
        if self.fleet is not None and self._prefix is not None \
                and n >= self.page:
            # the prefill dispatch that fills the registered pages is
            # enqueued; publication reads them back (block_until_ready
            # implicit in the host transfer) — newly-canonical only
            self._fleet_publish()
        rem0 = req.max_new_tokens - 1
        eos0 = -1 if req.eos_id is None else int(req.eos_id)
        # a budget-of-one request (or one whose first token is eos)
        # never activates — the device analog of _emit retiring it
        alive = jnp.logical_and(
            rem0 > 0, jnp.logical_or(eos0 < 0, nxt != eos0))
        if self.spec_k:
            # seed the prompt-lookup history: prompt rows [0, n), the
            # pending sampled token at index n (both uploads — nxt
            # stays on device)
            self.toks = self.toks.at[slot, :n].set(jnp.asarray(prompt))
            self.toks = self.toks.at[slot, n].set(nxt)
        self.lengths = self.lengths.at[slot].set(n)
        self.last = self.last.at[slot].set(nxt)
        if self.prefill_only:
            # a prefill replica never decodes: the slot stays bound
            # (its pages leave via detach_handoff) but device-inactive,
            # and the dispatch loop skips it (_disp_rem stays 0)
            self.active = self.active.at[slot].set(False)
        else:
            self.active = self.active.at[slot].set(alive)
        self.remaining = self.remaining.at[slot].set(rem0)
        self.eos_ids = self.eos_ids.at[slot].set(eos0)
        self._slot_req[slot] = req
        self._host_len[slot] = n
        self._proj_len[slot] = n
        self._disp_rem[slot] = 0 if self.prefill_only else rem0
        self._pending.append(_Inflight("prefill", [(slot, req)], nxt,
                                       time.perf_counter()))

    def _emit(self, slot: int, req: Request, token: int):
        req.tokens.append(token)
        self._obs_first_token(req)
        if self.on_token is not None:
            self.on_token(req, token)
        if ((req.eos_id is not None and token == req.eos_id)
                or len(req.tokens) >= req.max_new_tokens):
            req.done = True
            self._slot_req[slot] = None
            self._release(slot)
            self.active = self.active.at[slot].set(False)
            self._obs_request_end(req)

    # -- disaggregated handoff (docs/serving.md "Disaggregated serving") ----

    def detach_handoff(self, req: Request):
        """Extract a request's KV pages + decode state and retire it
        locally WITHOUT finishing — the sending half of both handoff
        shapes. On a ``prefill_only`` engine the pages hold exactly the
        prompt's KV (the classic prefill→transfer→decode handoff); on
        a decode-capable engine the request may be MID-DECODE (a
        draining replica migrating its in-flight work, ISSUE 16): the
        pipeline drains first, so rows ``[0, lengths)`` hold prompt +
        generated[:-1] and ``meta["tokens"]`` carries every token
        generated so far — the receiver re-emits the last one and
        continues bit-for-bit. Call once ``req.tokens`` is non-empty.

        Returns ``(meta, k, v)``: ``meta`` carries everything
        ``submit_handoff`` needs to reconstruct bit-identical device
        state on the receiving replica (prompt, tokens so far, valid
        row count, remaining budget, eos), ``k``/``v`` are (L, npages,
        Hkv, page, D) host arrays of the slot's pages (tail rows past
        ``n_tokens`` are recycled-pool garbage — the wire codec zeroes
        them; decode overwrites before reading either way)."""
        if req.failed:
            raise ValueError(f"request failed before detach: {req.error}")
        if not req.tokens:
            raise ValueError("prefill not harvested yet — pump step() "
                             "until req.tokens holds the first token")
        self._drain()
        if req.done:
            # the drain finished it (budget/eos landed in the pipeline)
            raise ValueError("request completed during drain — publish "
                             "its result directly")
        try:
            slot = self._slot_req.index(req)
        except ValueError:
            raise ValueError("request no longer holds a slot "
                             "(budget-1 requests retire at harvest — "
                             "publish their result directly)")
        n = int(self._host_len[slot])
        npg = (n + self.page - 1) // self.page
        tab = list(self._tables[slot][:npg])
        ids = (np.arange(self.cfg.n_layers, dtype=np.int32)[:, None]
               * self.P + np.asarray(tab, np.int32)[None, :]).ravel()
        L = self.cfg.n_layers
        # ptlint: disable=PT001 -- deliberate device→host transfer: this
        # IS the KV handoff payload leaving the prefill replica
        k = np.asarray(self.kp[ids]).reshape(
            L, npg, self.cfg.kv_heads, self.page, self.cfg.head_dim)
        v = np.asarray(self.vp[ids]).reshape(
            L, npg, self.cfg.kv_heads, self.page, self.cfg.head_dim)
        meta = {"prompt": list(req.prompt), "n_tokens": n,
                "first": int(req.tokens[0]),
                # full generated-so-far history: rows [0, n) hold
                # prompt + tokens[:-1]; the receiver re-emits
                # tokens[-1] (its KV is the next dispatch's write) —
                # [first] right after prefill, longer mid-decode
                "tokens": [int(t) for t in req.tokens],
                "max_new_tokens": int(req.max_new_tokens),
                "eos_id": req.eos_id,
                # trace context rides the handoff: the decode replica's
                # spans for this request carry the SAME rid, so the
                # per-replica trace files stitch into one timeline
                "rid": req.rid}
        from paddle_tpu.observability import flight
        flight.record(req.rid, "handoff-detach", n_tokens=n,
                      pages=npg)
        # retire cleanly: registered prefix pages go warm (they stay
        # published/fleet-canonical on this replica), private ones free
        self._slot_req[slot] = None
        self._release(slot)
        # a mid-decode detach leaves a device-live slot behind:
        # deactivate it so the next dispatch never decodes a ghost
        self.active = self.active.at[slot].set(False)
        self._disp_rem[slot] = 0
        req.done = True
        self._obs_request_end(req)
        return meta, k, v

    def submit_handoff(self, meta: dict, k, v,
                       deadline_s: Optional[float] = None) -> Request:
        """Receiving half of the handoff: enqueue a request whose KV
        state was built elsewhere — right after prefill (the disagg
        pipeline) or mid-decode (a drain migration). Admission (when a
        slot frees) installs the wire pages into this pool and
        reconstructs the exact sender-side device state, so decode
        continues bit-for-bit where the sender stopped (the fp32-wire
        bit-identity contract); the last sender-emitted token rides
        the harvest queue like any local prefill's first token."""
        import time
        req = _HandoffRequest(
            meta["prompt"], meta["max_new_tokens"], meta["eos_id"],
            deadline=(None if deadline_s is None
                      else time.monotonic() + deadline_s),
            rid=meta.get("rid"))
        req.kv_first = int(meta["first"])
        req.kv_tokens = [int(t) for t in
                         meta.get("tokens", [meta["first"]])]
        if not req.kv_tokens:
            raise ValueError("handoff meta carries no tokens")
        req.kv_ntok = int(meta.get(
            "n_tokens", len(req.prompt) + len(req.kv_tokens) - 1))
        if req.kv_ntok != len(req.prompt) + len(req.kv_tokens) - 1:
            raise ValueError(
                f"handoff meta inconsistent: n_tokens={req.kv_ntok} "
                f"!= prompt {len(req.prompt)} + generated "
                f"{len(req.kv_tokens)} - 1")
        if len(req.kv_tokens) > req.max_new_tokens:
            raise ValueError("handoff carries more generated tokens "
                             "than its budget")
        req.kv_pages = (np.asarray(k), np.asarray(v))
        # the wire these pages crossed (senders stamp it into the
        # handoff meta); absent → assume lossy, so the pages are never
        # re-published under the original content digest
        req.kv_wire = str(meta.get("wire", "lossy"))
        # NOT check_request: its bucket cap is a PREFILL constraint,
        # and a handoff never prefills here — decode replicas may
        # legitimately run smaller buckets than the prefill tier.
        # What must still hold: a non-empty prompt and a cache window
        # that fits prompt + budget.
        if len(req.prompt) < 1:
            raise ValueError("empty prompt")
        if len(req.prompt) + req.max_new_tokens > self.cfg.max_seq_len:
            raise ValueError("prompt + new tokens exceed max_seq_len")
        # geometry screen HERE (ValueError a serve loop turns into a
        # per-request result): a mismatched fleet config surfacing as a
        # shape error inside a later engine.step() would kill the
        # replica and every other in-flight request on it
        cfg = self.cfg
        n = req.kv_ntok
        want_npg = (n + self.page - 1) // self.page
        repacked = []
        for name, arr in (("k", req.kv_pages[0]), ("v",
                                                   req.kv_pages[1])):
            ok = (arr.ndim == 5 and arr.shape[0] == cfg.n_layers
                  and arr.shape[2] == cfg.kv_heads
                  and arr.shape[4] == cfg.head_dim
                  and arr.shape[1] * arr.shape[3] >= n)
            if not ok:
                raise ValueError(
                    f"handoff {name} pages shaped {tuple(arr.shape)} "
                    f"do not fit this engine's geometry "
                    f"{(cfg.n_layers, want_npg, cfg.kv_heads, self.page, cfg.head_dim)}"
                    " — prefill and decode replicas must share "
                    "(n_layers, kv_heads, head_dim) and carry "
                    "n_tokens rows")
            if arr.shape[1] == want_npg and arr.shape[3] == self.page:
                repacked.append(arr)
                continue
            # cross-geometry sender (different page size, or a dense
            # engine's single page of exactly n rows): flatten to a
            # row stream and repack into THIS pool's page size — the
            # rows are identical, only the blocking differs
            L, H, D = cfg.n_layers, cfg.kv_heads, cfg.head_dim
            rows = arr.transpose(0, 2, 1, 3, 4).reshape(
                L, H, arr.shape[1] * arr.shape[3], D)[:, :, :n, :]
            pad = np.zeros((L, H, want_npg * self.page, D), arr.dtype)
            pad[:, :, :n, :] = rows
            repacked.append(pad.reshape(
                L, H, want_npg, self.page, D).transpose(0, 2, 1, 3, 4))
        req.kv_pages = (repacked[0], repacked[1])
        self._waiting.append(req)
        return req

    def _admit_handoff(self, req: "_HandoffRequest", slot: int):
        """Install transferred pages instead of prefilling: reserve,
        upload the page rows, register the prompt's full pages locally
        (future submits of the same prefix hit them — and publish to
        the fleet like any registration), then reconstruct the device
        state the prefill replica's ``_admit`` would have left."""
        import time
        from paddle_tpu.observability import flight
        n = req.kv_ntok
        flight.record(req.rid, "handoff-install", n_tokens=n,
                      slot=slot, wire=req.kv_wire,
                      generated=len(req.kv_tokens))
        self._reserve(slot, n)
        tab = self._tables[slot]
        k, v = req.kv_pages
        npg = k.shape[1]
        L = self.cfg.n_layers
        # ptlint: disable=PT001 -- tab is a host int list (slot table);
        # this builds an index upload, never a device sync
        tab_arr = np.asarray(tab[:npg], np.int32)
        ids = (np.arange(L, dtype=np.int32)[:, None] * self.P
               + tab_arr[None, :]).ravel()
        self.kp = self.kp.at[ids].set(
            jnp.asarray(k.reshape(ids.size, *k.shape[2:]),
                        self.kp.dtype))
        self.vp = self.vp.at[ids].set(
            jnp.asarray(v.reshape(ids.size, *v.shape[2:]),
                        self.vp.dtype))
        req.kv_pages = None            # free the host copy
        if req.kv_wire != "fp32":
            self._lossy_pids.update(tab[:npg])
        if self._prefix is not None and n >= self.page \
                and n == len(req.prompt):
            # prefix registration only for post-prefill handoffs: a
            # migrated mid-decode slot's tail pages hold GENERATED
            # rows, which must never become prompt-prefix canon
            # ptlint: disable=PT001 -- req.prompt is a host int list
            # (submit coerced it); this is an upload, never a sync
            prompt = np.asarray(req.prompt, np.int32)
            self._prefix.register(prompt, tab)
            self._update_pool_gauges()
            if self.fleet is not None:
                self._fleet_publish()
        # sender-side history replays locally: tokens[:-1] are already
        # final (their KV sits in the installed rows); tokens[-1] is
        # the pending one whose KV the next dispatch writes
        req.tokens = list(req.kv_tokens[:-1])
        nxt = req.kv_tokens[-1]
        rem0 = req.max_new_tokens - len(req.kv_tokens)
        eos0 = -1 if req.eos_id is None else int(req.eos_id)
        alive = rem0 > 0 and (eos0 < 0 or nxt != eos0)
        if self.spec_k:
            # reconstruct the drafting history the sender would hold:
            # prompt + generated[:-1] in rows [0, n), pending token at n
            hist = np.zeros((self.cfg.max_seq_len,), np.int32)
            hist[:len(req.prompt)] = req.prompt
            hist[len(req.prompt):n] = req.kv_tokens[:-1]
            hist[n] = nxt
            self.toks = self.toks.at[slot].set(jnp.asarray(hist))
        self.lengths = self.lengths.at[slot].set(n)
        self.last = self.last.at[slot].set(jnp.int32(nxt))
        self.active = self.active.at[slot].set(bool(alive))
        self.remaining = self.remaining.at[slot].set(rem0)
        self.eos_ids = self.eos_ids.at[slot].set(eos0)
        self._slot_req[slot] = req
        self._host_len[slot] = n
        self._proj_len[slot] = n
        self._disp_rem[slot] = rem0
        # the first token rides the harvest queue exactly like a local
        # prefill's sampled token (replay does _emit(int(payload)))
        self._pending.append(_Inflight("prefill", [(slot, req)],
                                       np.int32(nxt),
                                       time.perf_counter()))

    def step(self) -> int:
        import time
        from paddle_tpu.observability import trace
        t0 = time.perf_counter()
        base = self.tokens_emitted
        with trace.span("serve/step") as sp:
            n_live = self._step_inner(sp)
            n = self.tokens_emitted - base
            sp.attrs["tokens"] = n
        if n_live or n:
            # idle polls record nothing (matching DecodeEngine): zero
            # occupancy/queue samples from an empty engine would read
            # as "admission-bound" on the dashboards
            self._obs_step(t0, n, n_live)
        return n

    def _step_inner(self, sp) -> int:
        """One pipeline step — evict (drain boundary), admit, dispatch,
        harvest lag-one. Each harvested dispatch costs exactly ONE
        packed device→host transfer. Returns the live slot count for
        the obs hooks."""
        self._evict_expired()
        self._admit_waiting()
        self._pump(self._dispatch_decode())
        live = sum(r is not None for r in self._slot_req)
        sp.attrs["active"] = live
        return live

    def _admit_waiting(self):
        drained = False
        while self._waiting:
            slot = self._free_slot()
            if slot is None:
                return
            req = self._waiting.popleft()
            try:
                if isinstance(req, _HandoffRequest):
                    self._admit_handoff(req, slot)
                else:
                    self._admit(req, slot)
            except MemoryError:
                # not enough pages right now: return the partial
                # reservation and requeue. Retired pages may be stuck
                # in unharvested dispatches — drain once and retry
                # before falling back to decode-until-room
                self._release(slot)
                self._waiting.appendleft(req)
                if self._pending and not drained:
                    self._drain()
                    drained = True
                    continue
                if not any(r is not None for r in self._slot_req):
                    raise MemoryError(
                        f"page pool ({self.P} pages of {self.page}) too "
                        f"small for even one request of "
                        f"{len(req.prompt)} tokens")
                return

    @property
    def _disp_span(self) -> int:
        """Worst-case per-slot length growth of one decode dispatch:
        ``chunk`` tokens plain, ``chunk * K`` rows speculative (every
        chunk step WRITES K rows at lengths..lengths+K-1 even when
        fewer are accepted)."""
        return self.chunk * max(1, self.spec_k)

    def _reserve_chunk(self, live):
        """Reserve pages for one chunk per live slot against the
        PROJECTED length (host shadow + in-flight growth), capped at
        the request's true maximum (prompt + budget) so projection
        slack never demands pages the request cannot use. Speculative
        dispatches write K rows per step, and the final accepted
        token's verify window pokes up to K-1 rows past the cap — the
        cap stretches by K-1 (check_request guarantees those positions
        exist in the fixed-width table)."""
        for slot, req in live:
            cap = len(req.prompt) + req.max_new_tokens
            if self.spec_k:
                cap += self.spec_k - 1
            need = min(int(self._proj_len[slot]) + self._disp_span + 1,
                       cap)
            self._reserve(slot, need)

    def _dispatch_decode(self) -> bool:
        from paddle_tpu.observability import trace

        def _live():
            return [(s, r) for s, r in enumerate(self._slot_req)
                    if r is not None and self._disp_rem[s] > 0]

        live = _live()
        if not live:
            return False
        try:
            self._reserve_chunk(live)
        except MemoryError:
            # pool pressure: retired pages may sit in unharvested
            # dispatches — drain, re-anchor the shadows, retry once
            if not self._pending:
                raise
            self._drain()
            live = _live()
            if not live:
                return False
            self._reserve_chunk(live)
        self.steps += 1
        self._obs_host_gap()
        if self.spec_k:
            with trace.span("serve/dispatch", kind="paged_spec",
                            k=self.spec_k, chunk=self.chunk,
                            inflight=len(self._pending)):
                (self.kp, self.vp, self.toks, self.lengths, self.last,
                 self.active, self.remaining, packed) = self._verify_fn(
                    self._head, self._stacked, self.kp, self.vp,
                    self._table(), self.toks, self.lengths, self.last,
                    self.active, self.remaining, self.eos_ids,
                    self._poison_mask())
            kind = "spec"
        else:
            with trace.span("serve/dispatch", kind="paged",
                            chunk=self.chunk,
                            inflight=len(self._pending)):
                (self.kp, self.vp, self.lengths, self.last, self.active,
                 self.remaining, packed) = self._multi_fn(
                    self._head, self._stacked, self.kp, self.vp,
                    self._table(), self.lengths, self.last, self.active,
                    self.remaining, self.eos_ids, self._poison_mask())
            kind = "decode"
        for s, _ in live:
            self._proj_len[s] += self._disp_span
        self._finish_dispatch(kind, live, packed)
        return True

    def _resync_budgets(self, live, cover=None):
        if cover is None:
            cover = self._pending_cover()
        super()._resync_budgets(live, cover)
        for slot, req in live:
            if req.done or self._slot_req[slot] is not req:
                continue
            self._proj_len[slot] = (self._host_len[slot]
                                    + self._disp_span
                                    * cover.get(slot, 0))

    def _apply_token(self, slot, req, token):
        """Harvested token (shared base replay): emit — which retires
        the request and releases its pages the moment budget/eos hits —
        and advance the exact host length shadow (device lengths grew
        by one for every emitted flag)."""
        self._emit(slot, req, token)
        self._host_len[slot] += 1

    def warmup(self):
        """Pre-trace/compile every (bucket, decode) jitted function on
        throwaway pool mirrors (the pool transiently exists twice) so
        first requests pay no compile latency."""
        import time
        from paddle_tpu import stats
        t0 = time.perf_counter()
        kp, vp = jnp.zeros_like(self.kp), jnp.zeros_like(self.vp)
        mx = (self.cfg.max_seq_len + self.page - 1) // self.page
        for b in self.buckets:
            segs = np.zeros((b // self.page + 1, self.cfg.n_layers, 3),
                            np.int32)
            kp, vp, _ = self._prefill_fn(
                self._head, self._stacked, kp, vp,
                jnp.zeros((1, b), jnp.int32), jnp.int32(1),
                jnp.asarray(segs))
            if self._prefix is not None:
                # the warm-hit admission path (suffix-only prefill)
                # compiles per bucket too
                sfx_segs = np.zeros((b // self.page + 2, 4), np.int32)
                kp, vp, _ = self._prefill_sfx_fn(
                    self._head, self._stacked, kp, vp,
                    jnp.zeros((1, b), jnp.int32), jnp.int32(0),
                    jnp.int32(1), jnp.asarray(sfx_segs), jnp.int32(-1),
                    jnp.int32(-1), jnp.zeros((mx,), jnp.int32))
        if self.spec_k:
            out = self._verify_fn(
                self._head, self._stacked, kp, vp, self._table(),
                jnp.zeros_like(self.toks), self.lengths, self.last,
                self.active, self.remaining, self.eos_ids,
                jnp.zeros((self.S,), bool))
        else:
            out = self._multi_fn(
                self._head, self._stacked, kp, vp, self._table(),
                self.lengths, self.last, self.active, self.remaining,
                self.eos_ids, jnp.zeros((self.S,), bool))
        jax.block_until_ready(out)
        stats.observe("serve/warmup_s", time.perf_counter() - t0)

    def run(self) -> None:
        while self._waiting or any(r is not None for r in self._slot_req):
            self.step()
        self._drain()   # trailing no-op dispatches (see DecodeEngine.run)

    def dispatch_cost(self, name=None):
        """ISSUE 15 roofline capture for the paged path: AOT
        cost/memory analysis of one paged decode dispatch (megakernel
        when PT_PAGED_MEGA, fused append+attend when PT_PAGED_FUSED,
        the speculative verify program when ``speculative_k``) at the
        current pool/table geometry. See DecodeEngine.dispatch_cost."""
        from paddle_tpu.observability import devprof
        if self.spec_k:
            return devprof.capture_jit(
                self._verify_fn, self._head, self._stacked, self.kp,
                self.vp, self._table(), self.toks, self.lengths,
                self.last, self.active, self.remaining, self.eos_ids,
                self._poison_mask(), name=name or "paged_spec")
        return devprof.capture_jit(
            self._multi_fn, self._head, self._stacked, self.kp,
            self.vp, self._table(), self.lengths, self.last,
            self.active, self.remaining, self.eos_ids,
            self._poison_mask(), name=name or "paged")

    def dispatch_fn_args(self):
        """The decode dispatch's (jitted fn, args) at the current
        geometry — what `tools/profile_decode.py`'s launches/step
        section lowers to count kernel launches without executing."""
        if self.spec_k:
            return (self._verify_fn,
                    (self._head, self._stacked, self.kp, self.vp,
                     self._table(), self.toks, self.lengths, self.last,
                     self.active, self.remaining, self.eos_ids,
                     self._poison_mask()))
        return (self._multi_fn,
                (self._head, self._stacked, self.kp, self.vp,
                 self._table(), self.lengths, self.last, self.active,
                 self.remaining, self.eos_ids, self._poison_mask()))
