"""paddle_tpu.inference — serving runtime (≙ paddle/fluid/inference/, the
90.3k-LoC AnalysisPredictor subsystem, api/analysis_predictor.h:95).

What the reference spends that subsystem on — IR pass pipelines, TRT/Lite
subgraph offload, memory-optimize passes — XLA does at compile time; what
remains to build natively is the serving surface:

- Config  ≙ AnalysisConfig (api/analysis_config.cc): model path + run opts.
- Predictor ≙ AnalysisPredictor: owns a loaded StableHLO artifact
  (paddle_tpu.jit.save export) or a jitted callable, pads request batches
  to the compiled batch size, runs, unpads.
- create_predictor ≙ paddle_infer::CreatePredictor.

Decode serving for LM models is models.gpt.generate (KV-cache loop in one
jit); Predictor serves the per-request batched forward case.
"""

import threading
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """≙ paddle_infer.Config (api/analysis_config.cc). Collects the model
    path and execution options; device/IR-opt toggles that configure
    CUDA/TRT in the reference are accepted for API parity and ignored
    (XLA owns compilation)."""

    def __init__(self, model_path: Optional[str] = None):
        self.model_path = model_path
        self.batch_size: Optional[int] = None
        self._switches = {}

    def set_model(self, path: str):
        self.model_path = path

    def enable_memory_optim(self, *a, **k):
        self._switches["memory_optim"] = True

    def switch_ir_optim(self, flag: bool = True):
        self._switches["ir_optim"] = flag

    def __getattr__(self, name):  # absorb the reference's long option list
        if name.startswith(("enable_", "switch_", "set_", "disable_")):
            return lambda *a, **k: self._switches.__setitem__(name, a)
        raise AttributeError(name)


class Predictor:
    """Batched predictor over an exported StableHLO artifact or callable
    (≙ AnalysisPredictor::Run, api/analysis_predictor.h:95).

    The exported program has static shapes; `run` accepts any number of
    requests, pads the stacked batch up to the compiled batch size (running
    multiple sub-batches when more arrive), and strips padding from the
    outputs. Thread-safe: a lock serializes device execution.
    """

    def __init__(self, model: Union[str, Callable, "Config"],
                 batch_size: Optional[int] = None):
        if isinstance(model, Config):
            batch_size = batch_size or model.batch_size
            model = model.model_path
        if isinstance(model, str):
            from paddle_tpu import jit as ptjit
            self._fn = ptjit.load(model)
            shapes = getattr(self._fn, "_exported", None)
            if batch_size is None and shapes is not None:
                in_avals = shapes.in_avals
                if in_avals and in_avals[0].shape:
                    batch_size = in_avals[0].shape[0]
        else:
            self._fn = model
        self._batch = batch_size
        self._lock = threading.Lock()

    def _run_padded(self, arrays: Sequence[np.ndarray]):
        n = arrays[0].shape[0]
        bs = self._batch or n
        outs = []
        for lo in range(0, n, bs):
            chunk = [a[lo:lo + bs] for a in arrays]
            pad = bs - chunk[0].shape[0]
            if pad > 0:
                chunk = [np.concatenate(
                    [c, np.repeat(c[-1:], pad, axis=0)], axis=0)
                    for c in chunk]
            with self._lock:
                res = self._fn(*[jnp.asarray(c) for c in chunk])
            multi = isinstance(res, (tuple, list))
            rs = list(res) if multi else [res]
            rs = [np.asarray(r)[:bs - pad] if pad > 0 else np.asarray(r)
                  for r in rs]
            outs.append(rs if multi else rs[0])
        if not isinstance(outs[0], list):
            return np.concatenate(outs, axis=0)
        return [np.concatenate([o[i] for o in outs], axis=0)
                for i in range(len(outs[0]))]

    def run(self, inputs: Union[Sequence[np.ndarray], np.ndarray],
            batched: Optional[bool] = None):
        """inputs: one array or a sequence of per-feed arrays, leading dim =
        requests. Returns outputs with the same leading dim."""
        if isinstance(inputs, np.ndarray) or hasattr(inputs, "shape"):
            inputs = [inputs]
        arrays = [np.asarray(a) for a in inputs]
        return self._run_padded(arrays)

    # convenience single-request form
    def predict(self, *feeds):
        out = self.run([np.asarray(f)[None] for f in feeds])
        if isinstance(out, list):
            return [o[0] for o in out]
        return out[0]


def create_predictor(config: Config) -> Predictor:
    """≙ paddle_infer::CreatePredictor(config)."""
    return Predictor(config)
