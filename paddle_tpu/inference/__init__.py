"""paddle_tpu.inference — serving runtime (≙ paddle/fluid/inference/, the
90.3k-LoC AnalysisPredictor subsystem, api/analysis_predictor.h:95).

What the reference spends that subsystem on — IR pass pipelines, TRT/Lite
subgraph offload, memory-optimize passes — XLA does at compile time; what
remains to build natively is the serving surface:

- Config  ≙ AnalysisConfig (api/analysis_config.cc): model path + run opts.
- Predictor ≙ AnalysisPredictor: owns a loaded StableHLO artifact
  (paddle_tpu.jit.save export) or a jitted callable, pads request batches
  to the compiled batch size, runs, unpads.
- create_predictor ≙ paddle_infer::CreatePredictor.

Decode serving for LM models is models.gpt.generate (KV-cache loop in one
jit); Predictor serves the per-request batched forward case.
"""

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["Config", "Predictor", "create_predictor", "DynamicBatcher",
           "DecodeEngine", "PagedDecodeEngine", "make_engine",
           "default_engine_kind", "decode_roofline_tokens_per_sec"]

from paddle_tpu.inference.decode_engine import (  # noqa: E402
    DecodeEngine, decode_roofline_tokens_per_sec)
from paddle_tpu.inference.paged_engine import PagedDecodeEngine
from paddle_tpu.inference.factory import (  # noqa: E402
    default_engine_kind, make_engine)


class Config:
    """≙ paddle_infer.Config (api/analysis_config.cc). Collects the model
    path and execution options; device/IR-opt toggles that configure
    CUDA/TRT in the reference are accepted for API parity and ignored
    (XLA owns compilation)."""

    def __init__(self, model_path: Optional[str] = None):
        self.model_path = model_path
        self.batch_size: Optional[int] = None
        self._switches = {}

    def set_model(self, path: str):
        self.model_path = path

    def enable_memory_optim(self, *a, **k):
        self._switches["memory_optim"] = True

    def switch_ir_optim(self, flag: bool = True):
        self._switches["ir_optim"] = flag

    def __getattr__(self, name):  # absorb the reference's long option list
        if name.startswith(("enable_", "switch_", "set_", "disable_")):
            return lambda *a, **k: self._switches.__setitem__(name, a)
        raise AttributeError(name)


class Predictor:
    """Batched predictor over an exported StableHLO artifact or callable
    (≙ AnalysisPredictor::Run, api/analysis_predictor.h:95).

    The exported program has static shapes; `run` accepts any number of
    requests, pads the stacked batch up to the compiled batch size (running
    multiple sub-batches when more arrive), and strips padding from the
    outputs. Thread-safe: a lock serializes device execution.
    """

    def __init__(self, model: Union[str, Callable, "Config"],
                 batch_size: Optional[int] = None):
        if isinstance(model, Config):
            batch_size = batch_size or model.batch_size
            model = model.model_path
        if isinstance(model, str):
            from paddle_tpu import jit as ptjit
            self._fn = ptjit.load(model)
            shapes = getattr(self._fn, "_exported", None)
            if batch_size is None and shapes is not None:
                in_avals = shapes.in_avals
                if in_avals and in_avals[0].shape:
                    batch_size = in_avals[0].shape[0]
        else:
            self._fn = model
        self._batch = batch_size
        self._lock = threading.Lock()

    def _run_padded(self, arrays: Sequence[np.ndarray]):
        n = arrays[0].shape[0]
        bs = self._batch or n
        outs = []
        for lo in range(0, n, bs):
            chunk = [a[lo:lo + bs] for a in arrays]
            pad = bs - chunk[0].shape[0]
            if pad > 0:
                chunk = [np.concatenate(
                    [c, np.repeat(c[-1:], pad, axis=0)], axis=0)
                    for c in chunk]
            with self._lock:
                res = self._fn(*[jnp.asarray(c) for c in chunk])
            multi = isinstance(res, (tuple, list))
            rs = list(res) if multi else [res]
            rs = [np.asarray(r)[:bs - pad] if pad > 0 else np.asarray(r)
                  for r in rs]
            outs.append(rs if multi else rs[0])
        if not isinstance(outs[0], list):
            return np.concatenate(outs, axis=0)
        return [np.concatenate([o[i] for o in outs], axis=0)
                for i in range(len(outs[0]))]

    def run(self, inputs: Union[Sequence[np.ndarray], np.ndarray],
            batched: Optional[bool] = None):
        """inputs: one array or a sequence of per-feed arrays, leading dim =
        requests. Returns outputs with the same leading dim."""
        if isinstance(inputs, np.ndarray) or hasattr(inputs, "shape"):
            inputs = [inputs]
        arrays = [np.asarray(a) for a in inputs]
        return self._run_padded(arrays)

    # convenience single-request form
    def predict(self, *feeds):
        out = self.run([np.asarray(f)[None] for f in feeds])
        if isinstance(out, list):
            return [o[0] for o in out]
        return out[0]


class DynamicBatcher:
    """Request queue + dynamic batching over a Predictor (the serving
    loop AnalysisPredictor leaves to paddle-serving; VERDICT r2 weak 10).

    Many threads ``submit()`` single requests; a background worker
    coalesces up to ``predictor.batch_size`` of them, waiting at most
    ``max_delay_ms`` for stragglers after the first arrival, runs ONE
    padded device call, and resolves each request's Future. Bounded
    queue: submissions beyond ``max_queue`` raise instead of building an
    unbounded backlog.

        batcher = DynamicBatcher(Predictor(fn, batch_size=8))
        fut = batcher.submit(tokens)      # from any thread
        out = fut.result(timeout=1.0)
    """

    def __init__(self, predictor: Predictor, max_delay_ms: float = 2.0,
                 max_queue: int = 1024):
        if predictor._batch is None:
            raise ValueError("DynamicBatcher needs a predictor with a "
                             "fixed batch_size")
        self.predictor = predictor
        self.max_delay = max_delay_ms / 1e3
        self._q = queue.Queue(maxsize=max_queue)
        self._closed = False
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def submit(self, *feeds) -> Future:
        """Enqueue one request (each feed WITHOUT the batch dim)."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        fut = Future()
        try:
            self._q.put_nowait((feeds, fut))
        except queue.Full:
            raise RuntimeError(
                f"request queue full ({self._q.maxsize}); shed load or "
                f"raise max_queue") from None
        return fut

    def _loop(self):
        bs = self.predictor._batch
        while True:
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if first is None:
                return
            batch = [first]
            end = time.monotonic() + self.max_delay
            while len(batch) < bs:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    item = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if item is None:
                    self._run(batch)
                    return
                batch.append(item)
            self._run(batch)

    def _run(self, batch):
        try:
            feeds = [np.stack([np.asarray(b[0][i]) for b in batch])
                     for i in range(len(batch[0][0]))]
            out = self.predictor.run(feeds)
            multi = isinstance(out, list)
            for i, (_, fut) in enumerate(batch):
                if not fut.set_running_or_notify_cancel():
                    continue
                fut.set_result([o[i] for o in out] if multi else out[i])
        except Exception as e:
            for _, fut in batch:
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(e)

    def close(self):
        """Drain and stop the worker (pending requests still complete;
        requests racing past the sentinel get a RuntimeError, never a
        forever-hanging Future)."""
        self._closed = True
        self._q.put(None)
        self._worker.join(timeout=10)
        while True:  # fail anything enqueued after the sentinel
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None and item[1].set_running_or_notify_cancel():
                item[1].set_exception(RuntimeError("batcher closed"))


def create_predictor(config: Config) -> Predictor:
    """≙ paddle_infer::CreatePredictor(config)."""
    return Predictor(config)
