"""Weight-only int8 post-training quantization for serving.

Reference analog: python/paddle/fluid/contrib/slim/quantization/
post_training_quantization.py (PTQ: per-channel absmax weight scales) and
the int8 fused kernels (operators/fused/*int8*). The TPU-native design is
weight-only QDQ: weights live in HBM as int8 + per-channel fp32 scales
(4x smaller than fp32, 2x smaller than bf16 — decode is HBM-bandwidth
bound, so smaller weights are faster weights) and are dequantized at use
INSIDE the jitted program, where XLA fuses the convert into the matmul
read instead of materializing a float copy.

    from paddle_tpu import quantization as quant
    qmodel = quant.quantize_for_inference(model)
    out = qmodel.generate(tokens, max_new_tokens=64)   # transparent

``QuantTensor`` is a pytree (int8 payload + scales) that presents the
array protocol (__jax_array__, .T, shape/dtype), so model code written
against plain weights (``x @ self.wqkv``) runs unmodified.
"""

import re
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["QuantTensor", "quantize_tensor", "quantize_for_inference",
           "dequantize_params", "quantize_aware", "convert", "qat"]

# embedding-table heuristic shared with the planner: vocab-ratio tables
# are lookup (gather) weights — quantizing them per-column would mix
# per-matmul-channel semantics with per-row lookups; skip by default
_VOCAB_RATIO = 4


@jax.tree_util.register_pytree_node_class
class QuantTensor:
    """int8 weight + per-channel fp32 scales, dequantized at use.

    ``axis`` records which dim carries the channel scales (kept size-1 in
    ``scale`` for broadcasting). Registered as a pytree so it passes
    through jit/scan/stack like a weight array; the array protocol makes
    ``x @ qt``, ``qt.T``, ``jnp.take(qt, ...)`` work unmodified.
    """

    def __init__(self, q, scale, dtype=jnp.bfloat16):
        self.q = q
        self.scale = scale
        self._dtype = dtype

    def dequantize(self):
        return (self.q.astype(jnp.float32) * self.scale).astype(self._dtype)

    # NOTE deliberately NO __jax_array__: jax's deferring binary ops would
    # convert (dequantize) the operand BEFORE Python ever tries our
    # __rmatmul__, silently bypassing the Pallas int8 kernel. Without it,
    # jnp_array @ qt returns NotImplemented and Python dispatches here.

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def size(self):
        return self.q.size

    @property
    def dtype(self):
        return self._dtype

    @property
    def T(self):  # noqa: N802 (array-protocol parity)
        return self.dequantize().T

    def astype(self, dtype):
        return self.dequantize().astype(dtype)

    def __matmul__(self, other):
        return self.dequantize() @ other

    def __rmatmul__(self, other):
        """``x @ qt`` — the serving hot path. On TPU this routes the
        Pallas int8 matmul (weights stream HBM→VMEM as int8, dequantized
        per-tile at the MXU; ops/pallas/quant_matmul.py); elsewhere XLA
        fuses the convert into the dot."""
        other = jnp.asarray(other)
        if (jax.default_backend() == "tpu" and self.q.ndim == 2
                and other.ndim >= 2 and other.dtype == self._dtype):
            try:
                from paddle_tpu.ops.pallas.quant_matmul import int8_matmul
                return int8_matmul(other, self.q,
                                   self.scale.reshape(1, -1))
            except Exception:
                pass
        return other @ self.dequantize()

    def __getitem__(self, idx):
        return self.dequantize()[idx]

    def __repr__(self):
        return (f"QuantTensor(int8{list(self.shape)}, "
                f"dequant={self._dtype.__name__ if hasattr(self._dtype, '__name__') else self._dtype})")

    def tree_flatten(self):
        return (self.q, self.scale), (self._dtype,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, aux[0])


def quantize_tensor(w, axis: int = -1) -> QuantTensor:
    """Symmetric per-channel absmax int8 quantization (≙ PTQ
    abs_max/channel_wise_abs_max, post_training_quantization.py). ``axis``
    is the channel dim whose scales are kept (the matmul OUTPUT dim for a
    weight used as ``x @ w``: quantization error then never mixes across
    output features)."""
    w = jnp.asarray(w)
    dtype = w.dtype
    wf = w.astype(jnp.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis % w.ndim)
    absmax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantTensor(q, scale, dtype)


def _is_vocab_table(shape) -> bool:
    return (len(shape) == 2 and shape[0] >= _VOCAB_RATIO * shape[1]
            and shape[0] >= 256)


def _matmul_weights(model):
    """Param names the structural planner classifies as column/row/expert
    matmul weights (its completion already separates matmul weights from
    lookup tables — exactly the split PTQ needs). Vocab-ratio tables at
    the root are excluded even when their spec collides with the
    row-parallel spec."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed.planner import (plan_module,
                                                _in_repeated_block)
    matmul_specs = {P("fsdp", "tp"), P("tp", "fsdp"), P("fsdp", None),
                    P("ep", "fsdp", "tp"), P("ep", "tp", "fsdp")}
    plan = plan_module(model)
    names = set()
    for name, w in model.named_parameters():
        if plan.get(name) not in matmul_specs:
            continue
        if not _in_repeated_block(name) and _is_vocab_table(w.shape):
            continue
        names.add(name)
    return names


def quantize_for_inference(model, include: Optional[str] = None,
                           min_size: int = 4096):
    """Return a copy of ``model`` with matmul weights replaced by int8
    ``QuantTensor``s (weight-only PTQ for the Predictor/generate serving
    paths; VERDICT r2 item 7).

    Quantized: weights the structural planner classifies as matmul
    (column/row/expert-parallel) with >= ``min_size`` elements — or
    exactly the params matching the ``include`` regex when given.
    Embedding/position tables (lookup + lax.dynamic_slice consumers),
    biases, norms and scalars stay float.
    """
    params, _ = model.split_params()
    selected = None if include is not None else _matmul_weights(model)
    out = {}
    n_q = 0
    for name, w in params.items():
        quantize = (re.search(include, name) is not None) \
            if include is not None else (name in selected
                  and jnp.issubdtype(w.dtype, jnp.floating)
                  and w.size >= min_size)
        if quantize:
            # matmul weights (in, out): channel dim is the output = -1;
            # conv kernels OIHW: the output-channel dim is 0
            out[name] = quantize_tensor(w, axis=0 if w.ndim == 4 else -1)
            n_q += 1
        else:
            out[name] = w
    if n_q == 0:
        raise ValueError("quantize_for_inference found no weight to "
                         "quantize (check include/min_size)")
    return model.merge_params(out)


def dequantize_params(params):
    """Flat param dict with every QuantTensor materialized back to float
    (for checkpointing a quantized model or accuracy diffing)."""
    return {k: (v.dequantize() if isinstance(v, QuantTensor) else v)
            for k, v in params.items()}


# QAT (fake-quant training → convert into the weight-only serving path);
# imported at the tail so qat.py can import the PTQ machinery above.
from paddle_tpu.quantization import qat  # noqa: E402
from paddle_tpu.quantization.qat import convert, quantize_aware  # noqa: E402
