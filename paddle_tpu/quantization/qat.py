"""Quantization-aware training.

Reference analog: the fake-quant layer pairs in
python/paddle/nn/quant/quant_layers.py (QuantizedLinear/QuantizedConv2D with
FakeQuantMovingAverageAbsMax for activations + FakeQuantChannelWiseAbsMax for
weights) driven by
fluid/contrib/slim/quantization/imperative/qat.py ImperativeQuantAware
(quantize = swap layers in, convert = bake scales out).

TPU-native design: fake-quant is a pure function with a straight-through
estimator (the round sits under ``stop_gradient``, so XLA fuses the whole
QDQ into the surrounding matmul and the backward pass is the identity —
no custom kernels, no graph passes). Activation ranges are EMA buffers
threaded through the functional ``nn.stateful`` Context exactly like
BatchNorm running stats; weight scales are recomputed from the live
weights each step (the reference does the same for channel-wise weight
quant). ``convert`` lowers a trained QAT model back to plain layers and
hands the named weights to the existing weight-only int8 PTQ path
(``quantize_for_inference``), so serving sees one quantization story.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.common import Linear
from paddle_tpu.nn.layer.conv import Conv2D
from paddle_tpu.nn.module import (Module, Parameter, current_context,
                                  is_training)

__all__ = ["fake_quant", "QuantedLinear", "QuantedConv2D",
           "quantize_aware", "convert"]


def fake_quant(x, absmax, bits: int = 8):
    """Symmetric quantize-dequantize with a straight-through estimator
    (≙ FakeQuantAbsMax forward, quant_layers.py; STE ≙ its backward
    passing gradients through unchanged)."""
    bound = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.asarray(absmax, jnp.float32), 1e-8) / bound
    xf = x.astype(jnp.float32)
    q = jnp.clip(jnp.round(xf / scale), -bound, bound) * scale
    return (xf + jax.lax.stop_gradient(q - xf)).astype(x.dtype)


def _channel_absmax(w, axis: int = -1):
    """Per-output-channel absmax, kept broadcastable against ``w``."""
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis % w.ndim)
    return jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes,
                   keepdims=True)


class _FakeQuantActMixin:
    """EMA absmax tracking for activations (≙ moving_average_abs_max)."""

    def _init_act_state(self, activation_bits: int, ema: float):
        self.activation_bits = activation_bits
        self.ema = ema
        self.register_buffer("act_absmax", jnp.zeros((), jnp.float32))

    def _fake_quant_input(self, x):
        cur = jnp.max(jnp.abs(x.astype(jnp.float32)))
        have = self.act_absmax > 0
        if is_training():
            absmax = jnp.where(have,
                               self.ema * self.act_absmax
                               + (1.0 - self.ema) * cur, cur)
            ctx = current_context()
            if ctx is not None:
                tag = getattr(self, "_stat_tag", None)
                if tag is None:
                    tag = f"id{id(self) % 10**9}"  # untagged: tag_paths()
                prefix = f"{tag}." if tag else ""
                ctx.record_update(f"{prefix}act_absmax", absmax)
        else:
            # inference: trust the trained range; fall back to the live
            # batch range only if the model never trained
            absmax = jnp.where(have, self.act_absmax, cur)
        return fake_quant(x, absmax, self.activation_bits)


class QuantedLinear(Module, _FakeQuantActMixin):
    """Linear with fake-quantized input + per-channel fake-quantized weight
    (≙ QuantizedLinear, quant_layers.py)."""

    def __init__(self, layer: Linear, weight_bits: int = 8,
                 activation_bits: int = 8, ema: float = 0.9):
        super().__init__()
        self.in_features = layer.in_features
        self.out_features = layer.out_features
        self.weight_bits = weight_bits
        self.weight = Parameter(layer.weight)
        self.bias = (Parameter(layer.bias) if layer.bias is not None
                     else None)
        self._init_act_state(activation_bits, ema)

    def forward(self, x):
        xq = self._fake_quant_input(x)
        wq = fake_quant(self.weight, _channel_absmax(self.weight, -1),
                        self.weight_bits)
        return F.linear(xq, wq, self.bias)


class QuantedConv2D(Module, _FakeQuantActMixin):
    """Conv2D with fake-quantized input + per-out-channel fake-quantized
    kernel (≙ QuantizedConv2D, quant_layers.py). Kernel layout is OIHW, so
    the channel axis is 0."""

    def __init__(self, layer: Conv2D, weight_bits: int = 8,
                 activation_bits: int = 8, ema: float = 0.9):
        super().__init__()
        for attr in ("in_channels", "out_channels", "kernel_size", "stride",
                     "padding", "dilation", "groups", "data_format"):
            setattr(self, attr, getattr(layer, attr))
        self.weight_bits = weight_bits
        self.weight = Parameter(layer.weight)
        self.bias = (Parameter(layer.bias) if layer.bias is not None
                     else None)
        self._init_act_state(activation_bits, ema)

    def forward(self, x):
        xq = self._fake_quant_input(x)
        wq = fake_quant(self.weight, _channel_absmax(self.weight, 0),
                        self.weight_bits)
        return F.conv2d(xq, wq, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


_SWAP = {Linear: QuantedLinear, Conv2D: QuantedConv2D}


def _deep_copy(model: Module) -> Module:
    # a Module is a pytree: identity tree_map rebuilds fresh module objects
    return jax.tree_util.tree_map(lambda x: x, model)


def _swap_children(module: Module, weight_bits, activation_bits, ema):
    for name in sorted(module._modules):
        child = getattr(module, name)
        if isinstance(child, Module):
            setattr(module, name,
                    _maybe_quant(child, weight_bits, activation_bits, ema))
        else:  # registered list/tuple of modules
            setattr(module, name, type(child)(
                _maybe_quant(c, weight_bits, activation_bits, ema)
                for c in child))
    return module


def _maybe_quant(module, weight_bits, activation_bits, ema):
    cls = _SWAP.get(type(module))
    if cls is not None:
        return cls(module, weight_bits, activation_bits, ema)
    return _swap_children(module, weight_bits, activation_bits, ema)


def quantize_aware(model: Module, weight_bits: int = 8,
                   activation_bits: int = 8, ema: float = 0.9) -> Module:
    """Return a copy of ``model`` with every Linear/Conv2D swapped for its
    fake-quant twin (≙ ImperativeQuantAware.quantize, qat.py:~200). Train
    the result exactly like the original — same optimizers, same
    ``nn.stateful`` loop; the EMA act ranges ride ``ctx.updates``."""
    out = _swap_children(_deep_copy(model), weight_bits, activation_bits,
                         ema)
    return out.tag_paths()


def convert(model: Module, for_inference: bool = True,
            min_size: int = 0) -> Module:
    """Lower a trained QAT model back to plain layers
    (≙ ImperativeQuantAware.save_quantized_model's conversion half), then —
    by default — push the quantized-in-training weights through the
    weight-only int8 PTQ path so serving uses the one existing
    ``QuantTensor`` machinery."""
    from paddle_tpu.quantization import quantize_for_inference

    quant_paths = []

    def _unswap(module, prefix=""):
        for name in sorted(module._modules):
            child = getattr(module, name)
            path = f"{prefix}{name}"
            if isinstance(child, Module):
                setattr(module, name, _restore(child, path))
            else:
                setattr(module, name, type(child)(
                    _restore(c, f"{path}.{i}")
                    for i, c in enumerate(child)))
        return module

    def _restore(module, path):
        if isinstance(module, QuantedLinear):
            new = Linear(module.in_features, module.out_features,
                         bias_attr=False if module.bias is None else None)
            new.weight = Parameter(
                fake_quant(module.weight,
                           _channel_absmax(module.weight, -1),
                           module.weight_bits))
            if module.bias is not None:
                new.bias = Parameter(module.bias)
            quant_paths.append(f"{path}.weight")
            return new
        if isinstance(module, QuantedConv2D):
            new = Conv2D(module.in_channels, module.out_channels,
                         module.kernel_size, module.stride, module.padding,
                         module.dilation, module.groups,
                         bias_attr=False if module.bias is None else None,
                         data_format=module.data_format)
            new.weight = Parameter(
                fake_quant(module.weight,
                           _channel_absmax(module.weight, 0),
                           module.weight_bits))
            if module.bias is not None:
                new.bias = Parameter(module.bias)
            quant_paths.append(f"{path}.weight")
            return new
        return _unswap(module, f"{path}.")

    plain = _unswap(_deep_copy(model))
    if not for_inference:
        return plain
    if not quant_paths:
        raise ValueError("convert() found no Quanted layer in the model")
    include = "^(" + "|".join(
        p.replace(".", r"\.") for p in quant_paths) + ")$"
    return quantize_for_inference(plain, include=include,
                                  min_size=min_size)
