"""paddle_tpu.linalg namespace (ref: paddle.linalg re-exporting
tensor/linalg.py functions)."""

from paddle_tpu.tensor.linalg import (  # noqa: F401
    matmul, mm, bmm, dot, mv, t, norm, cond, det, slogdet, inv, pinv, solve,
    triangular_solve, cholesky, cholesky_solve, lu, qr, svd, eig, eigh,
    eigvals, eigvalsh, matrix_power, matrix_rank, multi_dot, cross,
    histogram, bincount, einsum, lstsq, corrcoef, cov)

__all__ = ["matmul", "mm", "bmm", "dot", "mv", "t", "norm", "cond", "det",
           "slogdet", "inv", "pinv", "solve", "triangular_solve", "cholesky",
           "cholesky_solve", "lu", "qr", "svd", "eig", "eigh", "eigvals",
           "eigvalsh", "matrix_power", "matrix_rank", "multi_dot", "cross",
           "histogram", "bincount", "einsum", "lstsq", "corrcoef", "cov"]
from paddle_tpu.tensor.linalg import lu_unpack  # noqa: E402,F401

__all__ = __all__ + ["lu_unpack"]
