"""Persistent-compilation-cache hardening (ISSUE 4 satellite).

BENCH r05 logged ``RESOURCE_EXHAUSTED: TPU backend error`` UserWarnings
from persistent-cache reads mid-bench: jax treats a failed cache
read/write as a warning and recompiles, which is the right fallback —
but a serving process then prints one warning line per flaky entry
(spam), and an operator has no counter to tell a degraded cache from a
healthy one. This module:

- ``guard()`` — routes jax's per-entry compilation-cache failure
  warnings into the stats registry (``serve/compile_cache_errors``,
  plus a per-exception-class counter and the
  ``prof/compile_cache_disabled`` gauge), printing only the FIRST
  occurrence; every other warning passes through untouched. Installed
  idempotently by both decode engines at construction.
- ``enable(cache_dir)`` — points jax at a persistent cache dir with a
  fallback: a missing config knob (older jax) or a broken dir counts
  into the same counter and returns False instead of raising — cold
  compiles are a slowdown, not an outage.
- ``status()`` — {"disabled", "errors", "last_error_class"} for bench
  provenance: the r05 RESOURCE_EXHAUSTED that silently killed the
  bert/resnet/ppyoloe rows is now a stamped field on every BENCH
  snapshot and a /statsz gauge, not a line lost in stderr.

docs/serving.md documents the operator contract.
"""

import os
import re
import threading
import warnings

__all__ = ["guard", "enable", "status"]

# matches jax's "Error reading persistent compilation cache entry ..."
# and "Error writing persistent compilation cache entry ..." warnings
_MATCH = re.compile(r"persistent compilation cache", re.IGNORECASE)
# the exception class jax embeds in the warning text ("...: JaxRuntimeError:
# RESOURCE_EXHAUSTED: ..."); the class name is the triage key (a flaky
# read vs a full disk vs a permission wall are different runbooks)
_EXC_CLASS = re.compile(r"\b([A-Za-z_][A-Za-z0-9_]*(?:Error|Exception))\b")
_lock = threading.Lock()
_hook = None
_printed = False
_last_exc_class = None


def _record_failure(exc_class: str):
    """Count one cache failure: total + per-class counters, and latch
    the ``prof/compile_cache_disabled`` gauge (the cache is degraded —
    compiles fall back to cold — until an operator intervenes)."""
    global _last_exc_class
    from paddle_tpu import stats
    _last_exc_class = exc_class
    stats.add("serve/compile_cache_errors")
    stats.add(f"serve/compile_cache_errors/{exc_class}")
    stats.set_value("prof/compile_cache_disabled", 1.0)


def status() -> dict:
    """Provenance view of the cache's health this process: whether any
    failure latched the disabled gauge, the total error count, and the
    most recent exception class (None when healthy)."""
    from paddle_tpu import stats
    return {
        "disabled": bool(stats.get("prof/compile_cache_disabled", 0)),
        "errors": int(stats.get("serve/compile_cache_errors", 0)),
        "last_error_class": _last_exc_class,
    }


def guard() -> None:
    """Idempotently intercept compilation-cache failure warnings: every
    occurrence increments ``serve/compile_cache_errors``; only the
    first is shown. Never raises.

    The hook and its "always" filter mutate process-global ``warnings``
    state (an intervening ``warnings.catch_warnings()`` context restores
    the previous hook on exit, so guard() re-installs whenever it finds
    itself displaced — every engine construction calls it). Set
    ``PT_COMPILE_CACHE_GUARD=0`` to opt out entirely (e.g. a process
    run under ``-W ignore`` that wants no cache-failure line at all)."""
    global _hook
    if os.environ.get("PT_COMPILE_CACHE_GUARD", "1") == "0":
        return
    with _lock:
        if _hook is not None and warnings.showwarning is _hook:
            return   # still installed
        prev = warnings.showwarning

        def _showwarning(message, category, filename, lineno,
                         file=None, line=None):
            global _printed
            text = str(message)
            if _MATCH.search(text):
                m = _EXC_CLASS.search(text)
                _record_failure(m.group(1) if m else "unknown")
                if _printed:
                    return
                _printed = True
            prev(message, category, filename, lineno, file, line)

        warnings.showwarning = _showwarning
        _hook = _showwarning
        # the default "once per call site" filter would hide repeats
        # from the hook above — the hook dedupes the printing itself
        warnings.filterwarnings(
            "always", message=".*persistent compilation cache.*")


def enable(cache_dir, min_compile_secs: float = 1.0) -> bool:
    """Enable jax's persistent compilation cache at ``cache_dir``,
    tolerating failure (counter + one warning instead of an abort).
    Returns True when the cache was configured."""
    guard()
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
        return True
    except Exception as e:  # older jax without the knob / unusable dir
        _record_failure(type(e).__name__)
        warnings.warn(f"compile cache unavailable ({e}); continuing "
                      f"with cold compiles")
        return False
