"""Shape/layout manipulation ops (ref: python/paddle/tensor/manipulation.py).

All are pure views/copies in XLA; there is no LoD machinery (the reference's
LoDTensor ragged-sequence legacy, framework/lod_tensor.h, is replaced by
explicit masks/segment-ids as is idiomatic for static-shape TPU programs).
"""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op
from paddle_tpu.tensor._gen import _sample

__all__ = []

_slice = slice  # builtin, before the `slice` op below shadows it


def _reg(name, fn, np_ref=None, sample=None, diff=True):
    register_op(name, fn, "manipulation", np_ref=np_ref, sample_args=sample,
                differentiable=diff)
    globals()[name] = fn
    __all__.append(name)
    return fn


def reshape(x, shape):
    return jnp.reshape(jnp.asarray(x), shape)


def flatten(x, start_axis=0, stop_axis=-1):
    x = jnp.asarray(x)
    nd = x.ndim
    start = start_axis % nd
    stop = stop_axis % nd
    new_shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return jnp.reshape(x, new_shape)


def transpose(x, perm):
    return jnp.transpose(jnp.asarray(x), perm)


def moveaxis(x, source, destination):
    return jnp.moveaxis(jnp.asarray(x), source, destination)


def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(jnp.asarray(x), axis1, axis2)


def squeeze(x, axis=None):
    return jnp.squeeze(jnp.asarray(x), axis=axis)


def unsqueeze(x, axis):
    return jnp.expand_dims(jnp.asarray(x), axis)


def concat(x, axis=0):
    return jnp.concatenate([jnp.asarray(t) for t in x], axis=axis)


def stack(x, axis=0):
    return jnp.stack([jnp.asarray(t) for t in x], axis=axis)


def unstack(x, axis=0, num=None):
    x = jnp.asarray(x)
    n = num if num is not None else x.shape[axis]
    return [jnp.squeeze(t, axis=axis)
            for t in jnp.split(x, n, axis=axis)]


def split(x, num_or_sections, axis=0):
    x = jnp.asarray(x)
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    # sections list → cumulative indices; -1 means "the rest"
    sections = list(num_or_sections)
    total = x.shape[axis]
    if -1 in sections:
        known = sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = total - known
    idx = np.cumsum(sections)[:-1]
    return jnp.split(x, idx, axis=axis)


def chunk(x, chunks, axis=0):
    return jnp.array_split(jnp.asarray(x), chunks, axis=axis)


def tile(x, repeat_times):
    return jnp.tile(jnp.asarray(x), repeat_times)


def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(jnp.asarray(x), repeats, axis=axis)


def expand(x, shape):
    x = jnp.asarray(x)
    shape = tuple(x.shape[i - (len(shape) - x.ndim)] if s == -1 else s
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


def expand_as(x, y):
    return jnp.broadcast_to(jnp.asarray(x), jnp.asarray(y).shape)


def broadcast_to(x, shape):
    return jnp.broadcast_to(jnp.asarray(x), shape)


def broadcast_tensors(inputs):
    return list(jnp.broadcast_arrays(*[jnp.asarray(t) for t in inputs]))


def flip(x, axis):
    return jnp.flip(jnp.asarray(x), axis=axis)


def roll(x, shifts, axis=None):
    return jnp.roll(jnp.asarray(x), shifts, axis=axis)


def gather(x, index, axis=0):
    return jnp.take(jnp.asarray(x), jnp.asarray(index), axis=axis)


def gather_nd(x, index):
    x = jnp.asarray(x)
    index = jnp.asarray(index)
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def scatter(x, index, updates, overwrite=True):
    x = jnp.asarray(x)
    index = jnp.asarray(index).reshape(-1)
    updates = jnp.asarray(updates)
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter_nd(index, updates, shape):
    zeros = jnp.zeros(shape, jnp.asarray(updates).dtype)
    return scatter_nd_add(zeros, index, updates)


def scatter_nd_add(x, index, updates):
    x = jnp.asarray(x)
    index = jnp.asarray(index)
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def put_along_axis(x, index, value, axis, reduce="assign"):  # noqa: A002
    x = jnp.asarray(x)
    if reduce == "assign":
        return jnp.put_along_axis(x, jnp.asarray(index), value, axis=axis,
                                  inplace=False)
    mode = {"add": "add", "multiply": "multiply", "mul": "multiply"}[reduce]
    idx = jnp.asarray(index)
    full = [jnp.broadcast_to(jnp.arange(s).reshape(
        [-1 if d == i else 1 for d in range(x.ndim)]), idx.shape)
        for i, s in enumerate(x.shape)]
    full[axis] = idx
    if mode == "add":
        return x.at[tuple(full)].add(value)
    return x.at[tuple(full)].multiply(value)


def take_along_axis(x, index, axis):
    return jnp.take_along_axis(jnp.asarray(x), jnp.asarray(index), axis=axis)


def index_select(x, index, axis=0):
    return jnp.take(jnp.asarray(x), jnp.asarray(index), axis=axis)


def index_sample(x, index):
    return jnp.take_along_axis(jnp.asarray(x), jnp.asarray(index), axis=1)


def index_add(x, index, axis, value):
    x = jnp.asarray(x)
    idx = [_slice(None)] * x.ndim
    idx[axis] = jnp.asarray(index)
    return x.at[tuple(idx)].add(jnp.asarray(value))


def masked_select(x, mask):
    """Dynamic-shape op: returns a host-side compacted array (not jittable on
    TPU by design; use ``where``/mask arithmetic inside compiled code)."""
    x = np.asarray(x)
    mask = np.asarray(mask)
    return jnp.asarray(x[mask])


def masked_fill(x, mask, value):
    return jnp.where(jnp.asarray(mask), value, jnp.asarray(x))


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return jnp.where(jnp.asarray(condition), jnp.asarray(x), jnp.asarray(y))


def nonzero(x, as_tuple=False):
    x = np.asarray(x)
    res = np.nonzero(x)
    if as_tuple:
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(np.stack(res, axis=1))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):  # noqa: A002
    x = jnp.asarray(x)
    if len(pad) == 2 * x.ndim:
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # paddle convention: pad applies to the last len(pad)//2 spatial dims,
        # ordered innermost-last, e.g. NCHW with pad=[l,r,t,b]
        n_spatial = len(pad) // 2
        pairs = [(0, 0)] * (x.ndim - n_spatial)
        spatial = [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)]
        pairs += spatial[::-1]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pairs, mode=jmode, constant_values=value)
    return jnp.pad(x, pairs, mode=jmode)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    """Dynamic-shape op — host-side like the reference's CPU fallback."""
    res = np.unique(np.asarray(x), return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    x_np = np.asarray(x)
    if axis is None:
        flat = x_np.reshape(-1)
        keep = np.concatenate([[True], flat[1:] != flat[:-1]])
    else:
        moved = np.moveaxis(x_np, axis, 0)
        flat2d = moved.reshape(moved.shape[0], -1)
        diff = (flat2d[1:] != flat2d[:-1]).any(axis=1)
        keep = np.concatenate([[True], diff])
        flat = moved
    out = np.moveaxis(flat[keep], 0, axis) if axis is not None else flat[keep]
    res = [jnp.asarray(out)]
    if return_inverse:
        res.append(jnp.asarray(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.nonzero(keep)[0]
        res.append(jnp.asarray(np.diff(np.append(idx, len(keep)))))
    return res[0] if len(res) == 1 else tuple(res)


def as_complex(x):
    x = jnp.asarray(x)
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_real(x):
    x = jnp.asarray(x)
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def real(x):
    return jnp.real(jnp.asarray(x))


def imag(x):
    return jnp.imag(jnp.asarray(x))


def cast(x, dtype):
    from paddle_tpu.dtypes import to_dtype
    return jnp.asarray(x).astype(to_dtype(dtype))


def crop(x, shape=None, offsets=None):
    x = jnp.asarray(x)
    offsets = offsets or [0] * x.ndim
    shape = shape or x.shape
    slices = tuple(_slice(o, o + s) for o, s in zip(offsets, shape))
    return x[slices]


def strided_slice(x, axes, starts, ends, strides):
    x = jnp.asarray(x)
    slices = [_slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        slices[ax] = _slice(st, en, sd)
    return x[tuple(slices)]


def slice(x, axes, starts, ends):  # noqa: A001
    return strided_slice(x, axes, starts, ends, [1] * len(axes))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    x = jnp.asarray(input)
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    in_shard = (x >= lo) & (x < hi)
    return jnp.where(in_shard, x - lo, ignore_value)


def tensordot(x, y, axes=2):
    return jnp.tensordot(jnp.asarray(x), jnp.asarray(y), axes=axes)


def diag(x, offset=0, padding_value=0.0):
    x = jnp.asarray(x)
    if x.ndim == 1 and padding_value != 0:
        n = x.shape[0] + abs(offset)
        base = jnp.full((n, n), padding_value, x.dtype)
        return base + jnp.diag(x, k=offset) - jnp.diag(
            jnp.full((x.shape[0],), padding_value, x.dtype), k=offset)
    return jnp.diag(x, k=offset)


def diagflat(x, offset=0):
    return jnp.diagflat(jnp.asarray(x), k=offset)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    x = jnp.asarray(x)
    out = jnp.vectorize(lambda v: jnp.diag(v, k=offset),
                        signature="(n)->(m,m)")(x)
    # vectorize leaves the diagonal planes in the last two axes; move them
    # to the requested (dim1, dim2) of the output
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        perm = [None] * nd
        perm[d1], perm[d2] = nd - 2, nd - 1
        batch = iter(range(nd - 2))
        perm = [p if p is not None else next(batch) for p in perm]
        out = jnp.transpose(out, perm)
    return out


def tril(x, diagonal=0):
    return jnp.tril(jnp.asarray(x), k=diagonal)


def triu(x, diagonal=0):
    return jnp.triu(jnp.asarray(x), k=diagonal)


def meshgrid(*args, indexing="ij"):
    return list(jnp.meshgrid(*[jnp.asarray(a) for a in args],
                             indexing=indexing))


def unbind(x, axis=0):
    return unstack(x, axis=axis)


def numel(x):
    return jnp.asarray(jnp.size(jnp.asarray(x)))


def shape(x):
    return jnp.asarray(jnp.asarray(x).shape, dtype=jnp.int32)


def rank(x):
    return jnp.asarray(jnp.asarray(x).ndim, dtype=jnp.int32)


def is_empty(x):
    return jnp.asarray(jnp.size(jnp.asarray(x)) == 0)


def view(x, shape_or_dtype):
    x = jnp.asarray(x)
    if isinstance(shape_or_dtype, (list, tuple)):
        return jnp.reshape(x, shape_or_dtype)
    return x.view(shape_or_dtype)


def view_as(x, other):
    return jnp.reshape(jnp.asarray(x), jnp.asarray(other).shape)


def atleast_1d(*xs):
    r = jnp.atleast_1d(*[jnp.asarray(x) for x in xs])
    return r


def atleast_2d(*xs):
    return jnp.atleast_2d(*[jnp.asarray(x) for x in xs])


def atleast_3d(*xs):
    return jnp.atleast_3d(*[jnp.asarray(x) for x in xs])


for _n in ["reshape", "flatten", "transpose", "moveaxis", "swapaxes",
           "squeeze", "unsqueeze", "concat", "stack", "unstack", "split",
           "chunk", "tile", "repeat_interleave", "expand", "expand_as",
           "broadcast_to", "broadcast_tensors", "flip", "roll", "gather",
           "gather_nd", "scatter", "scatter_nd", "scatter_nd_add",
           "put_along_axis", "take_along_axis", "index_select",
           "index_sample", "masked_select", "masked_fill", "where", "nonzero",
           "pad", "unique", "unique_consecutive", "as_complex", "as_real",
           "real", "imag", "cast", "crop", "strided_slice", "slice",
           "shard_index", "tensordot", "diag", "diagflat", "diag_embed",
           "index_add", "tril",
           "triu", "meshgrid", "unbind", "numel", "shape", "rank", "is_empty",
           "view", "view_as", "atleast_1d", "atleast_2d", "atleast_3d"]:
    _reg(_n, globals()[_n])


def vsplit(x, num_or_sections):
    """Split along axis 0 (ref: python/paddle/tensor/manipulation.py
    vsplit)."""
    return split(x, num_or_sections, axis=0)


def hsplit(x, num_or_sections):
    x = jnp.asarray(x)
    return split(x, num_or_sections, axis=1 if x.ndim > 1 else 0)


def dsplit(x, num_or_sections):
    return split(x, num_or_sections, axis=2)


def hstack(x):
    return jnp.hstack([jnp.asarray(t) for t in x])


def vstack(x):
    return jnp.vstack([jnp.asarray(t) for t in x])


def fill_diagonal(x, value, offset=0, wrap=False):
    """Functional fill_diagonal_ (the reference mutates; XLA programs are
    pure, so this returns a copy — ref manipulation.py fill_diagonal_).

    ndim > 2 writes the hyper-diagonal x[i, i, ..., i] like the reference
    (and np.fill_diagonal); ``wrap`` repeats the diagonal every m+1 rows of
    a tall 2-D matrix (numpy wrap semantics; offset must be 0 with wrap)."""
    x = jnp.asarray(x)
    if x.ndim > 2:
        k = min(x.shape)
        idx = (jnp.arange(k),) * x.ndim
        return x.at[idx].set(value)
    n, m = x.shape
    ii = jnp.arange(n)[:, None]
    jj = jnp.arange(m)[None, :]
    if wrap and n > m:
        if offset:
            raise ValueError("offset must be 0 when wrap=True")
        return jnp.where((jj == ii % (m + 1)), value, x)
    return jnp.where(jj - ii == offset, value, x)


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1):
    """ref manipulation.py fill_diagonal_tensor: write y onto the
    (dim1, dim2) diagonal of x (functional copy)."""
    x = jnp.asarray(x)
    if x.ndim != 2 or (dim1, dim2) != (0, 1):
        raise NotImplementedError("fill_diagonal_tensor supports 2-D "
                                  "(dim1=0, dim2=1); transpose first")
    n, m = x.shape
    diag_len = min(n, m - offset) if offset >= 0 else min(n + offset, m)
    rows = jnp.arange(diag_len) - min(offset, 0)
    cols = jnp.arange(diag_len) + max(offset, 0)
    return x.at[rows, cols].set(jnp.asarray(y).reshape(-1)[:diag_len])


def tolist(x):
    """Host transfer + nested python lists (ref Tensor.tolist)."""
    return np.asarray(jax.device_get(x)).tolist()


for _n in ["vsplit", "hsplit", "dsplit", "hstack", "vstack",
           "fill_diagonal", "fill_diagonal_tensor", "tolist"]:
    _reg(_n, globals()[_n])
