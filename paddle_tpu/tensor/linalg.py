"""Linear algebra ops (ref: python/paddle/tensor/linalg.py — e.g. ``matmul``
at linalg.py:142 — and the phi matmul/blas kernels,
paddle/phi/kernels/funcs/blas/). On TPU every matmul lowers to the MXU via
XLA dot_general; precision is controlled by the ``matmul_precision`` flag."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu import flags
from paddle_tpu.ops.registry import register_op
from paddle_tpu.tensor._gen import _sample

__all__ = []


def _reg(name, fn, np_ref=None, sample=None, diff=True):
    register_op(name, fn, "linalg", np_ref=np_ref, sample_args=sample,
                differentiable=diff)
    globals()[name] = fn
    __all__.append(name)
    return fn


def _precision():
    return {"default": jax.lax.Precision.DEFAULT,
            "high": jax.lax.Precision.HIGH,
            "highest": jax.lax.Precision.HIGHEST}[
        flags.get_flag("matmul_precision")]


def matmul(x, y, transpose_x=False, transpose_y=False):
    """Batched matmul on the MXU (ref: python/paddle/tensor/linalg.py:142 →
    phi MatmulKernel). Transposes fold into XLA's dot_general dimension
    numbers rather than materializing."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y, precision=_precision())


def mm(x, y):
    return matmul(x, y)


def bmm(x, y):
    return jnp.matmul(jnp.asarray(x), jnp.asarray(y), precision=_precision())


def dot(x, y):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    return jnp.sum(x * y, axis=-1)


def mv(x, vec):
    return matmul(x, vec)


def t(x):
    x = jnp.asarray(x)
    return x if x.ndim < 2 else jnp.swapaxes(x, -1, -2)


def norm(x, p="fro", axis=None, keepdim=False):
    x = jnp.asarray(x)
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.linalg.norm(x, ord="fro" if isinstance(axis, (tuple, list))
                               else None, axis=axis, keepdims=keepdim)
    if p == np.inf or p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == -np.inf or p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


def cond(x, p=None):
    return jnp.linalg.cond(jnp.asarray(x), p=p)


def det(x):
    return jnp.linalg.det(jnp.asarray(x))


def slogdet(x):
    s, l = jnp.linalg.slogdet(jnp.asarray(x))
    return jnp.stack([s, l])


def inv(x):
    return jnp.linalg.inv(jnp.asarray(x))


def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(jnp.asarray(x), rtol=rcond, hermitian=hermitian)


def solve(x, y):
    return jnp.linalg.solve(jnp.asarray(x), jnp.asarray(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        jnp.asarray(x), jnp.asarray(y), lower=not upper,
        trans=1 if transpose else 0, unit_diagonal=unitriangular)


def cholesky(x, upper=False):
    c = jnp.linalg.cholesky(jnp.asarray(x))
    return jnp.swapaxes(c, -1, -2) if upper else c


def cholesky_solve(x, y, upper=False):
    y_ = jnp.asarray(y)
    return jax.scipy.linalg.cho_solve((jnp.asarray(y_), not upper),
                                      jnp.asarray(x))


def lu(x, pivot=True):
    lu_, piv = jax.scipy.linalg.lu_factor(jnp.asarray(x))
    return lu_, piv


def qr(x, mode="reduced"):
    return jnp.linalg.qr(jnp.asarray(x), mode=mode)


def svd(x, full_matrices=False):
    return jnp.linalg.svd(jnp.asarray(x), full_matrices=full_matrices)


def eig(x):
    """Not supported on TPU backends (no complex eigensolver in XLA:TPU);
    computed on host CPU like the reference's CPU-only Eig kernel."""
    w, v = np.linalg.eig(np.asarray(jax.device_get(x)))
    return jnp.asarray(w), jnp.asarray(v)


def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(jnp.asarray(x), UPLO=UPLO)


def eigvals(x):
    w = np.linalg.eigvals(np.asarray(jax.device_get(x)))
    return jnp.asarray(w)


def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(jnp.asarray(x), UPLO=UPLO)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(jnp.asarray(x), n)


def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(jnp.asarray(x), rtol=tol)


def multi_dot(xs):
    return jnp.linalg.multi_dot([jnp.asarray(x) for x in xs])


def cross(x, y, axis=-1):
    return jnp.cross(jnp.asarray(x), jnp.asarray(y), axis=axis)


def histogram(x, bins=100, min=0, max=0):  # noqa: A002
    x = jnp.asarray(x)
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return hist


def bincount(x, weights=None, minlength=0):
    return jnp.bincount(jnp.asarray(x), weights=weights, minlength=minlength,
                        length=None)


def einsum(equation, *operands):
    return jnp.einsum(equation, *[jnp.asarray(o) for o in operands],
                      precision=_precision())


def lstsq(x, y, rcond=None, driver=None):
    sol, res, rank_, sv = jnp.linalg.lstsq(jnp.asarray(x), jnp.asarray(y),
                                           rcond=rcond)
    return sol, res, rank_, sv


def corrcoef(x, rowvar=True):
    return jnp.corrcoef(jnp.asarray(x), rowvar=rowvar)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(jnp.asarray(x), rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


_reg("matmul", matmul, np.matmul,
     lambda: ((_sample("real", (4, 5)), _sample("real", (5, 3))), {}))
_reg("mm", mm, None)
_reg("bmm", bmm, np.matmul,
     lambda: ((_sample("real", (2, 4, 5)), _sample("real", (2, 5, 3))), {}))
_reg("dot", dot, None)
_reg("mv", mv, None)
_reg("t", t, np.transpose, lambda: ((_sample("real"),), {}))
_reg("norm", norm, np.linalg.norm, lambda: ((_sample("real"),), {}))
_reg("cond", cond, None, diff=False)
_reg("det", det, np.linalg.det, lambda: ((_sample("real", (3, 3)),), {}))
_reg("slogdet", slogdet, None)
_reg("inv", inv, np.linalg.inv, lambda: ((_sample("real", (3, 3)),), {}))
_reg("pinv", pinv, None)
_reg("solve", solve, None)
_reg("triangular_solve", triangular_solve, None)
_reg("cholesky", cholesky, None)
_reg("cholesky_solve", cholesky_solve, None)
_reg("lu", lu, None, diff=False)
_reg("qr", qr, None, diff=False)
_reg("svd", svd, None, diff=False)
_reg("eig", eig, None, diff=False)
_reg("eigh", eigh, None, diff=False)
_reg("eigvals", eigvals, None, diff=False)
_reg("eigvalsh", eigvalsh, None, diff=False)
_reg("matrix_power", matrix_power, None)
_reg("matrix_rank", matrix_rank, None, diff=False)
_reg("multi_dot", multi_dot, None)
_reg("cross", cross, np.cross,
     lambda: ((_sample("real", (4, 3)), _sample("real", (4, 3))), {}))
_reg("histogram", histogram, None, diff=False)
_reg("bincount", bincount, None, diff=False)
_reg("einsum", einsum, None)
_reg("lstsq", lstsq, None, diff=False)
_reg("corrcoef", corrcoef, None)
_reg("cov", cov, None)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True):
    """Unpack LAPACK-packed LU factorization into (P, L, U)
    (ref: python/paddle/tensor/linalg.py lu_unpack → lu_unpack_op).
    Supports batched inputs; pivots are 0-based (matching ``lu`` above)."""
    lu_m = jnp.asarray(lu_data)
    n = lu_m.shape[-2]
    m = lu_m.shape[-1]
    k = min(n, m)
    l = u = pmat = None
    if unpack_ludata:
        l = jnp.tril(lu_m[..., :, :k], -1) + jnp.eye(n, k, dtype=lu_m.dtype)
        u = jnp.triu(lu_m[..., :k, :])
    if unpack_pivots:
        piv = np.asarray(jax.device_get(lu_pivots))
        batch = piv.shape[:-1]
        piv2 = piv.reshape(-1, piv.shape[-1])
        mats = []
        for row in piv2:
            perm = np.arange(n)
            for i, p in enumerate(row):
                perm[[i, p]] = perm[[p, i]]
            mats.append(np.eye(n, dtype=np.float32)[:, perm])
        pmat = jnp.asarray(
            np.stack(mats).reshape(batch + (n, n)) if batch
            else mats[0])
    return pmat, l, u


_reg("lu_unpack", lu_unpack, None, diff=False)
