"""Functional tensor-op surface (ref: python/paddle/tensor/ — 314 top-level
functions dispatched per-op through _C_ops; here: pure jax functions compiled
by tracing)."""

from paddle_tpu.tensor import math as _math
from paddle_tpu.tensor import manipulation as _manipulation
from paddle_tpu.tensor import creation as _creation
from paddle_tpu.tensor import linalg as _linalg
from paddle_tpu.tensor import logic as _logic
from paddle_tpu.tensor import search as _search
from paddle_tpu.tensor import stat as _stat
from paddle_tpu.tensor import random_ops as _random_ops
from paddle_tpu.tensor import inplace as _inplace
from paddle_tpu.tensor import sequence as _sequence

from paddle_tpu.tensor.math import *        # noqa: F401,F403
from paddle_tpu.tensor.manipulation import *  # noqa: F401,F403
from paddle_tpu.tensor.creation import *    # noqa: F401,F403
from paddle_tpu.tensor.linalg import *      # noqa: F401,F403
from paddle_tpu.tensor.logic import *       # noqa: F401,F403
from paddle_tpu.tensor.search import *      # noqa: F401,F403
from paddle_tpu.tensor.stat import *        # noqa: F401,F403
from paddle_tpu.tensor.inplace import *    # noqa: F401,F403
from paddle_tpu.tensor.random_ops import *  # noqa: F401,F403
from paddle_tpu.tensor.sequence import *    # noqa: F401,F403

__all__ = (_math.__all__ + _manipulation.__all__ + _creation.__all__
           + _linalg.__all__ + _logic.__all__ + _search.__all__
           + _stat.__all__ + _random_ops.__all__
           + _inplace.__all__ + _sequence.__all__)
