"""Inplace-suffixed API variants + TensorArray ops + set_printoptions
(ref: python/paddle/tensor/__init__.py exports the ``op_``` family, e.g.
math.py add_/clip_/exp_; array.py array_read:25/array_write:74/
array_length:118/create_array:151).

JAX arrays are immutable, so ``x.add_(y)``-style mutation cannot exist;
the TPU-native contract for every ``op_`` here is: same computation,
returns the new array, caller rebinds (which is also what the reference's
inplace op returns — the same Tensor, updated). Under jit, XLA's buffer
donation already gives the memory reuse the reference's inplace pass
exists for, so these are pure API-parity aliases, each inheriting its
base op's oracle so the OpTest gate covers them.
"""

import numpy as np
import jax.numpy as jnp

from paddle_tpu.ops import registry
from paddle_tpu.ops.registry import OpSpec, register_op

__all__ = ["create_array", "array_write", "array_read", "array_length",
           "set_printoptions"]

_ALIASES = {
    "add_": "add", "ceil_": "ceil", "clip_": "clip", "erfinv_": "erfinv",
    "exp_": "exp", "flatten_": "flatten", "floor_": "floor",
    "floor_mod": "remainder", "remainder_": "remainder",
    "index_add_": "index_add", "lerp_": "lerp",
    "put_along_axis_": "put_along_axis", "reciprocal_": "reciprocal",
    "reshape_": "reshape", "round_": "round", "rsqrt_": "rsqrt",
    "scale_": "scale", "scatter_": "scatter", "sqrt_": "sqrt",
    "squeeze_": "squeeze", "subtract_": "subtract", "tanh_": "tanh",
    "unsqueeze_": "unsqueeze", "uniform_": "uniform",
}


def _register_aliases():
    for name, base in _ALIASES.items():
        spec = registry._OPS[base]
        alias = OpSpec(name, spec.fn, spec.category, None, None, spec.ref,
                       spec.differentiable, None, spec.jit_ok,
                       alias_of=base)
        registry._OPS[name] = alias
        globals()[name] = spec.fn
        __all__.append(name)


_register_aliases()


# -- TensorArray (≙ LoDTensorArray, python/paddle/tensor/array.py) ----------

def create_array(dtype="float32", initialized_list=None):
    """ref: array.py create_array:151 — a plain Python list IS the
    TensorArray in eager/traced JAX (lod metadata dissolves)."""
    return list(initialized_list) if initialized_list is not None else []


def array_write(x, i, array=None):
    """ref: array.py array_write:74 — write x at index i, growing as
    needed."""
    if array is None:
        array = []
    i = int(i)
    while len(array) <= i:
        array.append(None)
    array[i] = jnp.asarray(x)
    return array


def array_read(array, i):
    """ref: array.py array_read:25."""
    return array[int(i)]


def array_length(array):
    """ref: array.py array_length:118 (int32: the reference's int64 is
    unavailable with jax x64 disabled)."""
    return jnp.asarray(len(array), jnp.int32)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """ref: tensor/to_string.py set_printoptions — forwards to numpy's
    printoptions (jax arrays print through numpy)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)
    try:
        jnp.set_printoptions(**kw)
    except AttributeError:
        pass


register_op("create_array", create_array, "array",
            np_ref=lambda: np.zeros(0),
            sample_args=lambda: ((), {}),
            ref="python/paddle/tensor/array.py:151", differentiable=False)
registry._OPS["create_array"].test_fn = \
    lambda: jnp.zeros(len(create_array()))
register_op("array_write", array_write, "array",
            np_ref=lambda x: np.asarray(x),
            sample_args=lambda: ((np.arange(3.0, dtype=np.float32),), {}),
            ref="python/paddle/tensor/array.py:74", differentiable=False)
registry._OPS["array_write"].test_fn = \
    lambda x: array_read(array_write(x, 0), 0)
register_op("array_read", array_read, "array",
            np_ref=lambda x: np.asarray(x),
            sample_args=lambda: ((np.arange(4.0, dtype=np.float32),), {}),
            ref="python/paddle/tensor/array.py:25", differentiable=False)
registry._OPS["array_read"].test_fn = \
    lambda x: array_read(array_write(x, 2), 2)
register_op("array_length", array_length, "array",
            np_ref=lambda x: np.asarray(3, np.int32),
            sample_args=lambda: ((np.zeros(2, np.float32),), {}),
            ref="python/paddle/tensor/array.py:118", differentiable=False)
registry._OPS["array_length"].test_fn = \
    lambda x: array_length(array_write(x, 2))
register_op("set_printoptions", set_printoptions, "framework",
            np_ref=lambda: np.zeros(0),
            sample_args=lambda: ((), {}),
            ref="python/paddle/tensor/to_string.py", differentiable=False)
registry._OPS["set_printoptions"].test_fn = \
    lambda: (set_printoptions(precision=8), jnp.zeros(0))[1]
