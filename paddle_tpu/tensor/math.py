"""Math ops (ref: python/paddle/tensor/math.py — largest of the tensor-op
modules). All functions take/return jax Arrays; autodiff, broadcasting and
fusion come from tracing into XLA, so there is no per-op kernel or grad-node
codegen (contrast eager_gen.py in the reference)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.scipy import special as jsp_special

import scipy.special as sp_special

from paddle_tpu.ops.registry import register_op
from paddle_tpu.tensor._gen import make_unary, make_binary, _sample

__all__ = []

# name: (jax_fn, numpy_oracle, input_domain, differentiable)
_UNARY = {
    "abs": (jnp.abs, np.abs, "nonzero", True),
    "acos": (jnp.arccos, np.arccos, "unit", True),
    "acosh": (jnp.arccosh, np.arccosh, "ge1", True),
    "asin": (jnp.arcsin, np.arcsin, "unit", True),
    "asinh": (jnp.arcsinh, np.arcsinh, "real", True),
    "atan": (jnp.arctan, np.arctan, "real", True),
    "atanh": (jnp.arctanh, np.arctanh, "unit", True),
    "ceil": (jnp.ceil, np.ceil, "real", False),
    "cos": (jnp.cos, np.cos, "real", True),
    "cosh": (jnp.cosh, np.cosh, "real", True),
    "deg2rad": (jnp.deg2rad, np.deg2rad, "real", True),
    "digamma": (jsp_special.digamma, sp_special.digamma, "positive", True),
    "erf": (jax.lax.erf, sp_special.erf, "real", True),
    "erfinv": (jax.lax.erf_inv, sp_special.erfinv, "unit", True),
    "exp": (jnp.exp, np.exp, "real", True),
    "expm1": (jnp.expm1, np.expm1, "real", True),
    "floor": (jnp.floor, np.floor, "real", False),
    "frac": (lambda x: x - jnp.trunc(x), lambda x: x - np.trunc(x), "real", True),
    "i0": (jsp_special.i0, sp_special.i0, "real", True),
    "i0e": (jsp_special.i0e, sp_special.i0e, "real", True),
    "i1": (jsp_special.i1, sp_special.i1, "real", True),
    "i1e": (jsp_special.i1e, sp_special.i1e, "real", True),
    "lgamma": (jsp_special.gammaln, sp_special.gammaln, "positive", True),
    "log": (jnp.log, np.log, "positive", True),
    "log10": (jnp.log10, np.log10, "positive", True),
    "log1p": (jnp.log1p, np.log1p, "positive", True),
    "log2": (jnp.log2, np.log2, "positive", True),
    "neg": (jnp.negative, np.negative, "real", True),
    "rad2deg": (jnp.rad2deg, np.rad2deg, "real", True),
    "reciprocal": (jnp.reciprocal, np.reciprocal, "nonzero", True),
    "round": (jnp.round, np.round, "real", False),
    "rsqrt": (jax.lax.rsqrt, lambda x: 1.0 / np.sqrt(x), "positive", True),
    "sigmoid": (jax.nn.sigmoid, lambda x: 1 / (1 + np.exp(-x)), "real", True),
    "sign": (jnp.sign, np.sign, "nonzero", False),
    "sgn": (jnp.sign, np.sign, "nonzero", False),
    "sin": (jnp.sin, np.sin, "real", True),
    "sinh": (jnp.sinh, np.sinh, "real", True),
    "sqrt": (jnp.sqrt, np.sqrt, "positive", True),
    "square": (jnp.square, np.square, "real", True),
    "tan": (jnp.tan, np.tan, "unit", True),
    "tanh": (jnp.tanh, np.tanh, "real", True),
    "trunc": (jnp.trunc, np.trunc, "real", False),
    "angle": (jnp.angle, np.angle, "nonzero", False),
    "conj": (jnp.conj, np.conj, "real", True),
    "isfinite": (jnp.isfinite, np.isfinite, "real", False),
    "isinf": (jnp.isinf, np.isinf, "real", False),
    "isnan": (jnp.isnan, np.isnan, "real", False),
    "logit": (jsp_special.logit, sp_special.logit, "unit01", True),
    "exp2": (jnp.exp2, np.exp2, "real", True),
}

_BINARY = {
    "add": (jnp.add, np.add, "real", True),
    "subtract": (jnp.subtract, np.subtract, "real", True),
    "multiply": (jnp.multiply, np.multiply, "real", True),
    "divide": (jnp.divide, np.divide, "nonzero", True),
    "floor_divide": (jnp.floor_divide, np.floor_divide, "positive", False),
    "mod": (jnp.mod, np.mod, "positive", False),
    "remainder": (jnp.remainder, np.remainder, "positive", False),
    "pow": (jnp.power, np.power, "positive", True),
    "maximum": (jnp.maximum, np.maximum, "real", True),
    "minimum": (jnp.minimum, np.minimum, "real", True),
    "fmax": (jnp.fmax, np.fmax, "real", True),
    "fmin": (jnp.fmin, np.fmin, "real", True),
    "atan2": (jnp.arctan2, np.arctan2, "nonzero", True),
    "hypot": (jnp.hypot, np.hypot, "real", True),
    "logaddexp": (jnp.logaddexp, np.logaddexp, "real", True),
    "heaviside": (jnp.heaviside, np.heaviside, "nonzero", False),
    "copysign": (jnp.copysign, np.copysign, "nonzero", False),
    "nextafter": (jnp.nextafter, np.nextafter, "real", False),
    "ldexp": (lambda x, y: x * jnp.exp2(jnp.floor(y)),
              lambda x, y: x * np.exp2(np.floor(y)), "real", True),
    "gcd": (jnp.gcd, np.gcd, "int", False),
    "lcm": (jnp.lcm, np.lcm, "int", False),
    "bitwise_and": (jnp.bitwise_and, np.bitwise_and, "int", False),
    "bitwise_or": (jnp.bitwise_or, np.bitwise_or, "int", False),
    "bitwise_xor": (jnp.bitwise_xor, np.bitwise_xor, "int", False),
    "logical_and": (jnp.logical_and, np.logical_and, "bool", False),
    "logical_or": (jnp.logical_or, np.logical_or, "bool", False),
    "logical_xor": (jnp.logical_xor, np.logical_xor, "bool", False),
}

make_unary(__all__, globals(), _UNARY, "math.unary")
make_binary(__all__, globals(), _BINARY, "math.binary")


def _reg(name, fn, np_ref=None, sample=None, category="math", diff=True):
    register_op(name, fn, category, np_ref=np_ref, sample_args=sample,
                differentiable=diff)
    globals()[name] = fn
    __all__.append(name)
    return fn


def bitwise_not(x):
    return jnp.bitwise_not(jnp.asarray(x))


def logical_not(x):
    return jnp.logical_not(jnp.asarray(x))


_reg("bitwise_not", bitwise_not, np.bitwise_not,
     lambda: ((_sample("int"),), {}), diff=False)
_reg("logical_not", logical_not, np.logical_not,
     lambda: ((_sample("bool"),), {}), diff=False)


# -------------------- reductions --------------------

def _axis_kw(axis, keepdim):
    return dict(axis=axis, keepdims=keepdim)


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    return jnp.sum(jnp.asarray(x), axis=axis, dtype=dtype, keepdims=keepdim)


def mean(x, axis=None, keepdim=False):
    return jnp.mean(jnp.asarray(x), axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.max(jnp.asarray(x), axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.min(jnp.asarray(x), axis=axis, keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(jnp.asarray(x), axis=axis, dtype=dtype, keepdims=keepdim)


def amax(x, axis=None, keepdim=False):
    return jnp.amax(jnp.asarray(x), axis=axis, keepdims=keepdim)


def amin(x, axis=None, keepdim=False):
    return jnp.amin(jnp.asarray(x), axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False):
    return jsp_special.logsumexp(jnp.asarray(x), axis=axis, keepdims=keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(jnp.asarray(x), axis=axis, dtype=dtype, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(jnp.asarray(x), axis=axis, keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(jnp.asarray(x), axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.all(jnp.asarray(x), axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.any(jnp.asarray(x), axis=axis, keepdims=keepdim)


for _name, _np in [("sum", np.sum), ("mean", np.mean), ("max", np.max),
                   ("min", np.min), ("prod", np.prod), ("amax", np.amax),
                   ("amin", np.amin), ("nansum", np.nansum),
                   ("nanmean", np.nanmean)]:
    _reg(_name, globals()[_name], _np, lambda: ((_sample("real"),), {}),
         category="math.reduce")
_reg("logsumexp", logsumexp, sp_special.logsumexp,
     lambda: ((_sample("real"),), {}), category="math.reduce")
_reg("count_nonzero", count_nonzero, np.count_nonzero,
     lambda: ((_sample("int"),), {}), category="math.reduce", diff=False)
_reg("all", globals()["all"], np.all, lambda: ((_sample("bool"),), {}),
     category="math.reduce", diff=False)
_reg("any", globals()["any"], np.any, lambda: ((_sample("bool"),), {}),
     category="math.reduce", diff=False)


# -------------------- scans / cumulative --------------------

def cumsum(x, axis=None, dtype=None):
    x = jnp.asarray(x)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def cumprod(x, dim=None, dtype=None):
    return jnp.cumprod(jnp.asarray(x), axis=dim, dtype=dtype)


def cummax(x, axis=-1):
    x = jnp.asarray(x)
    vals = jax.lax.associative_scan(jax.lax.max, x, axis=axis)
    return vals


def cummin(x, axis=-1):
    return jax.lax.associative_scan(jax.lax.min, jnp.asarray(x), axis=axis)


def logcumsumexp(x, axis=-1):
    x = jnp.asarray(x)
    return jax.lax.cumlogsumexp(x, axis=axis % x.ndim)


_reg("cumsum", cumsum, lambda x: np.cumsum(x.reshape(-1)),
     lambda: ((_sample("real"),), {}))
_reg("cumprod", cumprod, None)
_reg("cummax", cummax, lambda x: np.maximum.accumulate(x, -1),
     lambda: ((_sample("real"),), {}), diff=False)
_reg("cummin", cummin, lambda x: np.minimum.accumulate(x, -1),
     lambda: ((_sample("real"),), {}), diff=False)
_reg("logcumsumexp", logcumsumexp, None)


# -------------------- misc math --------------------

def clip(x, min=None, max=None):  # noqa: A002
    return jnp.clip(jnp.asarray(x), min, max)


def lerp(x, y, weight):
    return x + weight * (jnp.asarray(y) - x)


def addmm(input, x, y, beta=1.0, alpha=1.0):  # noqa: A002
    return beta * input + alpha * (jnp.asarray(x) @ jnp.asarray(y))


def multiplex(inputs, index):
    stacked = jnp.stack(inputs, axis=0)
    idx = jnp.asarray(index).reshape(-1)
    return jnp.take_along_axis(
        stacked, idx[None, :, None].astype(jnp.int32), axis=0)[0]


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):  # noqa: A002
    x = jnp.asarray(x)
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * jnp.asarray(x))


def softplus_op(x, beta=1.0, threshold=20.0):
    x = jnp.asarray(x)
    return jnp.where(x * beta > threshold, x,
                     jnp.log1p(jnp.exp(beta * x)) / beta)


def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(jnp.asarray(x), offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(jnp.asarray(x), offset=offset, axis1=axis1, axis2=axis2)


def kron(x, y):
    return jnp.kron(jnp.asarray(x), jnp.asarray(y))


def diff(x, n=1, axis=-1, prepend=None, append=None):
    return jnp.diff(jnp.asarray(x), n=n, axis=axis, prepend=prepend,
                    append=append)


def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(jnp.asarray(x), k=k, axes=axes)


def inner(x, y):
    return jnp.inner(jnp.asarray(x), jnp.asarray(y))


def outer(x, y):
    return jnp.outer(jnp.asarray(x), jnp.asarray(y))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(jnp.asarray(x), nan=nan, posinf=posinf,
                          neginf=neginf)


def take(x, index, mode="raise"):
    return jnp.take(jnp.asarray(x).reshape(-1), jnp.asarray(index),
                    mode="clip" if mode == "raise" else mode)


_reg("clip", clip, lambda x: x, lambda: ((_sample("real"),), {}))
_reg("lerp", lerp, None)
_reg("addmm", addmm, None)
_reg("multiplex", multiplex, None)
_reg("scale", scale, lambda x: x, lambda: ((_sample("real"),), {}))
_reg("stanh", stanh, lambda x: 1.7159 * np.tanh(0.67 * x),
     lambda: ((_sample("real"),), {}))
_reg("trace", trace, np.trace, lambda: ((_sample("real"),), {}))
_reg("diagonal", diagonal, np.diagonal, lambda: ((_sample("real"),), {}))
_reg("kron", kron, np.kron, lambda: ((_sample("real"), _sample("real")), {}))
_reg("diff", diff, np.diff, lambda: ((_sample("real"),), {}))
_reg("rot90", rot90, np.rot90, lambda: ((_sample("real"),), {}))
_reg("inner", inner, np.inner, lambda: ((_sample("real"), _sample("real")), {}))
_reg("outer", outer, None)
_reg("nan_to_num", nan_to_num, np.nan_to_num, lambda: ((_sample("real"),), {}))
_reg("take", take, None, diff=False)


def add_n(inputs):
    """Sum a list of tensors (ref: python/paddle/tensor/math.py add_n →
    sum_op); XLA fuses the chain into one kernel."""
    if not isinstance(inputs, (list, tuple)):
        return jnp.asarray(inputs)
    out = jnp.asarray(inputs[0])
    for t in inputs[1:]:
        out = out + jnp.asarray(t)
    return out


def dist(x, y, p=2):
    """p-norm of (x - y) (ref math.py dist → dist_op)."""
    d = jnp.abs(jnp.asarray(x) - jnp.asarray(y))
    if p == float("inf"):
        return jnp.max(d)
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    return jnp.sum(d ** p) ** (1.0 / p)


def frexp(x):
    """(mantissa, exponent) decomposition (ref math.py frexp)."""
    return jnp.frexp(jnp.asarray(x))


def increment(x, value=1.0):
    """Functional increment (the reference mutates in place)."""
    return jnp.asarray(x) + value


def inverse(x):
    """Matrix inverse (ref math.py inverse → inverse_op)."""
    return jnp.linalg.inv(jnp.asarray(x))


def renorm(x, p, axis, max_norm):
    """Clamp the p-norm of every slice along ``axis`` to ``max_norm``
    (ref math.py renorm → renorm_op)."""
    x = jnp.asarray(x)
    axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


def trapezoid(y, x=None, dx=None, axis=-1):
    """Trapezoidal integration (ref math.py trapezoid)."""
    if x is not None:
        return jnp.trapezoid(jnp.asarray(y), jnp.asarray(x), axis=axis)
    return jnp.trapezoid(jnp.asarray(y), dx=1.0 if dx is None else dx,
                         axis=axis)


def broadcast_shape(x_shape, y_shape):
    """Static broadcast-shape utility (ref math.py broadcast_shape)."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def is_complex(x):
    return jnp.iscomplexobj(jnp.asarray(x))


def is_floating_point(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer)


_reg("add_n", add_n, None)
_reg("dist", dist, None)
_reg("frexp", frexp, None, diff=False)
_reg("increment", increment, lambda x: x + 1.0,
     lambda: ((_sample("real"),), {}))
_reg("inverse", inverse, None)
_reg("renorm", renorm, None)
_reg("trapezoid", trapezoid, None)
_reg("broadcast_shape", broadcast_shape, None, diff=False)
_reg("is_complex", is_complex, None, diff=False)
_reg("is_floating_point", is_floating_point, None, diff=False)
_reg("is_integer", is_integer, None, diff=False)
