"""Statistics ops (ref: python/paddle/tensor/stat.py)."""

import numpy as np
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op
from paddle_tpu.tensor._gen import _sample

__all__ = []


def _reg(name, fn, np_ref=None, sample=None, diff=True):
    register_op(name, fn, "stat", np_ref=np_ref, sample_args=sample,
                differentiable=diff)
    globals()[name] = fn
    __all__.append(name)
    return fn


def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(jnp.asarray(x), axis=axis, ddof=1 if unbiased else 0,
                   keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(jnp.asarray(x), axis=axis, ddof=1 if unbiased else 0,
                   keepdims=keepdim)


def median(x, axis=None, keepdim=False):
    return jnp.median(jnp.asarray(x), axis=axis, keepdims=keepdim)


def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(jnp.asarray(x), axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(jnp.asarray(x), jnp.asarray(q), axis=axis,
                        keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(jnp.asarray(x), jnp.asarray(q), axis=axis,
                           keepdims=keepdim)


_reg("std", std, lambda x: np.std(x, ddof=1), lambda: ((_sample("real"),), {}))
_reg("var", var, lambda x: np.var(x, ddof=1), lambda: ((_sample("real"),), {}))
_reg("median", median, np.median, lambda: ((_sample("real"),), {}))
_reg("nanmedian", nanmedian, np.nanmedian, lambda: ((_sample("real"),), {}))
_reg("quantile", quantile, None)
_reg("nanquantile", nanquantile, None)
