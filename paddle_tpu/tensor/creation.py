"""Creation ops (ref: python/paddle/tensor/creation.py)."""

import numpy as np
import jax.numpy as jnp

from paddle_tpu.dtypes import get_default_dtype, to_dtype
from paddle_tpu.ops.registry import register_op

__all__ = []


def _reg(name, fn):
    register_op(name, fn, "creation", differentiable=False)
    globals()[name] = fn
    __all__.append(name)
    return fn


def _dt(dtype, floating=True):
    if dtype is None:
        return get_default_dtype() if floating else np.int64
    return to_dtype(dtype)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    arr = jnp.asarray(data)
    if dtype is not None:
        arr = arr.astype(to_dtype(dtype))
    return arr


def zeros(shape, dtype=None):
    return jnp.zeros(shape, _dt(dtype))


def ones(shape, dtype=None):
    return jnp.ones(shape, _dt(dtype))


def full(shape, fill_value, dtype=None):
    if dtype is None:
        return jnp.full(shape, fill_value)
    return jnp.full(shape, fill_value, to_dtype(dtype))


def zeros_like(x, dtype=None):
    return jnp.zeros_like(jnp.asarray(x), dtype=None if dtype is None else to_dtype(dtype))


def ones_like(x, dtype=None):
    return jnp.ones_like(jnp.asarray(x), dtype=None if dtype is None else to_dtype(dtype))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(jnp.asarray(x), fill_value,
                         dtype=None if dtype is None else to_dtype(dtype))


def empty(shape, dtype=None):
    return jnp.zeros(shape, _dt(dtype))


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step,
                      dtype=None if dtype is None else to_dtype(dtype))


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=_dt(dtype))


def logspace(start, stop, num, base=10.0, dtype=None):
    return jnp.logspace(start, stop, num, base=base, dtype=_dt(dtype))


def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=_dt(dtype))


def tril_indices(row, col=None, offset=0):
    r = jnp.tril_indices(row, k=offset, m=col)
    return jnp.stack(r, axis=0)


def triu_indices(row, col=None, offset=0):
    r = jnp.triu_indices(row, k=offset, m=col)
    return jnp.stack(r, axis=0)


def clone(x):
    return jnp.asarray(x) + 0  # functional copy


def assign(x, output=None):
    return jnp.asarray(x)


def complex(real, imag):  # noqa: A001
    import jax
    return jax.lax.complex(jnp.asarray(real), jnp.asarray(imag))


def polar(abs, angle):  # noqa: A002
    import jax
    a = jnp.asarray(abs)
    t = jnp.asarray(angle)
    return jax.lax.complex(a * jnp.cos(t), a * jnp.sin(t))


def one_hot(x, num_classes):
    import jax
    return jax.nn.one_hot(jnp.asarray(x), num_classes)


for _n in ["to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
           "full_like", "empty", "empty_like", "arange", "linspace",
           "logspace", "eye", "tril_indices", "triu_indices", "clone",
           "assign", "complex", "polar", "one_hot"]:
    _reg(_n, globals()[_n])


def vander(x, n=None, increasing=False):
    """Vandermonde matrix (ref: python/paddle/tensor/creation.py vander)."""
    return jnp.vander(jnp.asarray(x), N=n, increasing=increasing)


def gaussian(shape, mean=0.0, std=1.0, dtype=None, key=None):
    """Gaussian sample (ref creation.py gaussian → gaussian_random op)."""
    from paddle_tpu.dtypes import to_dtype
    from paddle_tpu.tensor.random_ops import normal
    out = normal(mean=mean, std=std, shape=shape, key=key)
    return out.astype(to_dtype(dtype)) if dtype is not None else out


_reg("vander", vander)
_reg("gaussian", gaussian)
