"""Search / sort ops (ref: python/paddle/tensor/search.py)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op
from paddle_tpu.tensor._gen import _sample

__all__ = []


def _reg(name, fn, np_ref=None, sample=None, diff=False):
    register_op(name, fn, "search", np_ref=np_ref, sample_args=sample,
                differentiable=diff)
    globals()[name] = fn
    __all__.append(name)
    return fn


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    r = jnp.argmax(jnp.asarray(x), axis=axis, keepdims=keepdim if axis is not None else False)
    return r


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    return jnp.argmin(jnp.asarray(x), axis=axis,
                      keepdims=keepdim if axis is not None else False)


def argsort(x, axis=-1, descending=False, stable=True):
    x = jnp.asarray(x)
    idx = jnp.argsort(x, axis=axis, stable=stable,
                      descending=descending)
    return idx


def sort(x, axis=-1, descending=False, stable=True):
    x = jnp.asarray(x)
    return jnp.sort(x, axis=axis, stable=stable, descending=descending)


def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    x = jnp.asarray(x)
    if axis not in (-1, x.ndim - 1):
        x_m = jnp.moveaxis(x, axis, -1)
        v, i = topk(x_m, k, -1, largest, sorted)
        return jnp.moveaxis(v, -1, axis), jnp.moveaxis(i, -1, axis)
    if largest:
        return jax.lax.top_k(x, k)
    v, i = jax.lax.top_k(-x, k)
    return -v, i


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    r = jnp.searchsorted(jnp.asarray(sorted_sequence), jnp.asarray(values),
                         side="right" if right else "left")
    return r.astype(jnp.int32) if out_int32 else r


def kthvalue(x, k, axis=-1, keepdim=False):
    x = jnp.asarray(x)
    s = jnp.sort(x, axis=axis)
    i = jnp.argsort(x, axis=axis)
    v = jnp.take(s, k - 1, axis=axis)
    idx = jnp.take(i, k - 1, axis=axis)
    if keepdim:
        v = jnp.expand_dims(v, axis)
        idx = jnp.expand_dims(idx, axis)
    return v, idx


def mode(x, axis=-1, keepdim=False):
    x_np = np.asarray(jax.device_get(x))
    import scipy.stats
    m = scipy.stats.mode(x_np, axis=axis, keepdims=keepdim)
    return jnp.asarray(m.mode), jnp.asarray(m.count)


def index_fill(x, index, axis, value):
    x = jnp.asarray(x)
    idx = [slice(None)] * x.ndim
    idx[axis] = jnp.asarray(index)
    return x.at[tuple(idx)].set(value)


def bucketize(x, sorted_sequence, out_int32=False, right=False):
    return searchsorted(sorted_sequence, x, out_int32, right)


_reg("argmax", argmax, np.argmax, lambda: ((_sample("real"),), {}))
_reg("argmin", argmin, np.argmin, lambda: ((_sample("real"),), {}))
_reg("argsort", argsort, np.argsort, lambda: ((_sample("real"),), {}))
_reg("sort", sort, np.sort, lambda: ((_sample("real"),), {}), diff=True)
_reg("topk", topk)
_reg("searchsorted", searchsorted)
_reg("kthvalue", kthvalue)
_reg("mode", mode)
_reg("index_fill", index_fill)
_reg("bucketize", bucketize)
