"""Sequence ops over dense padded batches (the TPU-native LoD replacement).

Reference analog: the LoD sequence op family
(paddle/fluid/operators/sequence_ops/, python surface
python/paddle/fluid/layers/sequence_lod.py, re-exported as
paddle.static.nn.sequence_*).  The reference represents variable-length
batches as LoD (level-of-detail) tensors — a flat value buffer plus host-side
offset tables — and every sequence op walks the offsets.  That layout is
hostile to XLA (dynamic shapes, host-resident metadata), so here the SAME
operations are defined over the TPU-idiomatic representation:

    x        : (B, T, ...) dense, each row's valid data a prefix
    lengths  : (B,) int32, valid timesteps per row

Every op is a pure jax function; all but the host-boundary converters
(sequence_pad / sequence_unpad / sequence_expand, which by nature produce
ragged Python data) trace under jit with static shapes.  Ops whose result
has per-row valid extents return ``(out, out_lengths)`` so they chain.

The LoD→dense mapping for porting is documented in docs/porting_guide.md.
"""

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op

__all__ = [
    "sequence_softmax", "sequence_pool", "sequence_first_step",
    "sequence_last_step", "sequence_reverse", "sequence_enumerate",
    "sequence_conv", "sequence_expand_as", "sequence_expand",
    "sequence_reshape", "sequence_slice", "sequence_concat",
    "sequence_erase", "sequence_scatter", "sequence_pad", "sequence_unpad",
]


def _mask(lengths, T, extra_dims=0):
    """(B, T[, 1]*extra_dims) bool validity mask."""
    m = jnp.arange(T)[None, :] < jnp.asarray(lengths)[:, None]
    return m.reshape(m.shape + (1,) * extra_dims)


def sequence_softmax(x, lengths):
    """Masked softmax along the time axis (axis 1).

    ref: sequence_softmax_op.cc / sequence_lod.py:191 — softmax within each
    sequence independently; padded positions get probability 0."""
    x = jnp.asarray(x)
    m = _mask(lengths, x.shape[1], x.ndim - 2)
    neg = jnp.finfo(jnp.result_type(x, jnp.float32)).min
    z = jnp.where(m, x, neg)
    z = z - jnp.max(z, axis=1, keepdims=True)
    e = jnp.exp(z) * m.astype(x.dtype)
    return e / jnp.maximum(jnp.sum(e, axis=1, keepdims=True), 1e-30)


def sequence_pool(x, lengths, pool_type="sum", pad_value=0.0):
    """Reduce the time axis per sequence: sum/average/sqrt/max/min/first/last.

    ref: sequence_pool_op.cc / sequence_lod.py:278.  'sqrt' is sum scaled by
    1/sqrt(len) (the reference's attention-pooling variant).  Empty sequences
    produce ``pad_value`` (ref pad_value attr)."""
    x = jnp.asarray(x)
    lengths = jnp.asarray(lengths)
    T = x.shape[1]
    m = _mask(lengths, T, x.ndim - 2)
    md = m.astype(x.dtype)
    # broadcast-safe per-row divisor / emptiness
    len_shaped = lengths.reshape((-1,) + (1,) * (x.ndim - 2))
    empty = len_shaped == 0
    if pool_type in ("sum", "average", "sqrt"):
        s = jnp.sum(x * md, axis=1)
        if pool_type == "average":
            s = s / jnp.maximum(len_shaped, 1).astype(x.dtype)
        elif pool_type == "sqrt":
            s = s / jnp.sqrt(jnp.maximum(len_shaped, 1).astype(x.dtype))
        out = s
    elif pool_type in ("max", "min"):
        info = (jnp.finfo if jnp.issubdtype(x.dtype, jnp.inexact)
                else jnp.iinfo)(x.dtype)
        lim = info.min if pool_type == "max" else info.max
        z = jnp.where(m, x, lim)
        out = z.max(axis=1) if pool_type == "max" else z.min(axis=1)
    elif pool_type == "first":
        out = x[:, 0]
    elif pool_type == "last":
        idx = jnp.maximum(lengths - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
    else:
        raise ValueError(f"unknown pool_type {pool_type!r}")
    return jnp.where(empty, jnp.asarray(pad_value, x.dtype), out)


def sequence_first_step(x, lengths):
    """ref: sequence_lod.py:464 — first timestep of each sequence."""
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths):
    """ref: sequence_lod.py:522 — last valid timestep of each sequence."""
    return sequence_pool(x, lengths, "last")


def sequence_reverse(x, lengths):
    """Reverse each sequence's valid prefix; padding stays in place.

    ref: sequence_reverse_op.cc / sequence_lod.py:1434."""
    x = jnp.asarray(x)
    lengths = jnp.asarray(lengths)
    t = jnp.arange(x.shape[1])[None, :]
    src = jnp.where(t < lengths[:, None], lengths[:, None] - 1 - t, t)
    return jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)


def sequence_enumerate(x, lengths, win_size, pad_value=0):
    """Sliding windows of ids: out[b, t, k] = x[b, t+k] while t+k is inside
    the sequence, else pad_value.

    ref: sequence_enumerate_op.cc / sequence_lod.py:1301 (the all-window
    enumeration used by n-gram feature extraction)."""
    x = jnp.asarray(x)
    lengths = jnp.asarray(lengths)
    B, T = x.shape[:2]
    t = jnp.arange(T)[None, :, None]                  # (1, T, 1)
    k = jnp.arange(win_size)[None, None, :]           # (1, 1, K)
    pos = t + k                                       # (1, T, K)
    valid = pos < lengths[:, None, None]              # (B, T, K)
    gathered = x[jnp.arange(B)[:, None, None], jnp.minimum(pos, T - 1)]
    return jnp.where(valid, gathered, jnp.asarray(pad_value, x.dtype))


def sequence_conv(x, lengths, weight, bias=None, padding_start=None):
    """Contextual (a.k.a. row) convolution over each sequence.

    For filter_size F (= weight.shape[0] // D) the window at step t covers
    timesteps [t + padding_start, t + padding_start + F); positions outside
    the valid sequence contribute zeros.  ``padding_start`` defaults to
    ``-(F // 2)`` like the reference.

    ref: sequence_conv_op.cc (im2col over LoD rows) / sequence_lod.py:51,
    default padding sequence_lod.py:171-172.  Here the im2col is F static
    shifts concatenated on the feature axis — one (B*T, F*D) x (F*D, M)
    matmul, exactly the MXU-friendly layout."""
    x = jnp.asarray(x)
    weight = jnp.asarray(weight)
    B, T, D = x.shape
    F = weight.shape[0] // D
    if padding_start is None:
        padding_start = -(F // 2)
    m = _mask(lengths, T, 1).astype(x.dtype)
    xm = x * m
    cols = []
    for j in range(F):
        off = padding_start + j
        if off < 0:
            shifted = jnp.pad(xm[:, :T + off if T + off > 0 else 0],
                              ((0, 0), (min(-off, T), 0), (0, 0)))
        elif off > 0:
            shifted = jnp.pad(xm[:, min(off, T):],
                              ((0, 0), (0, min(off, T)), (0, 0)))
        else:
            shifted = xm
        cols.append(shifted)
    ctx = jnp.concatenate(cols, axis=-1)              # (B, T, F*D)
    out = ctx @ weight                                # (B, T, M)
    if bias is not None:
        out = out + jnp.asarray(bias)
    return out * _mask(lengths, T, 1).astype(out.dtype)


def sequence_expand_as(x, lengths, maxlen=None):
    """Expand each row of ``x`` (one timestep per sequence) along time to
    its target length: out[b, t] = x[b] for t < lengths[b], else 0.

    ref: sequence_expand_as_op.cc / sequence_lod.py:814.  Returns
    ``(out, lengths)``."""
    x = jnp.asarray(x)
    lengths = jnp.asarray(lengths)
    if maxlen is None:
        # host read, like sequence_mask
        maxlen = int(jnp.max(lengths)) if lengths.size else 0
    lengths = jnp.minimum(lengths, maxlen)
    out = jnp.broadcast_to(x[:, None], (x.shape[0], maxlen) + x.shape[1:])
    out = out * _mask(lengths, maxlen, x.ndim - 1).astype(x.dtype)
    return out, lengths


def sequence_expand(x, lengths, repeats):
    """Repeat each row's sequence ``repeats[b]`` times (ragged output —
    host-side by nature, like the reference's LoD-growing expand).

    ref: sequence_expand_op.cc / sequence_lod.py:675 (ref_level=0 case:
    whole-sequence repetition per y's outer LoD).  Returns the expanded
    padded batch (B', T, ...) and its lengths, B' = sum(repeats)."""
    x = np.asarray(x)
    lengths = np.asarray(lengths)
    repeats = np.asarray(repeats)
    rows = [x[b] for b in range(x.shape[0]) for _ in range(int(repeats[b]))]
    lens = [int(lengths[b]) for b in range(x.shape[0])
            for _ in range(int(repeats[b]))]
    if not rows:
        return (jnp.zeros((0,) + x.shape[1:], x.dtype),
                jnp.zeros((0,), jnp.int32))
    return jnp.asarray(np.stack(rows)), jnp.asarray(lens, jnp.int32)


def sequence_reshape(x, lengths, new_dim):
    """Re-chunk each sequence's features: (B, T, D) → (B, T*D//new_dim,
    new_dim), lengths scaled by D/new_dim.

    Because each row's valid data is a prefix of the flattened row, the
    dense reshape IS the LoD reshape — no data movement beyond XLA's
    bitcast.  ref: sequence_reshape_op.cc / sequence_lod.py:1136 (which
    requires len*D % new_dim == 0 per row; same constraint here)."""
    x = jnp.asarray(x)
    B, T, D = x.shape
    if (T * D) % new_dim:
        raise ValueError(f"T*D={T * D} not divisible by new_dim={new_dim}")
    lengths = jnp.asarray(lengths)
    if not isinstance(lengths, jax.core.Tracer):
        bad = np.asarray(lengths) * D % new_dim != 0
        if bad.any():
            raise ValueError(
                f"rows {np.nonzero(bad)[0].tolist()}: len*D (D={D}) not "
                f"divisible by new_dim={new_dim} (reference constraint)")
    out = x.reshape(B, (T * D) // new_dim, new_dim)
    new_len = (lengths * D) // new_dim
    return out, new_len


def sequence_slice(x, lengths, offset, length):
    """Per-sequence slice: out[b, t] = x[b, offset[b] + t] for t <
    length[b]; the padded width stays x.shape[1].

    ref: sequence_slice_op.cc / sequence_lod.py:581 (offset/length are
    per-sequence tensors there too).  Returns ``(out, length)``."""
    x = jnp.asarray(x)
    offset = jnp.broadcast_to(jnp.asarray(offset, jnp.int32), (x.shape[0],))
    length = jnp.broadcast_to(jnp.asarray(length, jnp.int32), (x.shape[0],))
    if not any(isinstance(v, jax.core.Tracer)
               for v in (offset, length, lengths)):
        over = (np.asarray(offset) + np.asarray(length)
                > np.asarray(lengths))
        if over.any():
            raise ValueError(
                f"rows {np.nonzero(over)[0].tolist()}: offset+length "
                "exceeds the sequence length (reference constraint, "
                "sequence_slice_op.cc)")
    T = x.shape[1]
    t = jnp.arange(T)[None, :]
    src = jnp.clip(offset[:, None] + t, 0, T - 1)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)
    out = out * _mask(length, T, x.ndim - 2).astype(x.dtype)
    return out, length


def sequence_concat(xs, lengths_list):
    """Concatenate sequences row-wise: out row b is xs[0][b][:l0] ++
    xs[1][b][:l1] ++ …, padded to the summed max width.

    ref: sequence_concat_op.cc / sequence_lod.py:396.  Jit-safe: each
    input's valid entries scatter to offset positions computed from the
    running per-row length sums (invalid lanes scatter out of range and
    drop).  Returns ``(out, total_lengths)``."""
    xs = [jnp.asarray(x) for x in xs]
    lens = [jnp.asarray(l) for l in lengths_list]
    B = xs[0].shape[0]
    T_out = sum(x.shape[1] for x in xs)
    feat = xs[0].shape[2:]
    out = jnp.zeros((B, T_out) + feat, xs[0].dtype)
    offset = jnp.zeros((B,), jnp.int32)
    for x, l in zip(xs, lens):
        T = x.shape[1]
        t = jnp.arange(T)[None, :]
        dest = jnp.where(t < l[:, None], offset[:, None] + t, T_out)
        b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
        out = out.at[b_idx, dest].set(x, mode="drop")
        offset = offset + l.astype(jnp.int32)
    return out, offset


def sequence_erase(x, lengths, tokens):
    """Remove every occurrence of ``tokens`` from each sequence and compact
    left; returns ``(out, new_lengths)`` with erased tail zero-padded.

    ref: sequence_erase_op.cc (used to drop <unk>/<pad> ids).  Jit-safe
    compaction: stable argsort of keep-flags moves survivors to the front
    without host sync."""
    x = jnp.asarray(x)
    T = x.shape[1]
    valid = _mask(lengths, T)
    keep = valid
    for tok in tokens:
        keep = keep & (x != tok)
    # stable sort: survivors (key 0) first, in original order
    order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
    compacted = jnp.take_along_axis(x, order, axis=1)
    new_len = keep.sum(axis=1).astype(jnp.int32)
    return compacted * _mask(new_len, T).astype(x.dtype), new_len


def sequence_scatter(x, ids, updates, lengths):
    """out = x; out[b, ids[b, t]] += updates[b, t] for t < lengths[b].

    ref: sequence_scatter_op.h:60-85 (the += is the reference's rule) —
    ids/updates are one scatter list per row there (LoD), here padded
    (B, T) with ``lengths``.  Invalid lanes scatter out of range and
    drop."""
    x = jnp.asarray(x)
    ids = jnp.asarray(ids, jnp.int32)
    updates = jnp.asarray(updates)
    B, T = ids.shape
    dump = x.shape[1]
    dest = jnp.where(_mask(lengths, T), ids, dump)
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    return x.at[b_idx, dest].add(updates.astype(x.dtype), mode="drop")


def sequence_pad(sequences, pad_value=0.0, maxlen=None):
    """Host-boundary converter: list of per-row arrays → dense (B, maxlen,
    ...) + lengths.  ref: sequence_pad_op.cc / sequence_lod.py:934 (returns
    (Out, Length) there too)."""
    seqs = [np.asarray(s) for s in sequences]
    lens = np.asarray([s.shape[0] for s in seqs], np.int32)
    T = int(maxlen) if maxlen is not None else int(lens.max(initial=0))
    feat = seqs[0].shape[1:] if seqs else ()
    out = np.full((len(seqs), T) + feat, pad_value,
                  seqs[0].dtype if seqs else np.float32)
    for b, s in enumerate(seqs):
        out[b, :min(s.shape[0], T)] = s[:T]
    return jnp.asarray(out), jnp.asarray(np.minimum(lens, T))


def sequence_unpad(x, lengths):
    """Inverse of sequence_pad: dense + lengths → list of valid prefixes
    (host).  ref: sequence_unpad_op.cc / sequence_lod.py:1055."""
    x = np.asarray(x)
    lengths = np.asarray(lengths)
    return [x[b, :int(lengths[b])] for b in range(x.shape[0])]


# ---------------------------------------------------------------- registry

def _np_mask(lengths, T):
    return np.arange(T)[None, :] < np.asarray(lengths)[:, None]


def _np_softmax(x, lengths):
    x = np.asarray(x, np.float64)
    m = _np_mask(lengths, x.shape[1])
    z = np.where(m[..., None] if x.ndim == 3 else m, x, -1e30)
    z = z - z.max(axis=1, keepdims=True)
    e = np.exp(z) * (m[..., None] if x.ndim == 3 else m)
    return (e / np.maximum(e.sum(axis=1, keepdims=True), 1e-30)).astype(
        np.float32)


def _np_pool(x, lengths, pool_type="sum", pad_value=0.0):
    x = np.asarray(x, np.float64)
    outs = []
    for b in range(x.shape[0]):
        v = x[b, :int(lengths[b])]
        if v.shape[0] == 0:
            outs.append(np.full(x.shape[2:], pad_value))
        elif pool_type == "sum":
            outs.append(v.sum(0))
        elif pool_type == "average":
            outs.append(v.mean(0))
        elif pool_type == "sqrt":
            outs.append(v.sum(0) / np.sqrt(v.shape[0]))
        elif pool_type == "max":
            outs.append(v.max(0))
        elif pool_type == "min":
            outs.append(v.min(0))
        elif pool_type == "first":
            outs.append(v[0])
        elif pool_type == "last":
            outs.append(v[-1])
    return np.stack(outs).astype(np.float32)


def _np_reverse(x, lengths):
    x = np.array(x)
    for b in range(x.shape[0]):
        n = int(lengths[b])
        x[b, :n] = x[b, :n][::-1]
    return x


def _np_enumerate(x, lengths, win_size=3, pad_value=0):
    x = np.asarray(x)
    B, T = x.shape
    out = np.full((B, T, win_size), pad_value, x.dtype)
    for b in range(B):
        n = int(lengths[b])
        for t in range(T):
            for k in range(win_size):
                if t + k < n:
                    out[b, t, k] = x[b, t + k]
    return out


def _np_conv(x, lengths, weight, padding_start=None):
    x = np.asarray(x, np.float64)
    w = np.asarray(weight, np.float64)
    B, T, D = x.shape
    F = w.shape[0] // D
    if padding_start is None:
        padding_start = -(F // 2)
    out = np.zeros((B, T, w.shape[1]))
    for b in range(B):
        n = int(lengths[b])
        for t in range(n):
            ctx = np.zeros((F, D))
            for j in range(F):
                s = t + padding_start + j
                if 0 <= s < n:
                    ctx[j] = x[b, s]
            out[b, t] = ctx.reshape(-1) @ w
    return out.astype(np.float32)


def _np_slice(x, lengths, offset, length):
    x = np.asarray(x)
    out = np.zeros_like(x)
    offset = np.broadcast_to(np.asarray(offset), (x.shape[0],))
    length = np.broadcast_to(np.asarray(length), (x.shape[0],))
    for b in range(x.shape[0]):
        n = int(length[b])
        out[b, :n] = x[b, int(offset[b]):int(offset[b]) + n]
    return out


def _np_concat(xs, lengths_list):
    B = xs[0].shape[0]
    T_out = sum(x.shape[1] for x in xs)
    out = np.zeros((B, T_out) + xs[0].shape[2:], xs[0].dtype)
    lens = np.zeros((B,), np.int32)
    for b in range(B):
        pos = 0
        for x, l in zip(xs, lengths_list):
            n = int(np.asarray(l)[b])
            out[b, pos:pos + n] = np.asarray(x)[b, :n]
            pos += n
        lens[b] = pos
    return out, lens


def _np_erase(x, lengths, tokens):
    x = np.asarray(x)
    out = np.zeros_like(x)
    lens = np.zeros((x.shape[0],), np.int32)
    for b in range(x.shape[0]):
        kept = [v for v in x[b, :int(lengths[b])] if v not in tokens]
        out[b, :len(kept)] = kept
        lens[b] = len(kept)
    return out, lens


def _np_scatter(x, ids, updates, lengths):
    out = np.array(x, np.float64)
    for b in range(ids.shape[0]):
        for t in range(int(lengths[b])):
            out[b, int(ids[b, t])] += updates[b, t]
    return out.astype(np.float32)


def _register():
    rs = np.random.RandomState(20260731)
    B, T, D = 4, 7, 6
    lens = np.array([7, 4, 1, 0], np.int32)
    xf = rs.randn(B, T, D).astype(np.float32)
    xi = rs.randint(1, 9, (B, T)).astype(np.int32)

    register_op("sequence_softmax", sequence_softmax, "sequence",
                np_ref=lambda x, l: _np_softmax(x, l),
                sample_args=lambda: ((xf, lens), {}),
                ref="fluid/layers/sequence_lod.py:191")
    for pt in ("sum", "average", "sqrt", "max", "min", "first", "last"):
        register_op(
            f"sequence_pool_{pt}" if pt != "sum" else "sequence_pool",
            sequence_pool, "sequence",
            np_ref=(lambda p: lambda x, l: _np_pool(x, l, p))(pt),
            sample_args=(lambda p: lambda: ((xf, lens), {"pool_type": p}))(
                pt),
            test_fn=(lambda p: lambda x, l, pool_type=None:
                     sequence_pool(x, l, p))(pt),
            ref="fluid/layers/sequence_lod.py:278")
    register_op("sequence_first_step", sequence_first_step, "sequence",
                np_ref=lambda x, l: _np_pool(x, l, "first"),
                sample_args=lambda: ((xf, lens), {}),
                ref="fluid/layers/sequence_lod.py:464")
    register_op("sequence_last_step", sequence_last_step, "sequence",
                np_ref=lambda x, l: _np_pool(x, l, "last"),
                sample_args=lambda: ((xf, lens), {}),
                ref="fluid/layers/sequence_lod.py:522")
    register_op("sequence_reverse", sequence_reverse, "sequence",
                np_ref=lambda x, l: _np_reverse(x, l),
                sample_args=lambda: ((xf, lens), {}),
                ref="fluid/layers/sequence_lod.py:1434")
    register_op("sequence_enumerate", sequence_enumerate, "sequence",
                np_ref=lambda x, l: _np_enumerate(x, l, 3, 0),
                sample_args=lambda: ((xi, lens), {"win_size": 3}),
                differentiable=False,
                ref="fluid/layers/sequence_lod.py:1301")
    wconv = rs.randn(3 * D, 5).astype(np.float32)
    register_op("sequence_conv", sequence_conv, "sequence",
                np_ref=lambda x, l, w: _np_conv(x, l, w),
                sample_args=lambda: ((xf, lens, wconv), {}),
                ref="fluid/layers/sequence_lod.py:51")
    x1 = rs.randn(B, D).astype(np.float32)
    register_op("sequence_expand_as", sequence_expand_as, "sequence",
                np_ref=lambda x, l: np.where(
                    _np_mask(l, 7)[..., None], np.asarray(x)[:, None], 0.0
                ).astype(np.float32),
                sample_args=lambda: ((x1, lens), {"maxlen": 7}),
                test_fn=lambda x, l, maxlen=7: sequence_expand_as(
                    x, l, maxlen)[0],
                ref="fluid/layers/sequence_lod.py:814")
    reps = np.array([2, 0, 1, 3], np.int32)
    register_op("sequence_expand", sequence_expand, "sequence",
                np_ref=lambda x, l, r: np.stack(
                    [np.asarray(x)[b] for b in range(len(r))
                     for _ in range(int(r[b]))]),
                sample_args=lambda: ((xf, lens, reps), {}),
                test_fn=lambda x, l, r: sequence_expand(x, l, r)[0],
                jit_ok=False, differentiable=False,
                ref="fluid/layers/sequence_lod.py:675")
    register_op("sequence_reshape", sequence_reshape, "sequence",
                np_ref=lambda x, l: np.asarray(x).reshape(B, T * 2, D // 2),
                sample_args=lambda: ((xf, lens), {"new_dim": D // 2}),
                test_fn=lambda x, l, new_dim=D // 2: sequence_reshape(
                    x, l, new_dim)[0],
                ref="fluid/layers/sequence_lod.py:1136")
    offs = np.array([0, 1, 0, 0], np.int32)
    slens = np.array([3, 2, 1, 0], np.int32)
    register_op("sequence_slice", sequence_slice, "sequence",
                np_ref=lambda x, l, o, n: _np_slice(x, l, o, n),
                sample_args=lambda: ((xf, lens, offs, slens), {}),
                test_fn=lambda x, l, o, n: sequence_slice(x, l, o, n)[0],
                ref="fluid/layers/sequence_lod.py:581")
    x2 = rs.randn(B, 5, D).astype(np.float32)
    lens2 = np.array([2, 5, 0, 3], np.int32)
    register_op("sequence_concat", sequence_concat, "sequence",
                np_ref=lambda x, l: _np_concat([x, x2], [l, lens2])[0],
                sample_args=lambda: ((xf, lens), {}),
                test_fn=lambda x, l: sequence_concat(
                    [x, x2], [l, lens2])[0],
                ref="fluid/layers/sequence_lod.py:396")
    register_op("sequence_erase", sequence_erase, "sequence",
                np_ref=lambda x, l: _np_erase(x, l, (2, 5))[0].astype(
                    np.int32),
                sample_args=lambda: ((xi, lens), {}),
                test_fn=lambda x, l: sequence_erase(x, l, (2, 5))[0],
                differentiable=False,
                ref="operators/sequence_ops/sequence_erase_op.cc")
    tgt = rs.randn(B, 10).astype(np.float32)
    ids = rs.randint(0, 10, (B, T)).astype(np.int32)
    upd = rs.randn(B, T).astype(np.float32)
    register_op("sequence_scatter", sequence_scatter, "sequence",
                np_ref=lambda x, i, u, l: _np_scatter(x, i, u, l),
                sample_args=lambda: ((tgt, ids, upd, lens), {}),
                ref="operators/sequence_ops/sequence_scatter_op.h:60")
    ragged = [rs.randn(5, 3).astype(np.float32),
              rs.randn(2, 3).astype(np.float32)]
    register_op("sequence_pad", sequence_pad, "sequence",
                np_ref=lambda: np.stack(
                    [np.pad(ragged[0], ((0, 0), (0, 0))),
                     np.pad(ragged[1], ((0, 3), (0, 0)))]),
                sample_args=lambda: ((), {}),
                test_fn=lambda: sequence_pad(ragged)[0],
                jit_ok=False, differentiable=False,
                ref="fluid/layers/sequence_lod.py:934")
    register_op("sequence_unpad", sequence_unpad, "sequence",
                np_ref=lambda x, l: np.concatenate(
                    [np.asarray(x)[b, :int(l[b])].reshape(-1)
                     for b in range(len(l))]),
                sample_args=lambda: ((xf, lens), {}),
                test_fn=lambda x, l: np.concatenate(
                    [np.asarray(p).reshape(-1)
                     for p in sequence_unpad(x, l)]),
                jit_ok=False, differentiable=False,
                ref="fluid/layers/sequence_lod.py:1055")


_register()
