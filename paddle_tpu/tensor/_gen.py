"""Table-driven generation of the elementwise/reduction op surface.

Reference analog: python/paddle/tensor/{math,logic}.py — ~200 of the 314
tensor functions are thin per-op dispatch wrappers there; here they are
generated from one table, each with a numpy oracle registered for the OpTest
harness (SURVEY.md §4).

Input domains drive sample generation for gradient/oracle tests:
  real      — N(0,1)
  positive  — |N(0,1)| + 0.5
  unit      — uniform(-0.9, 0.9)
  ge1       — |N(0,1)| + 1.5
  nonzero   — N(0,1) pushed away from 0
  int       — random int32 in [0, 10)
  bool      — random bool
"""

import functools

import numpy as np
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op

_RNG = np.random.RandomState(20240613)


def _sample(domain, shape=(4, 5)):
    if domain == "real":
        return _RNG.randn(*shape).astype(np.float32)
    if domain == "positive":
        return (np.abs(_RNG.randn(*shape)) + 0.5).astype(np.float32)
    if domain == "unit":
        return _RNG.uniform(-0.9, 0.9, shape).astype(np.float32)
    if domain == "unit01":
        return _RNG.uniform(0.05, 0.95, shape).astype(np.float32)
    if domain == "ge1":
        return (np.abs(_RNG.randn(*shape)) + 1.5).astype(np.float32)
    if domain == "nonzero":
        x = _RNG.randn(*shape).astype(np.float32)
        return x + np.sign(x) * 0.5
    if domain == "int":
        return _RNG.randint(0, 10, shape).astype(np.int32)
    if domain == "bool":
        return _RNG.rand(*shape) > 0.5
    raise ValueError(domain)


def make_unary(module_all, module_ns, table, category):
    for name, (jfn, nfn, domain, diff) in table.items():
        def fn(x, *, name=None, _jfn=jfn):
            return _jfn(jnp.asarray(x))
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__doc__ = (f"Elementwise ``{name}``. Ref: python/paddle/tensor/ "
                      f"op of the same name; TPU impl: XLA HLO.")
        register_op(name, fn, category, np_ref=nfn,
                    sample_args=functools.partial(_make_unary_sample, domain),
                    differentiable=diff)
        module_ns[name] = fn
        module_all.append(name)


def _make_unary_sample(domain):
    return (_sample(domain),), {}


def make_binary(module_all, module_ns, table, category):
    for name, (jfn, nfn, domain, diff) in table.items():
        def fn(x, y, *, name=None, _jfn=jfn):
            return _jfn(jnp.asarray(x), jnp.asarray(y))
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__doc__ = (f"Elementwise binary ``{name}`` with numpy-style "
                      f"broadcasting. Ref: python/paddle/tensor/.")
        register_op(name, fn, category, np_ref=nfn,
                    sample_args=functools.partial(_make_binary_sample, domain),
                    differentiable=diff)
        module_ns[name] = fn
        module_all.append(name)


def _make_binary_sample(domain):
    return (_sample(domain), _sample(domain)), {}
