"""Random sampling ops (ref: python/paddle/tensor/random.py).

Unlike the reference's stateful per-device Generator (phi/core/generator.h:23),
these draw keys from the named-stream tracker in paddle_tpu.random — explicit
JAX keys under the hood, so the same program is reproducible across chips and
meshes, and TP layers can opt into per-rank-distinct streams
(rng_state("model_parallel"))."""

import jax
import jax.numpy as jnp

from paddle_tpu import random as pt_random
from paddle_tpu.dtypes import get_default_dtype, to_dtype
from paddle_tpu.ops.registry import register_op

__all__ = []


def _reg(name, fn):
    register_op(name, fn, "random", differentiable=False)
    globals()[name] = fn
    __all__.append(name)
    return fn


def _key(key):
    return key if key is not None else pt_random.next_key()


def _dt(dtype):
    return get_default_dtype() if dtype is None else to_dtype(dtype)


def rand(shape, dtype=None, key=None):
    return jax.random.uniform(_key(key), shape, _dt(dtype))


def uniform(shape, dtype=None, min=-1.0, max=1.0, key=None):  # noqa: A002
    return jax.random.uniform(_key(key), shape, _dt(dtype), min, max)


def randn(shape, dtype=None, key=None):
    return jax.random.normal(_key(key), shape, _dt(dtype))


def normal(mean=0.0, std=1.0, shape=None, key=None):
    shape = shape if shape is not None else ()
    return mean + std * jax.random.normal(_key(key), shape, get_default_dtype())


def standard_normal(shape, dtype=None, key=None):
    return jax.random.normal(_key(key), shape, _dt(dtype))


def randint(low=0, high=None, shape=(1,), dtype="int64", key=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(_key(key), shape, low, high, to_dtype(dtype))


def randint_like(x, low=0, high=None, dtype=None, key=None):
    x = jnp.asarray(x)
    return randint(low, high, x.shape, dtype or x.dtype, key)


def randperm(n, dtype="int64", key=None):
    return jax.random.permutation(_key(key), n).astype(to_dtype(dtype))


def shuffle(x, axis=0, key=None):
    return jax.random.permutation(_key(key), jnp.asarray(x), axis=axis)


def multinomial(x, num_samples=1, replacement=False, key=None):
    x = jnp.asarray(x)
    logits = jnp.log(x / jnp.sum(x, axis=-1, keepdims=True))
    if replacement:
        return jax.random.categorical(_key(key), logits,
                                      shape=x.shape[:-1] + (num_samples,),
                                      axis=-1)
    k = _key(key)
    # Gumbel top-k trick for sampling without replacement
    g = jax.random.gumbel(k, x.shape)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return idx


def bernoulli(x, key=None):
    x = jnp.asarray(x)
    return jax.random.bernoulli(_key(key), x).astype(x.dtype)


def poisson(x, key=None):
    x = jnp.asarray(x)
    return jax.random.poisson(_key(key), x).astype(x.dtype)


def exponential_(x, lam=1.0, key=None):
    x = jnp.asarray(x)
    return (jax.random.exponential(_key(key), x.shape) / lam).astype(x.dtype)


def binomial(count, prob, key=None):
    return jax.random.binomial(_key(key), jnp.asarray(count),
                               jnp.asarray(prob))


for _n in ["rand", "uniform", "randn", "normal", "standard_normal", "randint",
           "randint_like", "randperm", "shuffle", "multinomial", "bernoulli",
           "poisson", "exponential_", "binomial"]:
    _reg(_n, globals()[_n])
