"""Comparison / logic ops (ref: python/paddle/tensor/logic.py)."""

import numpy as np
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op
from paddle_tpu.tensor._gen import _sample

__all__ = []


def _reg(name, fn, np_ref=None):
    register_op(name, fn, "logic", np_ref=np_ref,
                sample_args=(lambda: ((_sample("real"), _sample("real")), {}))
                if np_ref is not None else None,
                differentiable=False)
    globals()[name] = fn
    __all__.append(name)
    return fn


def equal(x, y):
    return jnp.equal(jnp.asarray(x), jnp.asarray(y))


def not_equal(x, y):
    return jnp.not_equal(jnp.asarray(x), jnp.asarray(y))


def greater_than(x, y):
    return jnp.greater(jnp.asarray(x), jnp.asarray(y))


def greater_equal(x, y):
    return jnp.greater_equal(jnp.asarray(x), jnp.asarray(y))


def less_than(x, y):
    return jnp.less(jnp.asarray(x), jnp.asarray(y))


def less_equal(x, y):
    return jnp.less_equal(jnp.asarray(x), jnp.asarray(y))


def equal_all(x, y):
    return jnp.array_equal(jnp.asarray(x), jnp.asarray(y))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(jnp.asarray(x), jnp.asarray(y), rtol=rtol, atol=atol,
                        equal_nan=equal_nan)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(jnp.asarray(x), jnp.asarray(y), rtol=rtol, atol=atol,
                       equal_nan=equal_nan)


def is_tensor(x):
    import jax
    return isinstance(x, jax.Array)


_reg("equal", equal, np.equal)
_reg("not_equal", not_equal, np.not_equal)
_reg("greater_than", greater_than, np.greater)
_reg("greater_equal", greater_equal, np.greater_equal)
_reg("less_than", less_than, np.less)
_reg("less_equal", less_equal, np.less_equal)
_reg("equal_all", equal_all)
_reg("allclose", allclose)
_reg("isclose", isclose, np.isclose)
_reg("is_tensor", is_tensor)
