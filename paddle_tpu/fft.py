"""FFT ops (ref: python/paddle/fft.py → phi fft kernels over cuFFT; here
jnp.fft over XLA's FFT HLO). The full reference surface — c2c/r2c/c2r in
1d/2d/nd, hermitian variants, helpers — with numpy.fft as the free oracle,
registered in the op registry like every other op."""

import numpy as np
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op

_j = jnp.fft

fft = _j.fft
ifft = _j.ifft
fft2 = _j.fft2
ifft2 = _j.ifft2
fftn = _j.fftn
ifftn = _j.ifftn
rfft = _j.rfft
irfft = _j.irfft
rfft2 = _j.rfft2
irfft2 = _j.irfft2
rfftn = _j.rfftn
irfftn = _j.irfftn
hfft = _j.hfft
ihfft = _j.ihfft
fftfreq = _j.fftfreq
rfftfreq = _j.rfftfreq
fftshift = _j.fftshift
ifftshift = _j.ifftshift


def hfft2(x, s=None, axes=(-2, -1), norm="backward"):
    """ref: python/paddle/fft.py hfft2 — hermitian-input FFT over two axes:
    c2c FFT on the leading axis, hermitian c2r on the last."""
    x = jnp.asarray(x)
    inner = _j.fft(x, n=None if s is None else s[0], axis=axes[0], norm=norm)
    return _j.hfft(inner, n=None if s is None else s[1], axis=axes[1],
                   norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward"):
    """ref: python/paddle/fft.py ihfft2 — inverse of hfft2 (r2c hermitian on
    the last axis, c2c inverse on the leading)."""
    x = jnp.asarray(x)
    inner = _j.ihfft(x, n=None if s is None else s[1], axis=axes[1],
                     norm=norm)
    return _j.ifft(inner, n=None if s is None else s[0], axis=axes[0],
                   norm=norm)


def hfftn(x, s=None, axes=None, norm="backward"):
    """ref: python/paddle/fft.py hfftn — c2c FFT over all but the last given
    axis, hermitian c2r over the last."""
    x = jnp.asarray(x)
    if axes is None:
        axes = (tuple(range(x.ndim)) if s is None
                else tuple(range(-len(s), 0)))
    lead, last = tuple(axes[:-1]), axes[-1]
    if lead:
        x = _j.fftn(x, s=None if s is None else s[:-1], axes=lead, norm=norm)
    return _j.hfft(x, n=None if s is None else s[-1], axis=last, norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward"):
    """ref: python/paddle/fft.py ihfftn — inverse of hfftn."""
    x = jnp.asarray(x)
    if axes is None:
        axes = (tuple(range(x.ndim)) if s is None
                else tuple(range(-len(s), 0)))
    lead, last = tuple(axes[:-1]), axes[-1]
    out = _j.ihfft(x, n=None if s is None else s[-1], axis=last, norm=norm)
    if lead:
        out = _j.ifftn(out, s=None if s is None else s[:-1], axes=lead,
                       norm=norm)
    return out


__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft", "irfft",
           "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft",
           "hfft2", "ihfft2", "hfftn", "ihfftn", "fftfreq",
           "rfftfreq", "fftshift", "ifftshift"]


# -- registry + oracles ------------------------------------------------------
# numpy.fft is the oracle for every op (the reference checks its phi fft
# kernels against scipy/numpy the same way, test_fft.py). Complex-output ops
# are non-differentiable under the harness (which needs a real scalar loss);
# the shift helpers are real→real and keep grad coverage.

_R = np.random.RandomState(20260730)
_xr = _R.randn(4, 6).astype(np.float32)
_xc = (_R.randn(4, 6) + 1j * _R.randn(4, 6)).astype(np.complex64)
_xh = (_R.randn(4, 4) + 1j * _R.randn(4, 4)).astype(np.complex64)


def _reg(name, fn, np_ref, sample, differentiable=False, jit_ok=True):
    register_op(name, fn, "fft", np_ref=np_ref,
                sample_args=lambda s=sample: s,
                ref="python/paddle/fft.py", differentiable=differentiable,
                jit_ok=jit_ok)


_reg("fft", fft, np.fft.fft, ((_xr,), {}))
_reg("ifft", ifft, np.fft.ifft, ((_xc,), {}))
_reg("fft2", fft2, np.fft.fft2, ((_xr,), {}))
_reg("ifft2", ifft2, np.fft.ifft2, ((_xc,), {}))
_reg("fftn", fftn, np.fft.fftn, ((_xr,), {}))
_reg("ifftn", ifftn, np.fft.ifftn, ((_xc,), {}))
_reg("rfft", rfft, np.fft.rfft, ((_xr,), {}))
_reg("irfft", irfft, np.fft.irfft, ((_xh,), {}))
_reg("rfft2", rfft2, np.fft.rfft2, ((_xr,), {}))
_reg("irfft2", irfft2, np.fft.irfft2, ((_xh,), {}))
_reg("rfftn", rfftn, np.fft.rfftn, ((_xr,), {}))
_reg("irfftn", irfftn, np.fft.irfftn, ((_xh,), {}))
_reg("hfft", hfft, np.fft.hfft, ((_xh,), {}))
_reg("ihfft", ihfft, np.fft.ihfft, ((_xr,), {}))
_reg("hfft2", hfft2,
     lambda x: np.fft.hfft(np.fft.fft(x, axis=-2), axis=-1), ((_xh,), {}))
_reg("ihfft2", ihfft2,
     lambda x: np.fft.ifft(np.fft.ihfft(x, axis=-1), axis=-2), ((_xr,), {}))
_reg("hfftn", hfftn,
     lambda x: np.fft.hfft(np.fft.fft(x, axis=0), axis=-1), ((_xh,), {}))
_reg("ihfftn", ihfftn,
     lambda x: np.fft.ifft(np.fft.ihfft(x, axis=-1), axis=0), ((_xr,), {}))
# size argument is a static shape, not a tensor — cannot trace under jit
_reg("fftfreq", fftfreq, lambda n: np.fft.fftfreq(8, 0.5), ((8,), {"d": 0.5}),
     jit_ok=False)
_reg("rfftfreq", rfftfreq, lambda n: np.fft.rfftfreq(8, 0.5),
     ((8,), {"d": 0.5}), jit_ok=False)
_reg("fftshift", fftshift, np.fft.fftshift, ((_xr,), {}),
     differentiable=True)
_reg("ifftshift", ifftshift, np.fft.ifftshift, ((_xr,), {}),
     differentiable=True)
