"""FFT ops (ref: python/paddle/fft.py → phi fft kernels over cuFFT; here
jnp.fft over XLA's FFT HLO)."""

import jax.numpy as jnp

_j = jnp.fft

fft = _j.fft
ifft = _j.ifft
fft2 = _j.fft2
ifft2 = _j.ifft2
fftn = _j.fftn
ifftn = _j.ifftn
rfft = _j.rfft
irfft = _j.irfft
rfft2 = _j.rfft2
irfft2 = _j.irfft2
rfftn = _j.rfftn
irfftn = _j.irfftn
hfft = _j.hfft
ihfft = _j.ihfft
fftfreq = _j.fftfreq
rfftfreq = _j.rfftfreq
fftshift = _j.fftshift
ifftshift = _j.ifftshift

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft", "irfft",
           "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft", "fftfreq",
           "rfftfreq", "fftshift", "ifftshift"]
