"""Text datasets (ref: python/paddle/text/datasets/ — Imdb, Conll05,
UCIHousing, Movielens...). Downloads are environment-gated (zero-egress
images); every dataset degrades to a deterministic synthetic split with a
learnable signal so tests and tutorials stay hermetic, mirroring
vision.datasets.SyntheticImages."""

import hashlib
import os
import tarfile

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["Imdb", "UCIHousing", "Conll05st"]


class Imdb(Dataset):
    """IMDB movie-review sentiment (ref text/datasets/imdb.py): tokenized
    review → binary label. Synthetic mode plants class-dependent token
    frequencies so a bag-of-words model can learn."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 seq_len=256, vocab_size=5000, num_samples=2000, seed=0):
        """``cutoff`` (the reference's frequency threshold) is accepted
        for API parity but has no effect here: words map to ids by STABLE
        feature hashing, so train/test instances agree on every word's id
        without sharing a frequency-built vocabulary."""
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        if data_file and os.path.exists(data_file):
            self._load_real(data_file, mode, cutoff)
            return
        rs = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self.labels = rs.randint(0, 2, num_samples).astype(np.int64)
        base = rs.randint(0, vocab_size, (num_samples, seq_len))
        # positive reviews over-sample the first vocab decile
        pos_tokens = rs.randint(0, vocab_size // 10, (num_samples, seq_len))
        use_pos = (rs.rand(num_samples, seq_len) < 0.3) \
            & (self.labels[:, None] == 1)
        self.docs = np.where(use_pos, pos_tokens, base).astype(np.int64)

    def _word_id(self, w):
        # stable feature hashing: id 0 is reserved for padding; the same
        # word gets the same id in every split/process (md5, not hash())
        h = int.from_bytes(hashlib.md5(w.encode()).digest()[:4], "little")
        return 1 + h % (self.vocab_size - 1)

    def _load_real(self, data_file, mode, cutoff):
        docs, labels = [], []
        pat = f"aclImdb/{mode}/"
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if not m.name.startswith(pat) or not m.name.endswith(".txt"):
                    continue
                if "/pos/" in m.name:
                    y = 1
                elif "/neg/" in m.name:
                    y = 0
                else:
                    continue
                words = tf.extractfile(m).read().decode(
                    "utf-8", "ignore").lower().split()
                ids = [self._word_id(w) for w in words[:self.seq_len]]
                ids += [0] * (self.seq_len - len(ids))
                docs.append(ids)
                labels.append(y)
        self.docs = np.asarray(docs, np.int64)
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    """Boston housing regression (ref text/datasets/uci_housing.py):
    13 features → price. Synthetic mode draws from a fixed linear model
    plus noise (learnable by linear regression)."""

    N_FEATURES = 13

    def __init__(self, data_file=None, mode="train", num_samples=404,
                 seed=0):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
            feats, target = raw[:, :-1], raw[:, -1:]
        else:
            rs = np.random.RandomState(seed)
            w = rs.randn(self.N_FEATURES, 1).astype(np.float32)
            feats = rs.randn(num_samples + 102,
                             self.N_FEATURES).astype(np.float32)
            target = feats @ w + 0.1 * rs.randn(len(feats), 1).astype(
                np.float32)
        split = int(0.8 * len(feats))
        sl = slice(0, split) if mode == "train" else slice(split, None)
        self.feats, self.target = feats[sl], target[sl]

    def __getitem__(self, idx):
        return self.feats[idx], self.target[idx]

    def __len__(self):
        return len(self.feats)


class Conll05st(Dataset):
    """CoNLL-2005 semantic-role labeling (ref text/datasets/conll05.py):
    (word_ids, predicate_ids, ..., label_ids) sequences. Synthetic mode
    emits self-consistent tag sequences (label = f(word) near the
    predicate) so a tagger can fit them."""

    def __init__(self, data_file=None, mode="train", seq_len=64,
                 word_vocab=5000, label_vocab=67, num_samples=1000, seed=0):
        if data_file is not None:
            raise NotImplementedError(
                "real CoNLL-2005 parsing is not implemented; omit "
                "data_file for the synthetic split")
        rs = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self.words = rs.randint(1, word_vocab,
                                (num_samples, seq_len)).astype(np.int64)
        pred_pos = rs.randint(0, seq_len, num_samples)
        self.predicates = np.zeros((num_samples, seq_len), np.int64)
        self.predicates[np.arange(num_samples), pred_pos] = 1
        near = np.abs(np.arange(seq_len)[None, :]
                      - pred_pos[:, None]) <= 3
        self.labels = np.where(near, self.words % (label_vocab - 1) + 1,
                               0).astype(np.int64)

    def __getitem__(self, idx):
        return self.words[idx], self.predicates[idx], self.labels[idx]

    def __len__(self):
        return len(self.words)
