"""BERT-family tokenizer: the FasterTokenizer analog (ref:
paddle/fluid/operators/string/faster_tokenizer_op.{h,cc} — an in-graph C++
wordpiece tokenizer so serving takes raw strings end-to-end).

TPU-form: tokenization is byte/codepoint work with data-dependent output
shapes — exactly what does NOT belong inside an XLA program — so it runs on
the host as part of the input/serving pipeline (same split the reference
makes between CPU-only tokenizer op and device model), and its OUTPUT is the
dense padded (ids, token_type_ids, lengths) batch the jitted model consumes.
``paddle_tpu.inference.Predictor`` / the decode engine take these directly.

Semantics follow the reference op: BasicTokenizer (unicode clean, optional
lower+accent-strip, CJK spacing, punctuation split) then greedy
longest-match WordPiece against a vocab, with [CLS]/[SEP] assembly and
truncation (faster_tokenizer_op.h:BertTokenizer::Encode).
"""

import unicodedata

import numpy as np

__all__ = ["load_vocab", "BasicTokenizer", "WordpieceTokenizer",
           "BertTokenizer"]


def load_vocab(path):
    """vocab.txt (one token per line, id = line number) → dict."""
    vocab = {}
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            tok = line.rstrip("\n")
            if tok:
                vocab[tok] = i
    return vocab


def _is_punct(ch):
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp):
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


class BasicTokenizer:
    """ref: faster_tokenizer_op.h BasicTokenizer — clean, lower/strip
    accents, space out CJK, split on whitespace and punctuation."""

    def __init__(self, do_lower_case=True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text):
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or (
                    unicodedata.category(ch) in ("Cc", "Cf")
                    and ch not in "\t\n\r"):
                continue
            if _is_cjk(cp):
                out.append(f" {ch} ")
            elif ch in "\t\n\r" or unicodedata.category(ch) == "Zs":
                out.append(" ")
            else:
                out.append(ch)
        text = "".join(out)
        if self.do_lower_case:
            text = text.lower()
            text = unicodedata.normalize("NFD", text)
            text = "".join(c for c in text
                           if unicodedata.category(c) != "Mn")
        tokens = []
        for word in text.split():
            cur = []
            for ch in word:
                if _is_punct(ch):
                    if cur:
                        tokens.append("".join(cur))
                        cur = []
                    tokens.append(ch)
                else:
                    cur.append(ch)
            if cur:
                tokens.append("".join(cur))
        return tokens


class WordpieceTokenizer:
    """ref: faster_tokenizer_op.h WordPieceTokenizer — greedy longest-match
    from each position; continuation pieces prefixed '##'; whole word →
    [UNK] if any position fails."""

    def __init__(self, vocab, unk_token="[UNK]", max_chars_per_word=100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars_per_word = max_chars_per_word

    def tokenize(self, word):
        if len(word) > self.max_chars_per_word:
            return [self.unk_token]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [self.unk_token]
            pieces.append(piece)
            start = end
        return pieces


class BertTokenizer:
    """End-to-end encoder: raw text (pairs) → padded id batches.

    ref: faster_tokenizer_op.h BertTokenizer::Encode/BatchEncode — the op's
    outputs are exactly these two dense int64 tensors (InputIds,
    SegmentIds); here lengths ride along instead of relying on pad id 0."""

    def __init__(self, vocab, do_lower_case=True, unk_token="[UNK]",
                 cls_token="[CLS]", sep_token="[SEP]", pad_token="[PAD]"):
        if isinstance(vocab, str):
            vocab = load_vocab(vocab)
        self.vocab = vocab
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(vocab, unk_token)
        self.cls_id = vocab[cls_token]
        self.sep_id = vocab[sep_token]
        self.pad_id = vocab.get(pad_token, 0)
        self.inv_vocab = {i: t for t, i in vocab.items()}

    def tokenize(self, text):
        return [p for w in self.basic.tokenize(text)
                for p in self.wordpiece.tokenize(w)]

    def convert_tokens_to_ids(self, tokens):
        return [self.vocab[t] for t in tokens]

    def __call__(self, texts, text_pairs=None, max_seq_len=128):
        """→ dict of np arrays: input_ids, token_type_ids (B, max_seq_len)
        int32 + seq_len (B,) — the jitted model's feed, no further host
        work."""
        if isinstance(texts, str):
            texts = [texts]
        if text_pairs is not None and isinstance(text_pairs, str):
            text_pairs = [text_pairs]
        B = len(texts)
        ids = np.full((B, max_seq_len), self.pad_id, np.int32)
        seg = np.zeros((B, max_seq_len), np.int32)
        lens = np.zeros((B,), np.int32)
        for b in range(B):
            a = self.convert_tokens_to_ids(self.tokenize(texts[b]))
            p = (self.convert_tokens_to_ids(self.tokenize(text_pairs[b]))
                 if text_pairs is not None else [])
            # truncate longest-first to fit specials (ref Encode truncation)
            has_pair = text_pairs is not None
            budget = max_seq_len - (3 if has_pair else 2)
            while len(a) + len(p) > budget:
                (a if len(a) > len(p) else p).pop()
            row = [self.cls_id] + a + [self.sep_id]
            types = [0] * len(row)
            if has_pair:
                row += p + [self.sep_id]
                types += [1] * (len(p) + 1)
            ids[b, :len(row)] = row
            seg[b, :len(types)] = types
            lens[b] = len(row)
        return {"input_ids": ids, "token_type_ids": seg, "seq_len": lens}
