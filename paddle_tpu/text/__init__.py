"""Text domain (ref: python/paddle/text/ — dataset loaders). Provides
viterbi_decode (ref: paddle.text.viterbi_decode phi kernel) and synthetic
datasets for hermetic tests."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.io.dataset import Dataset

from paddle_tpu.text.tokenizer import (  # noqa: F401
    BasicTokenizer, BertTokenizer, WordpieceTokenizer, load_vocab)

__all__ = ["viterbi_decode", "SyntheticTextDataset", "Imdb", "UCIHousing",
           "Conll05st", "BasicTokenizer", "BertTokenizer",
           "WordpieceTokenizer", "load_vocab"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """CRF Viterbi decode via lax.scan (ref: phi viterbi_decode kernel)."""
    pots = jnp.asarray(potentials)  # (B, T, N)
    trans = jnp.asarray(transition_params)  # (N, N)
    B, T, N = pots.shape

    def step(carry, emit_t):
        score = carry  # (B, N)
        # score[b, i] + trans[i, j] + emit[b, j]
        cand = score[:, :, None] + trans[None, :, :]
        best = jnp.max(cand, axis=1)
        idx = jnp.argmax(cand, axis=1)
        return best + emit_t, idx

    init = pots[:, 0]
    emits = jnp.moveaxis(pots[:, 1:], 1, 0)  # (T-1, B, N)
    final, backptrs = jax.lax.scan(step, init, emits)
    scores = jnp.max(final, axis=-1)
    last = jnp.argmax(final, axis=-1)

    def backtrack(carry, ptr_t):
        tag = carry
        prev = jnp.take_along_axis(ptr_t, tag[:, None], axis=1)[:, 0]
        return prev, prev

    _, path_rev = jax.lax.scan(backtrack, last, jnp.flip(backptrs, axis=0))
    path = jnp.concatenate(
        [jnp.flip(path_rev, axis=0), last[None]], axis=0)
    return scores, jnp.moveaxis(path, 0, 1)


class SyntheticTextDataset(Dataset):
    """Deterministic token-sequence dataset for LM tests/benches."""

    def __init__(self, num_samples=1024, seq_len=128, vocab_size=1000,
                 seed=0):
        rng = np.random.RandomState(seed)
        # markov-ish structure so models can learn
        self.tokens = rng.randint(0, vocab_size,
                                  (num_samples, seq_len + 1)).astype(np.int64)
        self.tokens[:, 1::2] = (self.tokens[:, 0::2][:, :self.tokens[:, 1::2].shape[1]]
                                + 1) % vocab_size

    def __getitem__(self, idx):
        return self.tokens[idx, :-1], self.tokens[idx, 1:]

    def __len__(self):
        return len(self.tokens)


from paddle_tpu.text.datasets import Imdb, UCIHousing, Conll05st  # noqa: E402
