"""paddle.hub parity (ref: python/paddle/hapi/hub.py). Zero-egress build:
local-directory sources only."""

import importlib.util
import os

__all__ = ["list", "help", "load"]


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    mod = _load_hubconf(repo_dir)
    return [k for k in dir(mod) if callable(getattr(mod, k))
            and not k.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    return getattr(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    return getattr(_load_hubconf(repo_dir), model)(**kwargs)
