"""paddle.utils (ref: python/paddle/utils/__init__.py — deprecated
decorator, try_import, unique_name, run_check, download; cpp_extension).

cpp_extension maps to the in-tree native build (paddle_tpu/native builds
libptnative.so with g++ directly — no setuptools dance needed for the
framework's own runtime); download is gated for the zero-egress
environment."""

import functools
import importlib
import warnings

__all__ = ["deprecated", "try_import", "unique_name", "run_check",
           "download", "require_version"]


def deprecated(update_to="", since="", reason="", level=1):
    """(≙ utils/deprecated.py) warn once per call site."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return decorator


def try_import(module_name, err_msg=None):
    """(≙ utils/lazy_import.py try_import)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required: {e}") from e


class _UniqueNameGenerator:
    """(≙ utils/unique_name.py): generate('fc') -> fc_0, fc_1, ..."""

    def __init__(self):
        self.ids = {}
        self._prefix = ""

    def generate(self, key="tmp"):
        i = self.ids.get(key, 0)
        self.ids[key] = i + 1
        return f"{self._prefix}{key}_{i}"

    def guard(self, new_prefix=""):
        import contextlib

        @contextlib.contextmanager
        def _guard():
            old_prefix, old_ids = self._prefix, self.ids
            self._prefix, self.ids = new_prefix, {}
            try:
                yield
            finally:
                self._prefix, self.ids = old_prefix, old_ids
        return _guard()

    def switch(self):
        self.ids = {}


unique_name = _UniqueNameGenerator()


def run_check():
    """(≙ utils/install_check.py run_check): one matmul per local device
    through pjit; prints the verdict."""
    import jax
    import jax.numpy as jnp
    n = len(jax.devices())
    x = jnp.ones((8 * max(n, 1), 8))
    out = jax.jit(lambda a: a @ a.T)(x)
    assert float(out[0, 0]) == 8.0
    print(f"paddle_tpu is installed successfully! "
          f"{n} {jax.default_backend()} device(s) available.")
    return True


def download(url, path=None, md5sum=None):
    """(≙ utils/download.py get_path_from_url). This environment has no
    egress; only file:// URLs and existing local paths resolve."""
    import os
    import shutil
    if url.startswith("file://"):
        src = url[len("file://"):]
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            shutil.copy(src, path)
            return path
        return src
    if os.path.exists(url):
        return url
    raise RuntimeError(
        f"download({url!r}): network egress is unavailable; place the "
        "file locally and pass its path")


def require_version(min_version, max_version=None):
    """(≙ utils/__init__.py require_version) against paddle_tpu.version."""
    from paddle_tpu.version import full_version

    def as_tuple(v):
        return tuple(int(p) for p in str(v).split(".")[:3])
    cur = as_tuple(full_version)
    if as_tuple(min_version) > cur:
        raise RuntimeError(
            f"requires version >= {min_version}, got {full_version}")
    if max_version is not None and as_tuple(max_version) < cur:
        raise RuntimeError(
            f"requires version <= {max_version}, got {full_version}")
    return True
