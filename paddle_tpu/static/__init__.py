"""Static-graph surface (ref: python/paddle/static/ — Program/Executor/
CompiledProgram, fluid/framework.py:5220, executor.py:912).

The reference maintains a protobuf IR + interpreter (InterpreterCore). Here
"static mode" IS the jit path: an InputSpec-described function traced once
and compiled by XLA to a single TPU executable — realizing the reference's
infrt/CINN ambition (SURVEY §7.1b item 4). This module provides a
Program-style API SHELL over jax.jit + AOT lowering (feed/fetch by name,
InputSpec AOT, save/load_inference_model via jax.export StableHLO).

Scope note (honesty over parity): there is no mutable Program IR here —
code that CONSTRUCTS reference Programs op-by-op (append_op, block
rewriting, paddle.static.nn.* layer building) does not port onto this
shell; write the model as a traced function instead. What ports is the
run surface: exe.run(feed=..., fetch_list=...) over a compiled function.
"""

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["InputSpec", "CompiledFunction", "compile_fn", "Executor",
           "save_inference_model", "load_inference_model", "default_main_program"]


@dataclass(frozen=True)
class InputSpec:
    """ref: paddle.static.InputSpec."""

    shape: tuple
    dtype: Any = jnp.float32
    name: Optional[str] = None

    def to_shape_struct(self, batch=1):
        shape = tuple(batch if (s is None or s == -1) else s
                      for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)


class CompiledFunction:
    """AOT-compiled function (≙ CompiledProgram + InterpreterCore: build
    once, run many; XLA owns scheduling/GC that the interpreter did)."""

    def __init__(self, fn, input_specs: Sequence[InputSpec], batch=1):
        self.fn = fn
        self.input_specs = list(input_specs)
        structs = [s.to_shape_struct(batch) for s in self.input_specs]
        self.lowered = jax.jit(fn).lower(*structs)
        self.executable = self.lowered.compile()

    def __call__(self, *args):
        return self.executable(*[jnp.asarray(a) for a in args])

    def stablehlo(self):
        return self.lowered.as_text()

    def cost_analysis(self):
        return self.executable.cost_analysis()


def compile_fn(fn, input_specs, batch=1):
    return CompiledFunction(fn, input_specs, batch)


class Executor:
    """API-parity Executor (ref: fluid/executor.py:912). ``run`` executes a
    compiled function with a feed dict."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None):
        if not callable(program):
            raise TypeError(
                "paddle_tpu Executor runs compiled functions; build one with "
                "paddle_tpu.static.compile_fn(fn, input_specs)")
        feed = feed or {}
        args = list(feed.values())
        out = program(*args)
        if isinstance(out, (list, tuple)):
            return [np.asarray(o) for o in out]
        return [np.asarray(out)]


def default_main_program():
    raise RuntimeError(
        "paddle_tpu has no mutable global Program; trace a function with "
        "paddle_tpu.jit.to_static / static.compile_fn instead "
        "(ref Program IR: paddle/fluid/framework/framework.proto — replaced "
        "by XLA HLO from tracing).")


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Export to StableHLO (≙ save_inference_model, python/paddle/static/
    io.py:459; realizes the infrt MLIR ambition via jax.export)."""
    from paddle_tpu.jit import save as jit_save
    if program is None or not callable(program):
        raise TypeError("pass the traced function as `program=`")
    return jit_save(program, path_prefix, input_spec=feed_vars)


def load_inference_model(path_prefix, executor=None, **kwargs):
    from paddle_tpu.jit import load as jit_load
    return jit_load(path_prefix)
