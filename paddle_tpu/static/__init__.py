"""Static-graph surface (ref: python/paddle/static/ — Program/Executor/
CompiledProgram, fluid/framework.py:5220, executor.py:912).

The reference maintains a protobuf IR + interpreter (InterpreterCore). Here
"static mode" IS the jit path: an InputSpec-described function traced once
and compiled by XLA to a single TPU executable — realizing the reference's
infrt/CINN ambition (SURVEY §7.1b item 4). This module provides a
Program-style API SHELL over jax.jit + AOT lowering (feed/fetch by name,
InputSpec AOT, save/load_inference_model via jax.export StableHLO).

Two surfaces:
- **compiled-function path**: InputSpec-described functions AOT-lowered to
  one executable (CompiledFunction), the jit face of static mode;
- **lazy-graph Program path** (static/program.py): op-by-op construction —
  ``static.data`` + ``static.nn.fc`` + Variable arithmetic +
  ``append_backward`` + ``minimize`` — executed by ``Executor.run`` with
  reference feed/fetch/scope semantics. Programs that REWRITE blocks (the
  reference's pass infrastructure) have no analog; XLA owns rewriting.
"""

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["InputSpec", "CompiledFunction", "compile_fn", "Executor",
           "save_inference_model", "load_inference_model",
           "default_main_program", "default_startup_program", "Program",
           "Variable", "program_guard", "data", "call", "minimize",
           "append_backward", "nn"]


@dataclass(frozen=True)
class InputSpec:
    """ref: paddle.static.InputSpec."""

    shape: tuple
    dtype: Any = jnp.float32
    name: Optional[str] = None

    def to_shape_struct(self, batch=1):
        shape = tuple(batch if (s is None or s == -1) else s
                      for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)


class CompiledFunction:
    """AOT-compiled function (≙ CompiledProgram + InterpreterCore: build
    once, run many; XLA owns scheduling/GC that the interpreter did)."""

    def __init__(self, fn, input_specs: Sequence[InputSpec], batch=1):
        self.fn = fn
        self.input_specs = list(input_specs)
        structs = [s.to_shape_struct(batch) for s in self.input_specs]
        self.lowered = jax.jit(fn).lower(*structs)
        self.executable = self.lowered.compile()

    def __call__(self, *args):
        return self.executable(*[jnp.asarray(a) for a in args])

    def stablehlo(self):
        return self.lowered.as_text()

    def cost_analysis(self):
        return self.executable.cost_analysis()


def compile_fn(fn, input_specs, batch=1):
    return CompiledFunction(fn, input_specs, batch)


class Executor:
    """≙ fluid Executor (executor.py:912 → InterpreterCore). Runs either a
    lazy-graph :class:`Program` (op-by-op construction, see
    static/program.py) or a compiled function. For Programs, one jitted
    XLA step per (program version, feed signature) covers forward +
    grads + optimizer update — the InterpreterCore replaced by the
    compiler."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None):
        from paddle_tpu.static.program import Program, Variable
        feed = feed or {}
        if program is None:
            program = default_main_program()
        if isinstance(program, Program):
            return self._run_program(program, feed, fetch_list or [])
        if callable(program):
            out = program(*list(feed.values()))
            if isinstance(out, (list, tuple)):
                return [np.asarray(o) for o in out]
            return [np.asarray(out)]
        raise TypeError("program must be a static.Program or a compiled "
                        "function")

    def _run_program(self, program, feed, fetch_list):
        from paddle_tpu.static.program import Variable
        if not program.vars and not fetch_list:
            return []  # empty/startup program
        # resolve fetch-by-name (reference Executor accepts names)
        resolved = []
        for f in list(fetch_list):
            if isinstance(f, str) and not f.endswith("@GRAD"):
                if f not in program.vars:
                    raise KeyError(f"fetch name {f!r} not in program")
                f = program.vars[f]
            resolved.append(f)
        fetch_list = resolved
        # @GRAD fetches (append_backward) resolve to param gradients
        grad_fetches = [f for f in fetch_list
                        if isinstance(f, str) and f.endswith("@GRAD")]
        var_fetches = [f for f in fetch_list if isinstance(f, Variable)]
        feed_vals = {k: jnp.asarray(v) for k, v in feed.items()}
        # run-mode + per-run dropout seed as ordinary (traced) inputs —
        # the reference bakes is_test into cloned programs; here the
        # clone only flips the flag the executor feeds
        feed_vals["__training__"] = jnp.asarray(not program._is_test)
        feed_vals["__rng__"] = jnp.asarray(
            np.random.randint(0, 2 ** 31 - 1), jnp.uint32)
        # only buffer updates whose data inputs are fed this run (partial
        # feed/fetch must not trace unrelated branches)
        buf_updates = [n for n in sorted(program._buffer_updates)
                       if program.data_deps(program._buffer_updates[n])
                       <= set(feed)]
        key = (id(program), program._version,
               tuple(sorted((k, v.shape, str(v.dtype))
                            for k, v in feed_vals.items())),
               tuple(v.name for v in var_fetches),
               tuple(grad_fetches), tuple(buf_updates))
        step = self._cache.get(key)
        opt = program._opt
        feed_names = [k for k in feed] + ["__training__", "__rng__"]
        if step is None:
            fwd = program.build_fn(var_fetches, feed_names)
            upd_fn = program.build_fn(
                [program._buffer_updates[n] for n in buf_updates],
                feed_names) if buf_updates else None
            loss_var = None
            if opt is not None:
                loss_var = opt[1]
            elif grad_fetches:
                loss_var = program._loss_for_grads
            loss_fn = (program.build_fn([loss_var], feed_names)
                       if loss_var is not None else None)

            def step(feed_vals, params, buffers, opt_state):
                fetched = fwd(feed_vals, params, buffers)
                grads = None
                if loss_fn is not None:
                    grads = jax.grad(
                        lambda p: loss_fn(feed_vals, p, buffers)[0])(params)
                new_params, new_state = params, opt_state
                if opt is not None:
                    new_params, new_state = opt[0].update(
                        grads, opt_state, params)
                new_buffers = buffers
                if upd_fn is not None:
                    # where(training, ...)-guarded: identity on test runs
                    vals = upd_fn(feed_vals, params, buffers)
                    new_buffers = dict(buffers)
                    for n, v in zip(buf_updates, vals):
                        new_buffers[n] = v
                gvals = []
                for gf in grad_fetches:
                    gvals.append(grads[program._grad_names[gf]])
                return fetched, gvals, new_params, new_buffers, new_state

            step = jax.jit(step)
            self._cache[key] = step
        if opt is not None and program._opt_state is None:
            program._opt_state = opt[0].init(program.params)
        fetched, gvals, new_params, new_buffers, new_state = step(
            feed_vals, program.params, program.buffers,
            program._opt_state)
        if opt is not None:
            # in-place: the scope dict is SHARED with clone(for_test=True)
            # programs, which must observe the trained parameters
            program.params.clear()
            program.params.update(new_params)
            program._opt_state = new_state
        if buf_updates:
            program.buffers.clear()           # shared dict: clones see the
            program.buffers.update(new_buffers)  # updated running stats
        out = []
        gi = vi = 0
        for f in fetch_list:
            if isinstance(f, str) and f.endswith("@GRAD"):
                out.append(np.asarray(gvals[gi]))
                gi += 1
            else:
                out.append(np.asarray(fetched[vi]))
                vi += 1
        return out


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Export to StableHLO (≙ save_inference_model, python/paddle/static/
    io.py:459; realizes the infrt MLIR ambition via jax.export)."""
    from paddle_tpu.jit import save as jit_save
    if program is None or not callable(program):
        raise TypeError("pass the traced function as `program=`")
    return jit_save(program, path_prefix, input_spec=feed_vars)


def load_inference_model(path_prefix, executor=None, **kwargs):
    from paddle_tpu.jit import load as jit_load
    return jit_load(path_prefix)


from paddle_tpu.static.program import (  # noqa: E402
    Program, Variable, program_guard, default_main_program,
    default_startup_program, data, call, minimize, append_backward, nn)
