"""Lazy-graph Program/Executor — op-by-op static program construction.

Reference analog: Program/Block/Operator/Variable (fluid/framework.py:5220,
:3552, :2712, :1353), ``append_backward`` (fluid/backward.py:1726) and
``Executor.run`` (fluid/executor.py:1378 → InterpreterCore). The reference
keeps a protobuf op list and interprets it per step; here the Program is a
lazy expression graph over named Variables, and ``Executor.run`` compiles
the whole requested computation (forward + grads + optimizer update) into
ONE jitted XLA program per feed signature — the InterpreterCore's job done
by the compiler, with reference run semantics (feed dict in, fetched
numpy out, parameters mutated in the program's scope).

Supported porting surface: ``static.data``, ``static.nn.fc``, Variable
arithmetic, any registered tensor op through ``static.call`` /
``Variable.apply``, ``append_backward``, ``optimizer minimize`` via
``static.minimize``, ``Executor.run(feed, fetch_list)``,
``program_guard``/``default_main_program``. Programs that REWRITE blocks
(pass infrastructure) have no analog here — XLA owns program rewriting.
"""

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["Program", "Variable", "program_guard", "default_main_program",
           "default_startup_program", "data", "call", "minimize",
           "append_backward", "nn"]


class Variable:
    """Lazy graph node (≙ fluid Variable:1353 + the Operator producing it)."""

    def __init__(self, program: "Program", name: str, shape=None,
                 dtype=None, kind: str = "op",
                 op: Optional[Callable] = None,
                 inputs: Sequence["Variable"] = ()):
        self.program = program
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.kind = kind                # "data" | "param" | "op"
        self.op = op
        self.inputs = list(inputs)

    # -- graph building -------------------------------------------------------
    def apply(self, fn: Callable, *others, **kwargs):
        """New node computing ``fn(self, *others, **kwargs)`` — the
        ``append_op`` analog for any pure tensor function."""
        return call(fn, self, *others, **kwargs)

    def _binop(self, other, fn, rev=False):
        if isinstance(other, Variable):
            a, b = (other, self) if rev else (self, other)
            return call(fn, a, b)
        const = other
        if rev:
            return call(lambda x: fn(jnp.asarray(const), x), self)
        return call(lambda x: fn(x, jnp.asarray(const)), self)

    def __add__(self, o):
        return self._binop(o, jnp.add)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, jnp.subtract)

    def __rsub__(self, o):
        return self._binop(o, jnp.subtract, rev=True)

    def __mul__(self, o):
        return self._binop(o, jnp.multiply)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, jnp.divide)

    def __rtruediv__(self, o):
        return self._binop(o, jnp.divide, rev=True)

    def __matmul__(self, o):
        return self._binop(o, jnp.matmul)

    def __neg__(self):
        return call(jnp.negative, self)

    def __pow__(self, p):
        return call(lambda x: jnp.power(x, p), self)

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"kind={self.kind})")


class Program:
    """≙ fluid Program (framework.py:5220): named variables + parameters
    scope + the optimizer/backward attachments ``minimize`` records."""

    def __init__(self):
        self.vars: Dict[str, Variable] = {}
        self.params: Dict[str, jnp.ndarray] = {}   # the "scope"
        self.buffers: Dict[str, jnp.ndarray] = {}  # non-trainable state
        # buffer-name → Variable computing its post-step value (BN running
        # stats); applied by the executor on TRAIN programs only
        self._buffer_updates: Dict[str, Variable] = {}
        self._is_test = False
        self._counter = 0
        self._version = 0          # bumped on mutation → executor recompile
        self._opt = None           # (optimizer, loss Variable)
        self._opt_state = None
        self._grad_names: Dict[str, str] = {}

    # -- construction ---------------------------------------------------------
    def _unique(self, prefix):
        self._counter += 1
        return f"{prefix}_{self._counter}"

    def add_var(self, var: Variable):
        self.vars[var.name] = var
        self._version += 1
        return var

    def create_parameter(self, shape, dtype=jnp.float32, name=None,
                         initializer=None):
        """≙ LayerHelper.create_parameter: materializes into the scope."""
        name = name or self._unique("param")
        if initializer is None:
            fan_in = int(np.prod(shape[:-1])) if len(shape) > 1 else shape[0]
            bound = float(np.sqrt(6.0 / max(fan_in + shape[-1], 1)))
            init = np.random.RandomState(
                abs(hash(name)) % (2**31)).uniform(
                -bound, bound, shape).astype("float32")
        else:
            init = np.asarray(initializer(shape), "float32")
        self.params[name] = jnp.asarray(init, dtype)
        var = Variable(self, name, shape, dtype, kind="param")
        return self.add_var(var)

    def create_buffer(self, shape, name=None, initializer=None,
                      dtype=jnp.float32):
        """Non-trainable scope state (BN running stats): evaluated like a
        param but excluded from grads/optimizer updates."""
        name = name or self._unique("buffer")
        init = (np.zeros(shape, "float32") if initializer is None
                else np.asarray(initializer(shape), "float32"))
        self.buffers[name] = jnp.asarray(init, dtype)
        var = Variable(self, name, shape, dtype, kind="buffer")
        return self.add_var(var)

    def clone(self, for_test: bool = False):
        """Shallow clone sharing the parameter scope (≙ Program.clone —
        the reference's test clone shares parameters and flips ops to
        is_test: here the mode is a run-time input, so the clone just
        records the flag and the executor feeds eval-mode)."""
        p = Program()
        p.vars = dict(self.vars)
        p.params = self.params      # shared scope, like the reference
        p.buffers = self.buffers
        p._buffer_updates = self._buffer_updates
        p._is_test = for_test or self._is_test
        p._counter = self._counter
        return p

    def global_block(self):
        return self                 # single-block programs (API parity)

    # -- evaluation -----------------------------------------------------------
    def _eval(self, var: Variable, feed_vals, params, buffers, memo):
        if var.name in memo:
            return memo[var.name]
        if var.kind == "data":
            val = feed_vals[var.name]
        elif var.kind == "param":
            val = params[var.name]
        elif var.kind == "buffer":
            val = buffers[var.name]
        elif var.kind == "mode":
            val = feed_vals["__training__"]
        elif var.kind == "rng":
            val = feed_vals["__rng__"]
        else:
            args = [self._eval(v, feed_vals, params, buffers, memo)
                    for v in var.inputs]
            val = var.op(*args)
        memo[var.name] = val
        return val

    def data_deps(self, var: Variable):
        """Names of the ``data`` placeholders var transitively reads —
        the executor uses this to skip buffer-update graphs whose inputs
        are not in the current feed (partial-fetch runs)."""
        out = set()
        stack = [var]
        seen = set()
        while stack:
            v = stack.pop()
            if v.name in seen:
                continue
            seen.add(v.name)
            if v.kind == "data":
                out.add(v.name)
            stack.extend(v.inputs)
        return out

    def _mode_var(self) -> Variable:
        """Shared run-mode input (True = training); the executor feeds it
        from the program's _is_test flag (≙ the reference rewriting ops to
        is_test in Program.clone — here mode is a run-time input)."""
        if "__mode__" not in self.vars:
            self.add_var(Variable(self, "__mode__", (), jnp.bool_,
                                  kind="mode"))
        return self.vars["__mode__"]

    def _rng_var(self) -> Variable:
        if "__rngv__" not in self.vars:
            self.add_var(Variable(self, "__rngv__", (), jnp.uint32,
                                  kind="rng"))
        return self.vars["__rngv__"]

    def build_fn(self, fetch_vars: Sequence[Variable],
                 feed_names: Sequence[str]):
        """Pure function (feed_vals, params, buffers) → fetched values."""
        def fn(feed_vals, params, buffers):
            memo = {}
            return [self._eval(v, feed_vals, params, buffers, memo)
                    for v in fetch_vars]
        return fn


# -- default-program machinery (≙ fluid.default_main_program) ---------------

_tls = threading.local()


def _progs():
    if not hasattr(_tls, "stack"):
        _tls.stack = [Program()]
    return _tls.stack


def default_main_program() -> Program:
    return _progs()[-1]


def default_startup_program() -> Program:
    """Parameters initialize at creation here; returns an empty runnable
    program for ``exe.run(startup)`` call-site parity."""
    return Program()


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program=None):
    _progs().append(main_program)
    try:
        yield
    finally:
        _progs().pop()


# -- op surface --------------------------------------------------------------

def data(name: str, shape, dtype=jnp.float32):
    """≙ paddle.static.data placeholder."""
    prog = default_main_program()
    var = Variable(prog, name, shape, dtype, kind="data")
    return prog.add_var(var)


def call(fn: Callable, *args, **kwargs):
    """Append an op node computing ``fn(*args, **kwargs)``; Variable args
    become graph edges, everything else is captured as a constant."""
    prog = None
    for a in args:
        if isinstance(a, Variable):
            prog = a.program
            break
    if prog is None:
        raise ValueError("call() needs at least one Variable argument")
    var_args = [a for a in args if isinstance(a, Variable)]

    def op(*vals):
        it = iter(vals)
        full = [next(it) if isinstance(a, Variable) else a for a in args]
        return fn(*full, **kwargs)

    name = prog._unique(getattr(fn, "__name__", "op"))
    out = Variable(prog, name, kind="op", op=op, inputs=var_args)
    return prog.add_var(out)


class _StaticNN:
    """≙ paddle.static.nn layer builders (LayerHelper style)."""

    @staticmethod
    def fc(x: Variable, size: int, num_flatten_dims: int = 1,
           activation: Optional[str] = None, name=None):
        prog = x.program
        in_dim = int(np.prod(x.shape[num_flatten_dims:]))
        w = prog.create_parameter((in_dim, size),
                                  name=name and f"{name}.w")
        b = prog.create_parameter((size,), name=name and f"{name}.b",
                                  initializer=lambda s: np.zeros(s))

        def op(xv, wv, bv):
            lead = xv.shape[:num_flatten_dims]
            flat = xv.reshape(lead + (-1,))
            out = flat @ wv + bv
            if activation is not None:
                from paddle_tpu.nn import functional as F
                out = getattr(F, activation)(out)
            return out

        out = Variable(prog, prog._unique(name or "fc"), kind="op", op=op,
                       inputs=[x, w, b])
        return prog.add_var(out)

    @staticmethod
    def conv2d(x: Variable, num_filters: int, filter_size, stride=1,
               padding=0, dilation=1, groups=1, activation=None,
               name=None):
        """≙ static.nn.conv2d (NCHW; weight OIHW like the reference)."""
        prog = x.program
        fs = ((filter_size, filter_size) if isinstance(filter_size, int)
              else tuple(filter_size))
        in_c = x.shape[1]
        wshape = (num_filters, in_c // groups) + fs
        fan_in = (in_c // groups) * int(np.prod(fs))
        fan_out = num_filters * int(np.prod(fs))
        bound = float(np.sqrt(6.0 / (fan_in + fan_out)))
        wname = f"{name}.w" if name else prog._unique("conv2d_w")
        import zlib
        seed = zlib.crc32(wname.encode()) % (2 ** 31)
        w = prog.create_parameter(
            wshape, name=wname,
            initializer=lambda s, b=bound, sd=seed: np.random.RandomState(
                sd).uniform(-b, b, s))
        b = prog.create_parameter((num_filters,),
                                  name=name and f"{name}.b",
                                  initializer=lambda s: np.zeros(s))

        def op(xv, wv, bv):
            from paddle_tpu.nn import functional as F
            out = F.conv2d(xv, wv, bv, stride=stride, padding=padding,
                           dilation=dilation, groups=groups)
            if activation is not None:
                out = getattr(F, activation)(out)
            return out

        out = Variable(prog, prog._unique(name or "conv2d"), kind="op",
                       op=op, inputs=[x, w, b])
        if x.shape is not None:  # static shape propagation for builders
            st = (stride, stride) if isinstance(stride, int) else stride
            pd = (padding, padding) if isinstance(padding, int) else padding
            dl = ((dilation, dilation) if isinstance(dilation, int)
                  else dilation)
            eff = [(f - 1) * d + 1 for f, d in zip(fs, dl)]
            h = (x.shape[2] + 2 * pd[0] - eff[0]) // st[0] + 1
            wdt = (x.shape[3] + 2 * pd[1] - eff[1]) // st[1] + 1
            out.shape = (x.shape[0], num_filters, h, wdt)
        return prog.add_var(out)

    @staticmethod
    def pool2d(x: Variable, pool_size=2, pool_type="max", pool_stride=None,
               pool_padding=0, name=None):
        """≙ static.nn.pool2d ('max' or 'avg')."""
        if pool_type not in ("max", "avg"):
            raise ValueError(f"pool_type must be 'max' or 'avg', "
                             f"got {pool_type!r}")

        def op(xv):
            from paddle_tpu.nn import functional as F
            fn = F.max_pool2d if pool_type == "max" else F.avg_pool2d
            return fn(xv, pool_size, stride=pool_stride,
                      padding=pool_padding)

        prog = x.program
        out = Variable(prog, prog._unique(name or "pool2d"), kind="op",
                       op=op, inputs=[x])
        if x.shape is not None:
            ks = ((pool_size, pool_size) if isinstance(pool_size, int)
                  else tuple(pool_size))
            st = ks if pool_stride is None else (
                (pool_stride, pool_stride)
                if isinstance(pool_stride, int) else tuple(pool_stride))
            pd = ((pool_padding, pool_padding)
                  if isinstance(pool_padding, int) else tuple(pool_padding))
            h = (x.shape[2] + 2 * pd[0] - ks[0]) // st[0] + 1
            w = (x.shape[3] + 2 * pd[1] - ks[1]) // st[1] + 1
            out.shape = (x.shape[0], x.shape[1], h, w)
        return prog.add_var(out)

    @staticmethod
    def embedding(x: Variable, size, padding_idx=None, name=None):
        """≙ static.nn.embedding: size = (vocab, dim)."""
        prog = x.program
        table = prog.create_parameter(tuple(size),
                                      name=name and f"{name}.w")

        def op(ids, tv):
            from paddle_tpu.nn import functional as F
            return F.embedding(jnp.asarray(ids, jnp.int32), tv,
                               padding_idx=padding_idx)

        out = Variable(prog, prog._unique(name or "embedding"), kind="op",
                       op=op, inputs=[x, table])
        return prog.add_var(out)

    @staticmethod
    def flatten(x: Variable, axis: int = 1, name=None):
        """≙ fluid.layers.flatten: 2-D output
        (prod(shape[:axis]), prod(shape[axis:]))."""
        prog = x.program

        def op(xv):
            lead = int(np.prod(xv.shape[:axis]))
            return xv.reshape(lead, -1)

        out = Variable(prog, prog._unique(name or "flatten"), kind="op",
                       op=op, inputs=[x])
        if x.shape is not None and all(
                d is not None and d >= 0 for d in x.shape):
            out.shape = (int(np.prod(x.shape[:axis])),
                         int(np.prod(x.shape[axis:])))
        elif x.shape is not None and axis == 1:
            out.shape = (x.shape[0], int(np.prod(x.shape[1:])))
        return prog.add_var(out)

    @staticmethod
    def batch_norm(x: Variable, momentum=0.9, epsilon=1e-5, name=None,
                   data_layout="NCHW", num_channels=None):
        """≙ static.nn.batch_norm: batch stats + running-stat updates in
        training mode (the executor applies the registered buffer
        updates), running stats in a ``clone(for_test=True)`` program —
        one graph, mode fed at run time."""
        prog = x.program
        if num_channels is not None:
            c = num_channels
        elif x.shape is not None:
            c = x.shape[1] if data_layout == "NCHW" else x.shape[-1]
        else:
            raise ValueError("batch_norm cannot infer the channel count "
                             "from an untyped variable; pass num_channels=")
        scale = prog.create_parameter((c,), name=name and f"{name}.scale",
                                      initializer=lambda s: np.ones(s))
        bias = prog.create_parameter((c,), name=name and f"{name}.bias",
                                     initializer=lambda s: np.zeros(s))
        prefix = name or prog._unique("bn")
        r_mean = prog.create_buffer((c,), name=f"{prefix}.mean")
        r_var = prog.create_buffer((c,), name=f"{prefix}.var",
                                   initializer=lambda s: np.ones(s))
        mode = prog._mode_var()
        axes = (0, 2, 3) if data_layout == "NCHW" else (0, 1, 2)
        shape_b = ((1, -1, 1, 1) if data_layout == "NCHW"
                   else (1, 1, 1, -1))

        def stat(xv):
            return jnp.mean(xv, axes), jnp.var(xv, axes)

        def op(xv, sv, bv_, rm, rv, training):
            bm, bvar = stat(xv)
            mean = jnp.where(training, bm, rm)
            var = jnp.where(training, bvar, rv)
            inv = jax.lax.rsqrt(var + epsilon)
            return ((xv - mean.reshape(shape_b)) * inv.reshape(shape_b)
                    * sv.reshape(shape_b) + bv_.reshape(shape_b))

        out = Variable(prog, prog._unique(name or "batch_norm"), kind="op",
                       op=op, inputs=[x, scale, bias, r_mean, r_var, mode])
        out.shape = x.shape  # elementwise: same static shape
        out = prog.add_var(out)

        # running-stat update nodes (applied by the executor in training)
        def upd_mean(xv, rm, training):
            bm, _ = stat(xv)
            return jnp.where(training, momentum * rm + (1 - momentum) * bm,
                             rm)

        def upd_var(xv, rv, training):
            _, bvar = stat(xv)
            return jnp.where(training,
                             momentum * rv + (1 - momentum) * bvar, rv)

        um = prog.add_var(Variable(prog, prog._unique("bn_upd_mean"),
                                   kind="op", op=upd_mean,
                                   inputs=[x, r_mean, mode]))
        uv = prog.add_var(Variable(prog, prog._unique("bn_upd_var"),
                                   kind="op", op=upd_var,
                                   inputs=[x, r_var, mode]))
        prog._buffer_updates[r_mean.name] = um
        prog._buffer_updates[r_var.name] = uv
        return out

    @staticmethod
    def dropout(x: Variable, dropout_prob=0.5, name=None):
        """≙ static.nn.dropout (upscale_in_train); active only in training
        mode, seeded per Executor.run."""
        prog = x.program
        mode = prog._mode_var()
        rng = prog._rng_var()
        node_name = prog._unique(name or "dropout")
        salt = abs(hash(node_name)) % (2 ** 31)

        def op(xv, training, seed):
            key = jax.random.fold_in(
                jax.random.PRNGKey(seed), salt)
            keep = jax.random.bernoulli(key, 1.0 - dropout_prob, xv.shape)
            dropped = jnp.where(keep, xv / (1.0 - dropout_prob), 0.0)
            return jnp.where(training, dropped.astype(xv.dtype), xv)

        out = Variable(prog, node_name, kind="op", op=op,
                       inputs=[x, mode, rng])
        out.shape = x.shape  # elementwise: same static shape
        return prog.add_var(out)

    @staticmethod
    def cross_entropy(input: Variable, label: Variable, soft_label=False,
                      name=None):
        """≙ fluid.layers.cross_entropy: ``input`` is a PROBABILITY
        distribution (e.g. fc(..., activation='softmax')); returns
        per-example loss (N, 1)."""
        prog = input.program

        def op(p, y):
            p = jnp.clip(p, 1e-8, 1.0)
            if soft_label:
                return -jnp.sum(y * jnp.log(p), -1, keepdims=True)
            y = jnp.asarray(y, jnp.int32).reshape(-1)
            picked = jnp.take_along_axis(p, y[:, None], axis=-1)
            return -jnp.log(picked)

        out = Variable(prog, prog._unique(name or "cross_entropy"),
                       kind="op", op=op, inputs=[input, label])
        return prog.add_var(out)


nn = _StaticNN()


def append_backward(loss: Variable, parameter_list=None):
    """≙ fluid.backward.append_backward:1726: registers @GRAD fetch names
    for every parameter; the executor computes them with jax.grad inside
    the same compiled program."""
    prog = loss.program
    names = parameter_list or list(prog.params)
    out = []
    for n in names:
        prog._grad_names[f"{n}@GRAD"] = n
        out.append((prog.vars[n], f"{n}@GRAD"))
    prog._loss_for_grads = loss
    prog._version += 1
    return out


def minimize(optimizer, loss: Variable):
    """≙ Optimizer.minimize in static mode: attaches the update rule; each
    ``Executor.run`` then performs forward + backward + parameter update
    as one compiled step, mutating the program scope."""
    prog = loss.program
    prog._opt = (optimizer, loss)
    prog._opt_state = None
    prog._version += 1
    return loss
