"""Lazy-graph Program/Executor — op-by-op static program construction.

Reference analog: Program/Block/Operator/Variable (fluid/framework.py:5220,
:3552, :2712, :1353), ``append_backward`` (fluid/backward.py:1726) and
``Executor.run`` (fluid/executor.py:1378 → InterpreterCore). The reference
keeps a protobuf op list and interprets it per step; here the Program is a
lazy expression graph over named Variables, and ``Executor.run`` compiles
the whole requested computation (forward + grads + optimizer update) into
ONE jitted XLA program per feed signature — the InterpreterCore's job done
by the compiler, with reference run semantics (feed dict in, fetched
numpy out, parameters mutated in the program's scope).

Supported porting surface: ``static.data``, ``static.nn.fc``, Variable
arithmetic, any registered tensor op through ``static.call`` /
``Variable.apply``, ``append_backward``, ``optimizer minimize`` via
``static.minimize``, ``Executor.run(feed, fetch_list)``,
``program_guard``/``default_main_program``. Programs that REWRITE blocks
(pass infrastructure) have no analog here — XLA owns program rewriting.
"""

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["Program", "Variable", "program_guard", "default_main_program",
           "default_startup_program", "data", "call", "minimize",
           "append_backward", "nn"]


class Variable:
    """Lazy graph node (≙ fluid Variable:1353 + the Operator producing it)."""

    def __init__(self, program: "Program", name: str, shape=None,
                 dtype=None, kind: str = "op",
                 op: Optional[Callable] = None,
                 inputs: Sequence["Variable"] = ()):
        self.program = program
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.kind = kind                # "data" | "param" | "op"
        self.op = op
        self.inputs = list(inputs)

    # -- graph building -------------------------------------------------------
    def apply(self, fn: Callable, *others, **kwargs):
        """New node computing ``fn(self, *others, **kwargs)`` — the
        ``append_op`` analog for any pure tensor function."""
        return call(fn, self, *others, **kwargs)

    def _binop(self, other, fn, rev=False):
        if isinstance(other, Variable):
            a, b = (other, self) if rev else (self, other)
            return call(fn, a, b)
        const = other
        if rev:
            return call(lambda x: fn(jnp.asarray(const), x), self)
        return call(lambda x: fn(x, jnp.asarray(const)), self)

    def __add__(self, o):
        return self._binop(o, jnp.add)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, jnp.subtract)

    def __rsub__(self, o):
        return self._binop(o, jnp.subtract, rev=True)

    def __mul__(self, o):
        return self._binop(o, jnp.multiply)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, jnp.divide)

    def __rtruediv__(self, o):
        return self._binop(o, jnp.divide, rev=True)

    def __matmul__(self, o):
        return self._binop(o, jnp.matmul)

    def __neg__(self):
        return call(jnp.negative, self)

    def __pow__(self, p):
        return call(lambda x: jnp.power(x, p), self)

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"kind={self.kind})")


class Program:
    """≙ fluid Program (framework.py:5220): named variables + parameters
    scope + the optimizer/backward attachments ``minimize`` records."""

    def __init__(self):
        self.vars: Dict[str, Variable] = {}
        self.params: Dict[str, jnp.ndarray] = {}   # the "scope"
        self._counter = 0
        self._version = 0          # bumped on mutation → executor recompile
        self._opt = None           # (optimizer, loss Variable)
        self._opt_state = None
        self._grad_names: Dict[str, str] = {}

    # -- construction ---------------------------------------------------------
    def _unique(self, prefix):
        self._counter += 1
        return f"{prefix}_{self._counter}"

    def add_var(self, var: Variable):
        self.vars[var.name] = var
        self._version += 1
        return var

    def create_parameter(self, shape, dtype=jnp.float32, name=None,
                         initializer=None):
        """≙ LayerHelper.create_parameter: materializes into the scope."""
        name = name or self._unique("param")
        if initializer is None:
            fan_in = int(np.prod(shape[:-1])) if len(shape) > 1 else shape[0]
            bound = float(np.sqrt(6.0 / max(fan_in + shape[-1], 1)))
            init = np.random.RandomState(
                abs(hash(name)) % (2**31)).uniform(
                -bound, bound, shape).astype("float32")
        else:
            init = np.asarray(initializer(shape), "float32")
        self.params[name] = jnp.asarray(init, dtype)
        var = Variable(self, name, shape, dtype, kind="param")
        return self.add_var(var)

    def clone(self, for_test: bool = False):
        """Shallow clone sharing the parameter scope (≙ Program.clone —
        the reference's test clone also shares parameters)."""
        p = Program()
        p.vars = dict(self.vars)
        p.params = self.params      # shared scope, like the reference
        p._counter = self._counter
        return p

    def global_block(self):
        return self                 # single-block programs (API parity)

    # -- evaluation -----------------------------------------------------------
    def _eval(self, var: Variable, feed_vals, params, memo):
        if var.name in memo:
            return memo[var.name]
        if var.kind == "data":
            val = feed_vals[var.name]
        elif var.kind == "param":
            val = params[var.name]
        else:
            args = [self._eval(v, feed_vals, params, memo)
                    for v in var.inputs]
            val = var.op(*args)
        memo[var.name] = val
        return val

    def build_fn(self, fetch_vars: Sequence[Variable],
                 feed_names: Sequence[str]):
        """Pure function (feed_vals, params) → fetched values."""
        def fn(feed_vals, params):
            memo = {}
            return [self._eval(v, feed_vals, params, memo)
                    for v in fetch_vars]
        return fn


# -- default-program machinery (≙ fluid.default_main_program) ---------------

_tls = threading.local()


def _progs():
    if not hasattr(_tls, "stack"):
        _tls.stack = [Program()]
    return _tls.stack


def default_main_program() -> Program:
    return _progs()[-1]


def default_startup_program() -> Program:
    """Parameters initialize at creation here; returns an empty runnable
    program for ``exe.run(startup)`` call-site parity."""
    return Program()


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program=None):
    _progs().append(main_program)
    try:
        yield
    finally:
        _progs().pop()


# -- op surface --------------------------------------------------------------

def data(name: str, shape, dtype=jnp.float32):
    """≙ paddle.static.data placeholder."""
    prog = default_main_program()
    var = Variable(prog, name, shape, dtype, kind="data")
    return prog.add_var(var)


def call(fn: Callable, *args, **kwargs):
    """Append an op node computing ``fn(*args, **kwargs)``; Variable args
    become graph edges, everything else is captured as a constant."""
    prog = None
    for a in args:
        if isinstance(a, Variable):
            prog = a.program
            break
    if prog is None:
        raise ValueError("call() needs at least one Variable argument")
    var_args = [a for a in args if isinstance(a, Variable)]

    def op(*vals):
        it = iter(vals)
        full = [next(it) if isinstance(a, Variable) else a for a in args]
        return fn(*full, **kwargs)

    name = prog._unique(getattr(fn, "__name__", "op"))
    out = Variable(prog, name, kind="op", op=op, inputs=var_args)
    return prog.add_var(out)


class _StaticNN:
    """≙ paddle.static.nn layer builders (LayerHelper style)."""

    @staticmethod
    def fc(x: Variable, size: int, num_flatten_dims: int = 1,
           activation: Optional[str] = None, name=None):
        prog = x.program
        in_dim = int(np.prod(x.shape[num_flatten_dims:]))
        w = prog.create_parameter((in_dim, size),
                                  name=name and f"{name}.w")
        b = prog.create_parameter((size,), name=name and f"{name}.b",
                                  initializer=lambda s: np.zeros(s))

        def op(xv, wv, bv):
            lead = xv.shape[:num_flatten_dims]
            flat = xv.reshape(lead + (-1,))
            out = flat @ wv + bv
            if activation is not None:
                from paddle_tpu.nn import functional as F
                out = getattr(F, activation)(out)
            return out

        out = Variable(prog, prog._unique(name or "fc"), kind="op", op=op,
                       inputs=[x, w, b])
        return prog.add_var(out)


nn = _StaticNN()


def append_backward(loss: Variable, parameter_list=None):
    """≙ fluid.backward.append_backward:1726: registers @GRAD fetch names
    for every parameter; the executor computes them with jax.grad inside
    the same compiled program."""
    prog = loss.program
    names = parameter_list or list(prog.params)
    out = []
    for n in names:
        prog._grad_names[f"{n}@GRAD"] = n
        out.append((prog.vars[n], f"{n}@GRAD"))
    prog._loss_for_grads = loss
    prog._version += 1
    return out


def minimize(optimizer, loss: Variable):
    """≙ Optimizer.minimize in static mode: attaches the update rule; each
    ``Executor.run`` then performs forward + backward + parameter update
    as one compiled step, mutating the program scope."""
    prog = loss.program
    prog._opt = (optimizer, loss)
    prog._opt_state = None
    prog._version += 1
    return loss
