"""Graph learning ops (ref: python/paddle/geometric/ — message passing
send_u_recv etc.; phi graph_send_recv kernels). On TPU these are
segment-reduction ops."""

import jax
import jax.numpy as jnp

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min"]


def segment_sum(data, segment_ids, num_segments=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_sum(jnp.asarray(data), jnp.asarray(segment_ids),
                               num_segments=n)


def segment_mean(data, segment_ids, num_segments=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    s = jax.ops.segment_sum(jnp.asarray(data), jnp.asarray(segment_ids),
                            num_segments=n)
    c = jax.ops.segment_sum(jnp.ones_like(jnp.asarray(data)),
                            jnp.asarray(segment_ids), num_segments=n)
    return s / jnp.maximum(c, 1)


def segment_max(data, segment_ids, num_segments=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_max(jnp.asarray(data), jnp.asarray(segment_ids),
                               num_segments=n)


def segment_min(data, segment_ids, num_segments=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_min(jnp.asarray(data), jnp.asarray(segment_ids),
                               num_segments=n)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None):
    """ref: paddle.geometric.send_u_recv (graph_send_recv kernel)."""
    x = jnp.asarray(x)
    gathered = x[jnp.asarray(src_index)]
    n = out_size or x.shape[0]
    red = {"sum": segment_sum, "mean": segment_mean, "max": segment_max,
           "min": segment_min}[reduce_op]
    return red(gathered, dst_index, n)


def send_ue_recv(x, e, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None):
    x = jnp.asarray(x)
    e = jnp.asarray(e)
    msg = x[jnp.asarray(src_index)]
    msg = msg + e if message_op == "add" else msg * e
    n = out_size or x.shape[0]
    red = {"sum": segment_sum, "mean": segment_mean, "max": segment_max,
           "min": segment_min}[reduce_op]
    return red(msg, dst_index, n)


def send_uv(x, y, src_index, dst_index, message_op="add"):
    x = jnp.asarray(x)[jnp.asarray(src_index)]
    y = jnp.asarray(y)[jnp.asarray(dst_index)]
    return x + y if message_op == "add" else x * y
