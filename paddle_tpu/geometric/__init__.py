"""Graph learning ops (ref: python/paddle/geometric/ — message passing
send_u_recv etc.; phi graph_send_recv kernels). On TPU these are
segment-reduction ops."""

import jax
import jax.numpy as jnp

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min",
           "reindex_graph", "reindex_heter_graph", "sample_neighbors"]


def segment_sum(data, segment_ids, num_segments=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_sum(jnp.asarray(data), jnp.asarray(segment_ids),
                               num_segments=n)


def segment_mean(data, segment_ids, num_segments=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    s = jax.ops.segment_sum(jnp.asarray(data), jnp.asarray(segment_ids),
                            num_segments=n)
    c = jax.ops.segment_sum(jnp.ones_like(jnp.asarray(data)),
                            jnp.asarray(segment_ids), num_segments=n)
    return s / jnp.maximum(c, 1)


def segment_max(data, segment_ids, num_segments=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_max(jnp.asarray(data), jnp.asarray(segment_ids),
                               num_segments=n)


def segment_min(data, segment_ids, num_segments=None):
    n = num_segments or int(jnp.max(segment_ids)) + 1
    return jax.ops.segment_min(jnp.asarray(data), jnp.asarray(segment_ids),
                               num_segments=n)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None):
    """ref: paddle.geometric.send_u_recv (graph_send_recv kernel)."""
    x = jnp.asarray(x)
    gathered = x[jnp.asarray(src_index)]
    n = out_size or x.shape[0]
    red = {"sum": segment_sum, "mean": segment_mean, "max": segment_max,
           "min": segment_min}[reduce_op]
    return red(gathered, dst_index, n)


def send_ue_recv(x, e, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None):
    x = jnp.asarray(x)
    e = jnp.asarray(e)
    msg = x[jnp.asarray(src_index)]
    msg = msg + e if message_op == "add" else msg * e
    n = out_size or x.shape[0]
    red = {"sum": segment_sum, "mean": segment_mean, "max": segment_max,
           "min": segment_min}[reduce_op]
    return red(msg, dst_index, n)


def send_uv(x, y, src_index, dst_index, message_op="add"):
    x = jnp.asarray(x)[jnp.asarray(src_index)]
    y = jnp.asarray(y)[jnp.asarray(dst_index)]
    return x + y if message_op == "add" else x * y


def _renumber(pos, out_nodes, nbr):
    """Shared subgraph renumbering: extend (pos, out_nodes) with unseen
    ids in ``nbr`` and return their dense indices."""
    import numpy as np
    src = np.empty(len(nbr), np.int32)
    for i, n in enumerate(nbr):
        n = int(n)
        j = pos.get(n)
        if j is None:
            j = len(out_nodes)
            pos[n] = j
            out_nodes.append(n)
        src[i] = j
    return src


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """ref: geometric/reindex.py reindex_graph:24 — renumber sampled
    subgraph node ids to a dense 0..n range; returns (reindex_src,
    reindex_dst, out_nodes). Host-side index plumbing (like the
    reference's CPU kernel); the renumbered edges then feed device
    segment ops."""
    import numpy as np
    x_np = np.asarray(jax.device_get(jnp.asarray(x)))
    nbr = np.asarray(jax.device_get(jnp.asarray(neighbors)))
    cnt = np.asarray(jax.device_get(jnp.asarray(count)))
    out_nodes = list(x_np)
    pos = {int(n): i for i, n in enumerate(x_np)}
    src = _renumber(pos, out_nodes, nbr)
    dst = np.repeat(np.arange(len(x_np)), cnt)
    return (jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32),
            jnp.asarray(np.asarray(out_nodes), jnp.int32))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """ref: reindex.py reindex_heter_graph — per-edge-type neighbor lists
    against ONE shared node renumbering."""
    import numpy as np
    x_np = np.asarray(jax.device_get(jnp.asarray(x)))
    out_nodes = list(x_np)
    pos = {int(n): i for i, n in enumerate(x_np)}
    srcs, dsts = [], []
    for nbr, cnt in zip(neighbors, count):
        nbr = np.asarray(jax.device_get(jnp.asarray(nbr)))
        cnt = np.asarray(jax.device_get(jnp.asarray(cnt)))
        srcs.append(jnp.asarray(_renumber(pos, out_nodes, nbr), jnp.int32))
        dsts.append(jnp.asarray(np.repeat(np.arange(len(x_np)), cnt),
                                jnp.int32))
    return srcs, dsts, jnp.asarray(np.asarray(out_nodes), jnp.int32)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None,
                     seed=0):
    """ref: geometric/sampling/neighbors.py sample_neighbors:23 — uniform
    neighbor sampling from a CSC graph. Host-side (sampling is data-
    dependent-shape by nature; the reference's is a CPU/GPU custom kernel
    outside the compiled graph too). Returns (out_neighbors, out_count
    [, out_eids])."""
    import numpy as np
    row_np = np.asarray(jax.device_get(jnp.asarray(row))).reshape(-1)
    col_np = np.asarray(jax.device_get(jnp.asarray(colptr))).reshape(-1)
    nodes = np.asarray(jax.device_get(jnp.asarray(input_nodes))).reshape(-1)
    eids_np = None if eids is None else np.asarray(
        jax.device_get(jnp.asarray(eids))).reshape(-1)
    rs = np.random.RandomState(seed)
    out_n, out_c, out_e = [], [], []
    for v in nodes:
        beg, end = int(col_np[int(v)]), int(col_np[int(v) + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(beg, end)
        else:
            sel = beg + rs.choice(deg, size=sample_size, replace=False)
        out_n.append(row_np[sel])
        out_c.append(len(sel))
        if eids_np is not None:
            out_e.append(eids_np[sel])
    out_neighbors = jnp.asarray(np.concatenate(out_n) if out_n
                                else np.zeros(0, row_np.dtype))
    out_count = jnp.asarray(np.asarray(out_c, np.int32))
    if return_eids:
        if eids_np is None:
            raise ValueError("return_eids=True requires eids")
        return (out_neighbors, out_count,
                jnp.asarray(np.concatenate(out_e) if out_e
                            else np.zeros(0, eids_np.dtype)))
    return out_neighbors, out_count


def khop_sampler(row, colptr, input_nodes, sample_sizes, sorted_eids=None,
                 return_eids=False, seed=0):
    """ref: incubate.graph_khop_sampler (graph_khop_sampler_op) — multi-hop
    neighbor sampling from a CSC graph, host-side like sample_neighbors.
    Returns (edge_src, edge_dst, sample_index, reindex_x): edges are
    reindexed into the sampled-node numbering, sample_index maps new ids
    back to original node ids, reindex_x locates the seed nodes."""
    import numpy as np
    row_np = np.asarray(jax.device_get(jnp.asarray(row))).reshape(-1)
    col_np = np.asarray(jax.device_get(jnp.asarray(colptr))).reshape(-1)
    seeds = np.asarray(jax.device_get(jnp.asarray(input_nodes))).reshape(-1)
    eids_np = None if sorted_eids is None else np.asarray(
        jax.device_get(jnp.asarray(sorted_eids))).reshape(-1)
    if return_eids and eids_np is None:
        raise ValueError("return_eids=True requires sorted_eids")
    rs = np.random.RandomState(seed)
    frontier = seeds
    edge_src, edge_dst, edge_ids = [], [], []
    for size in sample_sizes:
        next_frontier = []
        for v in frontier:
            beg, end = int(col_np[int(v)]), int(col_np[int(v) + 1])
            deg = end - beg
            if deg == 0:
                continue
            if size < 0 or deg <= size:
                sel = np.arange(beg, end)
            else:
                sel = beg + rs.choice(deg, size=size, replace=False)
            nbrs = row_np[sel]
            edge_src.extend(nbrs.tolist())
            edge_dst.extend([int(v)] * len(nbrs))
            if eids_np is not None:
                edge_ids.extend(eids_np[sel].tolist())
            next_frontier.extend(nbrs.tolist())
        frontier = np.unique(np.asarray(next_frontier, row_np.dtype))
    # unique node table: seeds first (so reindex_x = arange(len(seeds)))
    uniq = np.unique(np.concatenate(
        [seeds, np.asarray(edge_src, row_np.dtype),
         np.asarray(edge_dst, row_np.dtype)]))
    seed_set = set(seeds.tolist())
    order = {int(n): i for i, n in enumerate(
        list(dict.fromkeys(seeds.tolist()))
        + [n for n in uniq.tolist() if n not in seed_set])}
    sample_index = np.asarray(sorted(order, key=order.get), row_np.dtype)
    esrc = np.asarray([order[int(s)] for s in edge_src], np.int64)
    edst = np.asarray([order[int(d)] for d in edge_dst], np.int64)
    reindex_x = np.asarray([order[int(s)] for s in seeds], np.int64)
    out = (jnp.asarray(esrc), jnp.asarray(edst),
           jnp.asarray(sample_index), jnp.asarray(reindex_x))
    if return_eids:
        out = out + (jnp.asarray(np.asarray(edge_ids, np.int64)),)
    return out


__all__ += ["khop_sampler"]
