"""Cost model (ref: python/paddle/cost_model/cost_model.py — CostModel:
build_program/profile_measure/static_cost_data/get_static_op_time, backed
by static_op_benchmark.json profiles).

TPU-native re-design: instead of a shipped JSON of pre-profiled CUDA op
times, costs come from the two sources that exist on this stack —
(a) XLA's own cost analysis of a compiled callable (exact FLOPs/bytes for
THE program that will run), and (b) live profile_measure timing on the
attached device. A tiny analytic roofline turns (a) into seconds, which
is what the auto-parallel planner consumes (distributed/planner.py cites
this module's estimates for its fsdp-vs-tp choice)."""

import time

import jax

__all__ = ["CostModel"]

# bf16 peak FLOP/s, HBM GB/s, and per-chip ICI GB/s per generation
# (public numbers; ICI is the aggregate inter-chip bandwidth a collective
# can ride — the scaling-book's beta term)
_PEAKS = {"v6": (918e12, 1640e9, 360e9), "v5p": (459e12, 2765e9, 480e9),
          "v5": (197e12, 819e9, 160e9), "v4": (275e12, 1228e9, 240e9),
          "v3": (123e12, 900e9, 140e9), "cpu": (1e11, 5e10, 1e10)}


def _peak(device):
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in _PEAKS.items():
        if key in kind:
            return val
    return _PEAKS["v5"]


class CostModel:
    """(≙ cost_model.py CostModel:23)."""

    #: per-host DCN bandwidth (bytes/s) a cross-host collective can ride —
    #: a 200 Gbps NIC ballpark; ~an order of magnitude below ICI, which is
    #: why the planner routes only low-volume axes (pp activations) over it
    #: (≙ auto_parallel/cost/comm_op_cost.py's cross-machine link tier)
    DCN_BW = 25e9

    def __init__(self, dcn_bw: float = None, device_kind: str = None):
        """``device_kind`` ("v5", "v4", ...) plans for a TARGET chip
        without being attached to it — the search path runs on CPU but
        must reason with real TPU peaks (≙ the reference shipping
        static_op_benchmark.json profiles for absent hardware)."""
        if device_kind is not None:
            # planning for a TARGET chip: never touch the local backend
            # (the tunnel may be down — that's the very case this serves)
            self.device = None
            kind = device_kind.lower()
            for key, val in _PEAKS.items():
                if key in kind:
                    self.peak_flops, self.peak_bw, self.ici_bw = val
                    break
            else:
                raise ValueError(
                    f"unknown device_kind {device_kind!r}; expected one "
                    f"containing {sorted(_PEAKS)}")
        else:
            self.device = jax.devices()[0]
            self.peak_flops, self.peak_bw, self.ici_bw = _peak(self.device)
        self.dcn_bw = dcn_bw if dcn_bw is not None else self.DCN_BW
        self._measured = {}

    def collective_time(self, nbytes: float, tier: str = "ici") -> float:
        """Seconds to move ``nbytes`` over the given link tier ("ici"
        within a slice, "dcn" across hosts; bandwidth term only — latency
        is negligible at the message sizes the planner reasons about)."""
        bw = self.dcn_bw if tier == "dcn" else self.ici_bw
        return float(nbytes) / bw

    # -- static (analysis-based) costs --------------------------------------

    def static_cost_data(self, fn, *example_args):
        """XLA cost analysis of ``jit(fn)`` on example args: returns the
        raw dict (flops, bytes accessed, ...) — the analog of the
        reference's static_op_benchmark.json rows, but for the exact
        program (≙ static_cost_data:65)."""
        compiled = jax.jit(fn).lower(*example_args).compile()
        data = compiled.cost_analysis()
        if isinstance(data, (list, tuple)):  # older jax: list of dicts
            data = data[0] if data else {}
        if not isinstance(data, dict):
            import warnings
            warnings.warn(f"cost_analysis returned {type(data).__name__}; "
                          "static costs unavailable")
            return {}
        return dict(data)

    def get_static_op_time(self, fn, *example_args, forward=True,
                           dtype="float32"):
        """Roofline seconds for ``fn``: max(flops/peak, bytes/bandwidth)
        (≙ get_static_op_time:75; here per-callable, not per-op-name —
        there is no per-op dispatch to look up)."""
        data = self.static_cost_data(fn, *example_args)
        flops = float(data.get("flops", 0.0))
        if not forward:
            flops *= 3.0  # bwd ≈ 2x fwd on top of fwd
        nbytes = float(data.get("bytes accessed", 0.0))
        return max(flops / self.peak_flops, nbytes / self.peak_bw)

    # -- measured costs ------------------------------------------------------

    def profile_measure(self, fn, *example_args, warmup=1, iters=3):
        """Wall-clock measure of ``jit(fn)`` on the attached device
        (≙ profile_measure:46). Returns seconds per call."""
        jfn = jax.jit(fn)
        out = jfn(*example_args)
        for _ in range(warmup):
            out = jfn(*example_args)
        jax.tree_util.tree_map(
            lambda a: a.block_until_ready() if hasattr(
                a, "block_until_ready") else a, out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jfn(*example_args)
        leaf = jax.tree_util.tree_leaves(out)[0]
        float(leaf.reshape(-1)[0])  # sync (tunnel-safe)
        dt = (time.perf_counter() - t0) / iters
        self._measured[getattr(fn, "__name__", repr(fn))] = dt
        return dt
