"""Continuous-batching serving front-end (docs/serving.md "Front-end").

The service layer over the decode engines: admission-controlled
scheduling with deadline-aware queueing and slot backfill
(``scheduler.FrontEnd``), deterministic Poisson/trace load generation
(``loadgen``), and multi-replica routing over TCPStore membership
(``router.Router`` / ``router.serve_replica``).
"""

from paddle_tpu.serving.scheduler import (FrontEnd, ServeRequest,
                                          dynamic_bucket, projected_ttft)
from paddle_tpu.serving.loadgen import (Arrival, poisson_trace,
                                        from_trace, replay)
from paddle_tpu.serving.router import Router, serve_replica, router_port
from paddle_tpu.serving.disagg import (FleetPrefixDirectory,
                                       serve_prefill_replica,
                                       serve_decode_replica, serve_role)

__all__ = ["FrontEnd", "ServeRequest", "dynamic_bucket",
           "projected_ttft", "Arrival", "poisson_trace", "from_trace",
           "replay", "Router", "serve_replica", "router_port",
           "FleetPrefixDirectory", "serve_prefill_replica",
           "serve_decode_replica", "serve_role"]
