"""Disaggregated prefill/decode serving: roles, the fleet-wide prefix
directory, and the replica serve loops (docs/serving.md
"Disaggregated serving"; ROADMAP open item 2).

Real fleets split replicas by phase: prefill is compute-bound (big
buckets, shallow batches), decode is memory-bound (deep occupancy).
Co-locating them throttles each replica's batch by whichever phase it
happens to run. Here:

- **prefill replicas** (``PagedDecodeEngine(prefill_only=True)``) run
  bucketed prefill only; the finished pages leave over the block-scaled
  KV wire (``serving/kv_transfer.py``) as a per-request handoff blob on
  the router's store.
- **decode replicas** install handoff pages
  (``engine.submit_handoff``) and run the normal harvest pipeline —
  on the fp32 wire the token stream is bit-identical to same-replica
  serving.
- the **fleet prefix directory** (:class:`FleetPrefixDirectory`) layers
  `inference/prefix_cache.py`'s page-aligned sha1 chain digests over
  the TCPStore: a replica publishes every newly-canonical prefix page
  (content-addressed — racing replicas converge first-writer-wins),
  any replica's admission extends a local miss through the fleet
  (suffix-only prefill on a hit: ``serve/fleet_prefix_hit_tokens``),
  and local invalidation (poison) or reclaim (eviction) WITHDRAWS the
  digest fleet-wide via the prefix cache's ``on_drop`` hook before the
  page can be remapped. A fetch re-validates the directory entry's
  generation after reading the payload, so a withdraw racing a fetch
  makes the fetch a miss — a sharer can never install a stale page.

Placement lives in ``serving/router.py`` (role- and KV-bytes-aware);
this module owns the per-replica halves.
"""

import json
import os
import time
from typing import Dict, Optional

import numpy as np

from paddle_tpu.distributed import resilience
from paddle_tpu.distributed.membership import ReplicaDirectory
from paddle_tpu.serving import kv_transfer

__all__ = ["FleetPrefixDirectory", "serve_prefill_replica",
           "serve_decode_replica", "serve_role", "replica_load",
           "fleet_enabled"]


def serve_role() -> str:
    """This replica's serving role (``PT_SERVE_ROLE``):
    ``both`` (default — symmetric serving, PR 9 behavior),
    ``prefill``, or ``decode``."""
    role = os.environ.get("PT_SERVE_ROLE", "both").strip().lower()
    if role not in ("both", "prefill", "decode"):
        raise ValueError(
            f"PT_SERVE_ROLE must be both|prefill|decode, got {role!r}")
    return role


def fleet_enabled() -> bool:
    """``PT_FLEET_PREFIX`` (default on): 0 disables fleet prefix
    directory publication and lookup — replicas fall back to their
    local radix caches only."""
    return os.environ.get("PT_FLEET_PREFIX", "1") != "0"


def queue_age_s(frontend=None, engine=None) -> float:
    """Age (seconds) of the OLDEST waiting request — the runaway-queue
    detector's per-replica gauge. Looks at the front-end admission
    queue and/or the engine's own waiting deque (whichever the caller
    has); 0.0 when nothing waits."""
    now = time.perf_counter()
    ages = [0.0]
    if frontend is not None and frontend._queue:
        ages.append(now - min(r.t_submit for r in frontend._queue))
    eng = engine if engine is not None else (
        frontend.engine if frontend is not None else None)
    if eng is not None and eng._waiting:
        ages.append(now - min(r.t_submit for r in eng._waiting))
    return max(ages)


def replica_load(engine, role: str, queued: int = 0,
                 queue_age_s: float = 0.0) -> dict:
    """The gauge-style load fields a replica refreshes with its
    heartbeat (one store write per beat, one read per router poll):
    role-aware routing places prefill by ``queued`` + bucket fit and
    decode by ``kv_bytes`` + ``free_pages``; the fleet anomaly watch
    (observability/fleet) reads ``tokens`` (progress — a busy replica
    whose counter freezes is stalled), ``busy_slots``, ``queue_age_s``
    and ``total_pages``/``free_pages`` (pool exhaustion)."""
    from paddle_tpu import stats
    return {
        "role": role,
        "queued": int(queued),
        "free_slots": int(engine.free_slots),
        "busy_slots": int(engine.S - engine.free_slots),
        "free_pages": int(getattr(engine, "free_pages", 0)),
        "total_pages": int(getattr(engine, "P", 0)),
        "kv_bytes": int(getattr(engine, "kv_bytes", 0)),
        "tokens": int(engine.tokens_emitted),
        "queue_age_s": round(float(queue_age_s), 3),
        # process-local fleet counters ride the heartbeat so the
        # router/CI can assert cross-replica hits without scraping
        # replica processes
        "fleet_hit_tokens": int(stats.get(
            "serve/fleet_prefix_hit_tokens", 0)),
        "kv_transfer_bytes_wire": int(stats.get(
            "serve/kv_transfer_bytes_wire", 0)),
    }


class FleetPrefixDirectory:
    """Fleet-wide radix-digest directory over the router's TCPStore.

    One instance per replica process (``rid`` identifies the owner for
    withdraw bookkeeping). Content-addressed entries::

        fleetpfx/e/<digest-hex>        -> JSON {rid, gen}   (written LAST)
        fleetpfx/pg/<digest-hex>/<gen> -> KV-wire blob (chunked)
        fleetpfx/g/<digest-hex>        -> generation counter
        fleetpfx/l/<digest-hex>        -> fetch lease counter

    The **refcount lease** protocol: a fetcher bumps the lease before
    reading the payload and drops it after; a withdraw deletes the
    ENTRY first (no new fetchers) and deletes the payload chunks only
    at lease zero (an in-flight fetch finishes its read, then discards
    when the entry re-check fails). Invalidation is therefore ordered
    before any possible stale mapping without ever blocking the owner.
    """

    def __init__(self, store, rid: str, wire: Optional[str] = None,
                 namespace: str = "fleetpfx"):
        self.store = store
        self.rid = rid
        self.ns = namespace
        self.wire = kv_transfer.wire_format(wire)
        self._published: Dict[bytes, int] = {}   # digest -> gen (owner)

    # -- keys ---------------------------------------------------------------

    def _ekey(self, digest: bytes) -> str:
        return f"{self.ns}/e/{digest.hex()}"

    def _pkey(self, digest: bytes, gen: int) -> str:
        return f"{self.ns}/pg/{digest.hex()}/{gen}"

    # -- owner side ---------------------------------------------------------

    def publish(self, digest: bytes, k: np.ndarray, v: np.ndarray):
        """Publish one page's KV under its chain digest (k/v:
        (L, 1, Hkv, page, D) host arrays). Content-addressed: an entry
        already present (any owner) wins — identical prefix KV is
        deterministic in the weights, so racing replicas publishing the
        same digest carry the same page."""
        from paddle_tpu import stats
        if digest in self._published:
            return
        # no existence probe: a store.get miss blocks its full timeout
        # on the admission hot path. Racing publishers write IDENTICAL
        # content (pages are deterministic in the weights, and lossy-
        # wire copies are never re-published), each under its own
        # generation — the last entry write wins, fetchers re-validate
        # the gen, and each publisher deletes only its own generation's
        # chunks on withdraw
        try:
            gen = self.store.add(f"{self.ns}/g/{digest.hex()}", 1)
            page = k.shape[3]
            header, blob = kv_transfer.encode_kv_pages(
                k, v, n_tokens=page, wire=self.wire)
            kv_transfer.publish_blob(self.store,
                                     self._pkey(digest, gen),
                                     header, blob)
            # entry LAST: a reader that sees it can fetch the payload
            self.store.set(self._ekey(digest),
                           json.dumps({"rid": self.rid, "gen": gen}))
        except resilience.StorePartitioned:
            # publication is warmth, not correctness: a partitioned
            # store skips it (NOT marked published — a later admission
            # or the failover republish hook retries)
            stats.add("serve/fleet_prefix_publish_skipped")
            return
        self._published[digest] = gen
        stats.add("serve/fleet_prefix_published")

    def reset_published(self):
        """Forget what this replica has published — the router-failover
        recovery hook (`router.ReplicaSession._recover`). A NEW router
        generation's store starts empty, so every digest in
        ``_published`` is a stale memory: left in place it would make
        :meth:`publish` skip re-publication forever and the fleet would
        silently lose this replica's warm prefixes. The engine's
        ``fleet_republish`` walks the live radix cache and re-publishes
        through the now-cleared set."""
        self._published.clear()

    def withdraw(self, digest: bytes, force: bool = False):
        """Invalidate a digest fleet-wide (eviction/poison on the
        owning replica; the prefix cache's ``on_drop`` hook lands
        here). Non-owners no-op unless ``force`` — replica B dropping
        its private ADOPTED copy must not nuke A's canonical entry.
        The entry key is deleted only when it still carries THIS
        replica's (rid, gen): a publish race that let another replica
        overwrite the entry means ours lost — deleting the winner's
        live entry would silently evict a valid warm prefix. Our own
        generation's payload chunks are deleted at lease zero either
        way; with a lease outstanding the DISCARDING fetcher deletes
        them (see :meth:`fetch`)."""
        from paddle_tpu import stats
        gen = self._published.pop(digest, None)
        if gen is None and not force:
            return
        try:
            ent = None
            try:
                ent = json.loads(self.store.get(self._ekey(digest),
                                                timeout=0.05))
            except (TimeoutError, ValueError):
                pass
            if gen is None and ent is not None:   # force: current gen
                gen = int(ent["gen"])
            if ent is not None and (force or (
                    ent.get("rid") == self.rid
                    and int(ent.get("gen", -1)) == gen)):
                self.store.delete_key(self._ekey(digest))
            if gen is not None:
                try:
                    leases = int(self.store.add(
                        f"{self.ns}/l/{digest.hex()}", 0))
                except Exception:
                    leases = 0
                if leases <= 0:
                    kv_transfer.delete_blob(self.store,
                                            self._pkey(digest, gen))
            stats.add("serve/fleet_prefix_withdrawn")
        except Exception:
            pass                        # withdraw is best-effort

    # -- fetcher side -------------------------------------------------------

    def lookup(self, digest: bytes) -> bool:
        """Directory-only probe (no payload): is the digest published?
        The router's pre-placement consult."""
        try:
            self.store.get(self._ekey(digest), timeout=0.02)
            return True
        except (TimeoutError, resilience.StorePartitioned):
            return False

    def covered(self, chain) -> int:
        """How many LEADING digests of ``chain`` the fleet covers."""
        n = 0
        for digest in chain:
            if not self.lookup(digest):
                break
            n += 1
        return n

    def fetch(self, digest: bytes):
        """Fetch one page's KV, or None on miss. The entry is re-read
        AFTER the payload: if it vanished or changed generation
        mid-fetch (a racing withdraw — eviction or poison on the
        owner), the payload is DISCARDED — the invalidation wins, no
        stale page can be mapped."""
        from paddle_tpu import stats
        key = self._ekey(digest)
        try:
            ent = json.loads(self.store.get(key, timeout=0.02))
        except (TimeoutError, ValueError,
                resilience.StorePartitioned):
            return None
        gen = int(ent["gen"])
        lease = f"{self.ns}/l/{digest.hex()}"
        try:
            self.store.add(lease, 1)
            t0 = time.perf_counter()
            try:
                header, blob = kv_transfer.fetch_blob(
                    self.store, self._pkey(digest, gen), timeout=2.0)
            except TimeoutError:
                self.store.add(lease, -1)
                return None             # withdrawn mid-fetch
            leases = self.store.add(lease, -1)
        except resilience.StorePartitioned:
            return None                 # partition mid-fetch: a miss
        try:
            ent2 = json.loads(self.store.get(key, timeout=0.02))
            stale = int(ent2["gen"]) != gen
        except (TimeoutError, ValueError,
                resilience.StorePartitioned):
            stale = True                # withdrawn mid-fetch: discard
        if stale:
            # the owner's withdraw skipped chunk deletion while our
            # lease was out — the discarding fetcher cleans up, so a
            # withdraw-during-fetch never leaks the payload
            if leases <= 0:
                kv_transfer.delete_blob(self.store,
                                        self._pkey(digest, gen),
                                        nchunks=int(header["nchunks"]))
            return None
        k, v = kv_transfer.decode_kv_pages(header, blob)
        stats.observe("serve/kv_transfer_s", time.perf_counter() - t0)
        return k, v


# ---------------------------------------------------------------------------
# Replica serve loops (the role-split halves of router.serve_replica)
# ---------------------------------------------------------------------------

def _mailbox_pump(store, rid, seen):
    """The ONE mailbox idiom lives in router.py; re-exported here for
    the role loops below."""
    from paddle_tpu.serving.router import _mailbox_pump as pump
    return pump(store, rid, seen)


def _shutdown_requested(store) -> bool:
    from paddle_tpu.serving.router import (
        _shutdown_requested as probe)
    return probe(store)


def serve_prefill_replica(store, rid: str, engine, poll_s: float = 0.02,
                          max_idle_s: Optional[float] = None,
                          load_refresh_s: float = 0.25):
    """One prefill replica's loop: consume the mailbox, run big-bucket
    prefill only, and for each finished prefill publish the KV handoff
    blob (``serve/kv/<req_id>``) plus a ``prefill-done`` result — the
    router then places the decode phase on a decode replica. Requests
    whose whole budget was the first token (or that failed) publish
    their terminal result directly.

    ``engine`` must be a ``PagedDecodeEngine(prefill_only=True)``;
    attach a :class:`FleetPrefixDirectory` first so every prefix this
    replica prefills becomes a fleet-wide hit."""
    from paddle_tpu import stats
    from paddle_tpu.observability import flight, runtime, trace
    from paddle_tpu.serving.router import ReplicaSession
    if not getattr(engine, "prefill_only", False):
        raise ValueError("serve_prefill_replica needs a "
                         "prefill_only=True engine")
    sess = ReplicaSession(
        store, rid,
        meta={"pid": os.getpid(), "slots": engine.S, "role": "prefill",
              "page": engine.page, "max_bucket": engine.buckets[-1]},
        transport=kv_transfer.maybe_transport(),
        engine=engine, fleet=getattr(engine, "fleet", None))
    sess.announce()
    open_reqs: Dict[str, object] = {}
    idle_since = time.monotonic()
    last_load = 0.0
    draining = False
    while True:
        sess.maintain()
        sess.pump_transport()
        now = time.monotonic()
        if now - last_load >= load_refresh_s:
            runtime.hbm_gauges()
            sess.heartbeat(load=replica_load(
                engine, "prefill", queued=engine.queued,
                queue_age_s=queue_age_s(engine=engine)),
                stats_export=stats.export())
            last_load = now
            draining = draining or sess.lifecycle() == "draining"
        else:
            sess.heartbeat()
        # mailbox BEFORE the drain/shutdown exit checks: a request
        # placed just before the drain decision must be consumed and
        # finished here, not stranded for the death sweep
        for msg in sess.pump_mailbox():
            if msg.get("id") in open_reqs:
                continue        # duplicate re-place of in-flight work
            try:
                req = engine.submit(
                    msg["prompt"],
                    max_new_tokens=msg["max_new_tokens"],
                    eos_id=msg["eos_id"],
                    deadline_s=msg.get("deadline_s"),
                    req_id=msg["id"])
            except ValueError as e:
                # infeasible request: fail AS A RESULT (router.serve_
                # replica's cascade rationale)
                sess.publish(msg["id"], {
                    "id": msg["id"], "tokens": [],
                    "status": "rejected-invalid", "error": str(e),
                    "replica": rid})
                continue
            open_reqs[msg["id"]] = req
        if draining and not open_reqs:
            # drain protocol: every accepted prefill finished (handed
            # off or terminal) — publish drained and exit
            sess.set_state("drained")
            sess.close()
            return
        if sess.shutdown_requested() and not open_reqs:
            sess.close()
            return
        if open_reqs:
            # in-flight prefill keeps computing through a partition —
            # degrade, never die (tentpole 2)
            engine.step()
            idle_since = time.monotonic()
        else:
            if sess.partitioned:
                # never idle-exit into a partition: the router may be
                # mid-failover and about to re-place work here
                idle_since = time.monotonic()
            if (max_idle_s is not None
                    and time.monotonic() - idle_since > max_idle_s):
                sess.close()
                return
            time.sleep(poll_s)
        for req_id, req in list(open_reqs.items()):
            if req.failed or req.done:
                # deadline/poison eviction, or a budget-1 request that
                # retired at harvest: terminal here, no decode phase
                sess.publish(req_id, {
                    "id": req_id, "tokens": list(req.tokens),
                    "status": ("failed" if req.failed else "done"),
                    "error": req.error, "replica": rid})
                del open_reqs[req_id]
            elif req.tokens:
                # prefill harvested: hand off to a decode replica
                t0 = time.perf_counter()
                meta, k, v = engine.detach_handoff(req)
                header, blob = kv_transfer.encode_kv_pages(
                    k, v, n_tokens=meta["n_tokens"], rid=req_id)
                # stamp the wire into the handoff meta: the decode
                # replica refuses to re-publish lossy-wire pages under
                # the original content digest (quantization error must
                # not compound across hops)
                header["handoff"] = dict(meta, wire=header["wire"])
                try:
                    kv_ep = kv_transfer.send_handoff(
                        sess.store, sess.transport, f"serve/kv/{req_id}",
                        header, blob)
                except resilience.StorePartitioned as e:
                    # blob publication lost to the partition: still
                    # emit prefill-done (buffered) — the decode fetch
                    # misses, handoff-failed re-places from scratch
                    sess.link.note_partition(e)
                    kv_ep = None
                trace.complete("serve/kv_publish", t0, rid=req_id,
                               bytes=len(blob))
                flight.record(req_id, "handoff-publish",
                              bytes=len(blob), wire=header["wire"],
                              plane=("socket" if kv_ep else "store"))
                sess.publish(req_id, {
                    "id": req_id, "tokens": [],
                    "status": "prefill-done", "error": None,
                    "kv_ep": kv_ep, "replica": rid},
                    terminal=False)
                del open_reqs[req_id]


def serve_decode_replica(store, rid: str, frontend,
                         fleet: Optional[FleetPrefixDirectory] = None,
                         poll_s: float = 0.02,
                         max_idle_s: Optional[float] = None,
                         load_refresh_s: float = 0.25):
    """One decode replica's loop: the PR 9 serve loop plus two
    disaggregation duties — ``handoff`` mailbox messages install
    transferred KV pages (``frontend.submit_handoff``), and the
    engine's fleet directory (attach before calling) turns any
    replica's warm prefix into a local suffix-only prefill. Plain
    ``req`` messages serve end-to-end exactly as symmetric replicas
    (the router's fallback when no prefill replica is alive)."""
    from paddle_tpu import stats
    from paddle_tpu.observability import flight, runtime, trace
    from paddle_tpu.serving.router import (ReplicaSession,
                                           _migrate_open_requests,
                                           drain_migrate_enabled)
    engine = frontend.engine
    sess = ReplicaSession(
        store, rid,
        meta={"pid": os.getpid(), "slots": engine.S, "role": "decode",
              "page": getattr(engine, "page", 0),
              "max_bucket": engine.buckets[-1]},
        transport=kv_transfer.maybe_transport(),
        engine=engine,
        fleet=fleet if fleet is not None
        else getattr(engine, "fleet", None))
    sess.announce()
    open_reqs: Dict[str, object] = {}
    idle_since = time.monotonic()
    last_load = 0.0
    draining = False
    while True:
        sess.maintain()
        sess.pump_transport()
        now = time.monotonic()
        if now - last_load >= load_refresh_s:
            runtime.hbm_gauges()
            sess.heartbeat(load=replica_load(
                engine, "decode",
                queued=len(frontend._queue) + engine.queued,
                queue_age_s=queue_age_s(frontend=frontend)),
                stats_export=stats.export())
            last_load = now
            draining = draining or sess.lifecycle() == "draining"
        else:
            sess.heartbeat()
        # mailbox BEFORE the drain/shutdown exit checks (rationale in
        # serve_prefill_replica above)
        for msg in sess.pump_mailbox():
            if msg.get("id") in open_reqs:
                continue        # duplicate re-place of in-flight work
            try:
                if msg.get("kind") == "handoff":
                    t0 = time.perf_counter()
                    kv_ep = msg.get("kv_ep")
                    try:
                        # bounded below dead_after-scale stalls, and
                        # heartbeat immediately after either way — a
                        # slow fetch must not get this healthy replica
                        # death-swept
                        header, blob = kv_transfer.fetch_handoff(
                            sess.store, sess.transport,
                            f"serve/kv/{msg['id']}", kv_ep=kv_ep,
                            timeout=2.0)
                    finally:
                        sess.heartbeat()
                    k, v = kv_transfer.decode_kv_pages(header, blob)
                    stats.observe("serve/kv_transfer_s",
                                  time.perf_counter() - t0)
                    trace.complete("serve/kv_transfer", t0,
                                   rid=msg["id"], bytes=len(blob))
                    flight.record(msg["id"], "handoff-fetch",
                                  bytes=len(blob),
                                  wire=header.get("wire"),
                                  plane=("socket" if kv_ep
                                         else "store"))
                    req = frontend.submit_handoff(
                        header["handoff"], k, v,
                        deadline_s=msg.get("deadline_s"),
                        req_id=msg["id"])
                    # sole consumer: reclaim the blob's memory
                    # (a redelivered handoff after this point fails
                    # the fetch -> handoff-failed -> router re-places
                    # from scratch; at-least-once keeps it safe)
                    kv_transfer.delete_handoff(
                        sess.store, sess.transport,
                        f"serve/kv/{msg['id']}", kv_ep=kv_ep,
                        nchunks=int(header.get("nchunks", 0)))
                else:
                    req = frontend.submit(
                        msg["prompt"],
                        max_new_tokens=msg["max_new_tokens"],
                        eos_id=msg.get("eos_id"),
                        deadline_s=msg.get("deadline_s"),
                        priority=msg.get("priority", 0),
                        req_id=msg["id"])
            except (TimeoutError, RuntimeError,
                    resilience.StorePartitioned) as e:
                # the handoff blob is missing/incomplete (prefill
                # replica died mid-transfer, store hiccup, partition
                # mid-fetch) or failed the wire integrity guards
                # (in-transit corruption — digest/scale-envelope
                # mismatch): publish the RETRYABLE status — the router
                # re-places the request from scratch (re-prefill /
                # re-decode), never surfaces this as a client-visible
                # rejection and never installs corrupted pages
                flight.record(msg["id"], "handoff-failed",
                              error=str(e))
                flight.dump(msg["id"], "handoff-failed")
                sess.publish(msg["id"], {
                    "id": msg["id"], "tokens": [],
                    "status": "handoff-failed", "error": str(e),
                    "replica": rid}, terminal=False)
                continue
            except ValueError as e:
                # infeasible request (bad geometry, over-budget):
                # terminal, but AS A RESULT, never the replica
                # (fail-loud per request, fleet stays up)
                if msg.get("kind") == "handoff":
                    # terminal failure consumes the blob too
                    kv_transfer.delete_handoff(
                        sess.store, sess.transport,
                        f"serve/kv/{msg['id']}",
                        kv_ep=msg.get("kv_ep"))
                sess.publish(msg["id"], {
                    "id": msg["id"], "tokens": [],
                    "status": "rejected-invalid", "error": str(e),
                    "replica": rid})
                continue
            open_reqs[msg["id"]] = req
        if draining and open_reqs and drain_migrate_enabled():
            # migrate in-flight decodes to surviving decode replicas
            # (mid-decode KV handoff, fp32 wire — byte-identical
            # streams) instead of finishing them here
            _migrate_open_requests(sess.store, rid, frontend, open_reqs,
                                   sess=sess)
        if draining and not open_reqs and not frontend.busy:
            # drain protocol: in-flight decodes finished, nothing
            # queued — publish drained and exit
            sess.set_state("drained")
            sess.close()
            return
        if sess.shutdown_requested() and not open_reqs \
                and not frontend.busy:
            sess.close()
            return
        if frontend.busy:
            # in-flight decode continues straight through a partition —
            # degrade, never die (tentpole 2)
            frontend.step()
            idle_since = time.monotonic()
        else:
            if sess.partitioned:
                # never idle-exit into a partition: the router may be
                # mid-failover and about to re-place work here
                idle_since = time.monotonic()
            if (max_idle_s is not None
                    and time.monotonic() - idle_since > max_idle_s):
                sess.close()
                return
            time.sleep(poll_s)
        for req_id, req in list(open_reqs.items()):
            if req.done:
                sess.publish(req_id, {
                    "id": req_id, "tokens": list(req.tokens),
                    "status": req.status, "error": req.error,
                    "replica": rid})
                del open_reqs[req_id]
