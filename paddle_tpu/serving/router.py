"""Multi-replica request router over TCPStore membership.

One serving *replica* = one process (spawned like any other worker:
``python -m paddle_tpu.distributed.launch --nproc_per_node 1 replica.py``
per replica, or any orchestrator) running a decode engine behind a
:class:`~paddle_tpu.serving.scheduler.FrontEnd` and
:func:`serve_replica`. The :class:`Router` lives in the API-facing
process, hosts the TCPStore control plane (``PT_SERVE_ROUTER_PORT``),
and moves requests with **least-outstanding-requests** placement.

Wire protocol (all JSON on the shared store; the store lives in the
router process, so results survive any replica's death):

- mailbox: router bumps ``serve/mbox_n/<rid>`` and writes
  ``serve/mbox/<rid>/<i>``; the replica consumes indices it hasn't
  seen. Append-only + monotonic counters — no delete/list ops needed.
- results: replica writes ``serve/done/<req_id>`` once the request is
  terminal (tokens or error); the router polls outstanding ids.
- membership: ``distributed/membership.ReplicaDirectory`` (announce +
  counter heartbeats). A replica whose heartbeat stalls is dead; every
  outstanding request assigned to it is **redistributed** to the
  least-loaded survivor (``serve/router_redistributed``). A request
  the dead replica already finished is not re-sent (its done key
  persists); a request it was mid-decode on re-executes elsewhere —
  at-least-once, first result wins, so no request id is ever lost.
"""

import json
import os
import time
from typing import Dict, List, Optional

from paddle_tpu.distributed.membership import ReplicaDirectory

__all__ = ["Router", "serve_replica", "router_port"]


def router_port() -> int:
    """The router control-plane TCPStore port
    (``PT_SERVE_ROUTER_PORT``)."""
    return int(os.environ.get("PT_SERVE_ROUTER_PORT", "8997"))


class Router:
    """Client-side router: owns the store, places requests, accounts
    for every request id until a result lands.

        router = Router()                  # hosts the store
        ... spawn replica processes (they connect back) ...
        rid = router.wait_replicas(2)
        req_id = router.submit(prompt, max_new_tokens=16)
        results = router.drain(timeout=60)  # req_id -> result dict
    """

    def __init__(self, store=None, host: str = "127.0.0.1",
                 port: Optional[int] = None, dead_after: float = 2.0):
        if store is None:
            from paddle_tpu import native
            store = native.TCPStore(
                host, port if port is not None else router_port(),
                is_master=True)
            self._owns_store = True
        else:
            self._owns_store = False
        self.store = store
        self.directory = ReplicaDirectory(store)
        self.dead_after = float(dead_after)
        self._seq = 0
        self._payload: Dict[str, dict] = {}      # req_id -> request json
        self._assigned: Dict[str, str] = {}      # req_id -> replica id
        self._outstanding: Dict[str, int] = {}   # rid -> open requests
        self.results: Dict[str, dict] = {}       # req_id -> result json
        self._done_cursor: Dict[str, int] = {}   # rid -> done idx read
        # replicas whose current death has already been swept — NOT a
        # permanent blacklist: a false-positive death (heartbeat stalled
        # by host load, then resumed) re-earns routing eligibility the
        # moment the counter progresses again; the extra redistribution
        # is harmless (at-least-once, first result wins)
        self._swept = set()

    # -- membership ---------------------------------------------------------

    def replicas(self) -> List[str]:
        """Alive replicas, least-outstanding first."""
        alive = [rid for rid in self.directory.members()
                 if self.directory.alive(rid, self.dead_after)]
        return sorted(alive,
                      key=lambda r: (self._outstanding.get(r, 0), r))

    def wait_replicas(self, n: int, timeout: float = 60.0) -> List[str]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            got = self.replicas()
            if len(got) >= n:
                return got
            time.sleep(0.05)
        raise TimeoutError(
            f"only {len(self.replicas())}/{n} replicas announced "
            f"within {timeout}s")

    # -- placement ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               priority: int = 0) -> str:
        from paddle_tpu import stats
        self._seq += 1
        req_id = f"rq-{self._seq:06d}"
        self._payload[req_id] = {
            "id": req_id, "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens), "eos_id": eos_id,
            "deadline_s": deadline_s, "priority": int(priority)}
        self._place(req_id)
        stats.add("serve/router_requests")
        return req_id

    def _place(self, req_id: str, wait_s: float = 2.0):
        alive = self.replicas()
        deadline = time.monotonic() + wait_s
        while not alive and time.monotonic() < deadline:
            # a transient liveness blip (or replicas still announcing)
            # must not fail a submit outright
            time.sleep(0.05)
            alive = self.replicas()
        if not alive:
            raise RuntimeError("no alive replicas to route to")
        rid = alive[0]                   # least outstanding
        i = self.store.add(f"serve/mbox_n/{rid}", 1)
        self.store.set(f"serve/mbox/{rid}/{i}",
                       json.dumps(self._payload[req_id]))
        self._assigned[req_id] = rid
        self._outstanding[rid] = self._outstanding.get(rid, 0) + 1
        from paddle_tpu import stats
        stats.set_value("serve/router_outstanding",
                        sum(self._outstanding.values()))

    # -- completion / fault handling ----------------------------------------

    def poll(self) -> Dict[str, dict]:
        """Collect newly landed results; returns the new ones. Cost is
        one counter read per KNOWN replica (not one blocking probe per
        outstanding request): each replica appends completions to its
        done index (see ``_publish``), and the router fetches only the
        entries beyond its per-replica cursor."""
        from paddle_tpu import native, stats
        fresh = {}
        for rid in self.directory.members():
            try:
                n = native.decode_counter(
                    self.store.get(f"serve/done_n/{rid}", timeout=0.02))
            except (TimeoutError, ValueError):
                continue
            cursor = self._done_cursor.get(rid, 0)
            while cursor < n:
                cursor += 1
                try:
                    req_id = self.store.get(
                        f"serve/done_idx/{rid}/{cursor}",
                        timeout=1.0).decode()
                    raw = self.store.get(f"serve/done/{req_id}",
                                         timeout=1.0)
                except TimeoutError:
                    cursor -= 1    # index mid-write; retry next poll
                    break
                if req_id in self.results or req_id not in self._payload:
                    continue       # duplicate completion / foreign key
                res = json.loads(raw)
                self.results[req_id] = res
                fresh[req_id] = res
                owner = self._assigned.get(req_id)
                if owner is not None:
                    self._outstanding[owner] = max(
                        0, self._outstanding.get(owner, 0) - 1)
            self._done_cursor[rid] = cursor
        if fresh:
            stats.set_value("serve/router_outstanding",
                            sum(self._outstanding.values()))
        return fresh

    def check_replicas(self):
        """Death sweep: redistribute every unfinished request assigned
        to a replica whose heartbeat stalled. Each death is swept once;
        a replica whose heartbeat resumes becomes routable again."""
        from paddle_tpu import stats
        for rid in list(self.directory.members()):
            if self.directory.alive(rid, self.dead_after):
                self._swept.discard(rid)
                continue
            if rid in self._swept:
                continue
            self._swept.add(rid)
            self._outstanding.pop(rid, None)
            orphans = [q for q, r in self._assigned.items()
                       if r == rid and q not in self.results]
            for req_id in orphans:
                self._place(req_id)
            if orphans:
                stats.add("serve/router_redistributed", len(orphans))

    def drain(self, timeout: float = 120.0) -> Dict[str, dict]:
        """Block until every submitted request has a result (or
        ``timeout``); death sweeps run throughout, so replicas may die
        mid-drain and the work still completes elsewhere."""
        deadline = time.monotonic() + timeout
        while len(self.results) < len(self._payload):
            if time.monotonic() > deadline:
                missing = sorted(set(self._payload) - set(self.results))
                raise TimeoutError(
                    f"{len(missing)} requests unfinished after "
                    f"{timeout}s: {missing[:8]}")
            self.poll()
            self.check_replicas()
        return dict(self.results)

    def shutdown(self):
        """Ask every replica loop to exit (they finish in-flight work
        first), then release the store if this router owns it."""
        try:
            self.store.set("serve/shutdown", "1")
        except Exception:
            pass

    def close(self):
        if self._owns_store:
            self.store.close()


def _publish(store, rid: str, req_id: str, result: dict):
    """Write one terminal result AND append it to the replica's done
    index (``serve/done_n/<rid>`` counter + ``serve/done_idx/<rid>/<i>``
    -> req_id) — the same counter idiom as the mailbox, so the router
    learns of completions from one counter read per replica instead of
    one blocking probe per outstanding request."""
    store.set(f"serve/done/{req_id}", json.dumps(result))
    i = store.add(f"serve/done_n/{rid}", 1)
    store.set(f"serve/done_idx/{rid}/{i}", req_id)


def serve_replica(store, rid: str, frontend, poll_s: float = 0.02,
                  max_idle_s: Optional[float] = None):
    """One replica's serve loop: announce, then consume the mailbox,
    pump the front-end, publish terminal results, heartbeat — until
    the shutdown key appears (or ``max_idle_s`` with nothing to do).

    ``frontend`` is a :class:`~paddle_tpu.serving.scheduler.FrontEnd`;
    all admission policy (deadline rejection, backfill, streaming)
    applies per-replica exactly as single-process serving.
    """
    directory = ReplicaDirectory(store)
    directory.announce(rid, {"pid": os.getpid(),
                             "slots": frontend.engine.S})
    seen = 0
    open_reqs: Dict[str, object] = {}
    idle_since = time.monotonic()
    while True:
        directory.heartbeat(rid)
        try:
            store.get("serve/shutdown", timeout=0.001)
            if not open_reqs and not frontend.busy:
                return
        except TimeoutError:
            pass
        # mailbox: consume any indices the router appended
        try:
            from paddle_tpu import native
            n = native.decode_counter(
                store.get(f"serve/mbox_n/{rid}", timeout=0.001))
        except (TimeoutError, ValueError):
            n = seen
        while seen < n:
            seen += 1
            msg = json.loads(store.get(f"serve/mbox/{rid}/{seen}",
                                       timeout=5.0))
            try:
                req = frontend.submit(
                    msg["prompt"], max_new_tokens=msg["max_new_tokens"],
                    eos_id=msg["eos_id"], deadline_s=msg["deadline_s"],
                    priority=msg["priority"], req_id=msg["id"])
            except ValueError as e:
                # an infeasible request (too long for this engine's
                # cache, empty prompt) must fail AS A RESULT, never
                # kill the replica: an uncaught raise here would die,
                # the router would redistribute the same poison payload
                # to the next replica, and one bad client request would
                # cascade through the whole fleet
                _publish(store, rid, msg["id"], {
                    "id": msg["id"], "tokens": [],
                    "status": "rejected-invalid", "error": str(e),
                    "replica": rid})
                continue
            open_reqs[msg["id"]] = req
        if frontend.busy:
            frontend.step()
            idle_since = time.monotonic()
        else:
            if (max_idle_s is not None
                    and time.monotonic() - idle_since > max_idle_s):
                return
            time.sleep(poll_s)
        for req_id, req in list(open_reqs.items()):
            if req.done:
                _publish(store, rid, req_id, {
                    "id": req_id, "tokens": list(req.tokens),
                    "status": req.status, "error": req.error,
                    "replica": rid})
                del open_reqs[req_id]
