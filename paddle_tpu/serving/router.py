"""Multi-replica request router over TCPStore membership.

One serving *replica* = one process (spawned like any other worker:
``python -m paddle_tpu.distributed.launch --nproc_per_node 1 replica.py``
per replica, or any orchestrator) running a decode engine behind a
:class:`~paddle_tpu.serving.scheduler.FrontEnd` and
:func:`serve_replica`. The :class:`Router` lives in the API-facing
process, hosts the TCPStore control plane (``PT_SERVE_ROUTER_PORT``),
and places requests **role- and load-aware**: a symmetric fleet keeps
least-outstanding-requests placement; a disaggregated fleet
(serving/disagg.py — replicas announcing ``role`` prefill/decode)
places the prefill phase by queue depth + bucket fit, moves each
``prefill-done`` handoff to the decode replica with the least
outstanding KV bytes / most free pages, consults the fleet prefix
directory BEFORE placement (full coverage skips the prefill tier
entirely), and degrades to symmetric placement when a role tier dies.
Load gauges ride the replicas' heartbeats — one store read per
replica per poll, never a per-request round trip.

Wire protocol (all JSON on the shared store; the store lives in the
router process, so results survive any replica's death):

- mailbox: router bumps ``serve/mbox_n/<rid>`` and writes
  ``serve/mbox/<rid>/<i>``; the replica consumes indices it hasn't
  seen. Append-only + monotonic counters — no delete/list ops needed.
- results: replica writes ``serve/done/<req_id>`` once the request is
  terminal (tokens or error); the router polls outstanding ids.
- membership: ``distributed/membership.ReplicaDirectory`` (announce +
  counter heartbeats). A replica whose heartbeat stalls is dead; every
  outstanding request assigned to it is **redistributed** to the
  least-loaded survivor (``serve/router_redistributed``). A request
  the dead replica already finished is not re-sent (its done key
  persists); a request it was mid-decode on re-executes elsewhere —
  at-least-once, first result wins, so no request id is ever lost.
"""

import json
import os
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from paddle_tpu.distributed import resilience
from paddle_tpu.distributed.liveness import ProgressJudge
from paddle_tpu.distributed.membership import ReplicaDirectory

__all__ = ["Router", "serve_replica", "router_port", "RouterLink",
           "ReplicaSession", "write_endpoint_file", "read_endpoint_file"]


def router_port() -> int:
    """The router control-plane TCPStore port
    (``PT_SERVE_ROUTER_PORT``)."""
    return int(os.environ.get("PT_SERVE_ROUTER_PORT", "8997"))


# ---------------------------------------------------------------------------
# Router failover plumbing (ISSUE 17, docs/fleet-ha.md)
# ---------------------------------------------------------------------------

_ROUTER_HB_KEY = "serve/router_hb"


def write_endpoint_file(path: str, host: str, port: int, gen: int,
                        pid: Optional[int] = None):
    """Atomically publish a router generation's store endpoint:
    ``{"host", "port", "gen", "pid"}`` via tmp-file + rename, so a
    replica polling the file never reads a torn record. Each new
    router generation writes ``gen = prior + 1``."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"host": host, "port": int(port), "gen": int(gen),
                   "pid": int(pid if pid is not None else os.getpid())},
                  f)
    os.replace(tmp, path)


def read_endpoint_file(path: Optional[str]) -> Optional[dict]:
    """The current endpoint record, or None (absent / torn / no path)."""
    if not path:
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class RouterLink:
    """A replica's view of the control plane ACROSS router generations.

    Wraps the store connection in a `resilience.GuardedStore` and runs
    the reconnect state machine (docs/fleet-ha.md):

    - router liveness is judged on the ``serve/router_hb`` counter the
      router bumps every poll, through the shared
      `liveness.ProgressJudge` — counter progress vs THIS process's
      monotonic clock, never a wall clock;
    - a failed/stuck store op flips the link ``partitioned``
      (`note_partition`); while partitioned the serve loops skip
      control-plane IO, buffer results, and keep decoding;
    - :meth:`check` (throttled) watches the endpoint file for a new
      router generation — on one it dials the fresh store and swaps it
      into the GuardedStore (``reconnected``); otherwise it probes the
      current store (``healed`` when a same-generation partition
      clears).
    """

    def __init__(self, store, endpoint_file: Optional[str] = None,
                 router_dead_after: float = 5.0):
        self.store = store if isinstance(store, resilience.GuardedStore) \
            else resilience.GuardedStore(store)
        self.endpoint_file = endpoint_file if endpoint_file is not None \
            else (os.environ.get("PT_ROUTER_ENDPOINT_FILE") or None)
        ep = read_endpoint_file(self.endpoint_file)
        self.generation = int(ep["gen"]) if ep else 0
        self.router_dead_after = float(router_dead_after)
        self.partitioned = False
        self._judge = ProgressJudge()
        self._last_check = 0.0

    def note_partition(self, err=None):
        """A store op just failed its whole retry budget: enter
        partition mode (flight-recorded once per transition)."""
        if not self.partitioned:
            from paddle_tpu import stats
            from paddle_tpu.observability import flight
            stats.add("serve/link_partitions")
            flight.record("link", "partition",
                          gen=self.generation,
                          error=(str(err) if err else None))
        self.partitioned = True

    def router_alive(self) -> bool:
        """True while the router's liveness counter keeps progressing
        (fed by :meth:`check`'s probes)."""
        if not self._judge.has("router"):
            return False
        stalled = self._judge.stalled_for("router")
        return stalled is not None and stalled <= self.router_dead_after

    def _fresh_endpoint(self) -> Optional[dict]:
        ep = read_endpoint_file(self.endpoint_file)
        if ep and int(ep.get("gen", 0)) > self.generation:
            return ep
        return None

    def _reconnect(self, ep: dict) -> str:
        from paddle_tpu import native, stats
        from paddle_tpu.observability import flight
        try:
            raw = native.TCPStore(ep["host"], int(ep["port"]),
                                  is_master=False)
        except (ConnectionError, OSError, RuntimeError):
            # endpoint published but not accepting yet (standby still
            # binding): stay in the current state, retry next check
            return "partitioned" if self.partitioned else "ok"
        self.store.swap(raw)
        self.generation = int(ep["gen"])
        self.partitioned = False
        self._judge.forget("router")
        stats.add("serve/link_reconnects")
        flight.record("link", "reconnect", gen=self.generation)
        return "reconnected"

    def check(self, min_interval_s: float = 0.25) -> str:
        """Advance the state machine (call once per loop iteration;
        internally throttled). Returns ``ok`` | ``partitioned`` |
        ``healed`` | ``reconnected`` — the two transition states fire
        exactly once so the caller can run its recovery actions."""
        now = time.monotonic()
        if now - self._last_check < min_interval_s:
            return "partitioned" if self.partitioned else "ok"
        self._last_check = now
        ep = self._fresh_endpoint()
        if ep is not None:
            st = self._reconnect(ep)
            if st == "reconnected":
                return st
        val = self.store.probe(_ROUTER_HB_KEY)
        if val is None:
            self.note_partition()
            return "partitioned"
        self._judge.update("router", val, now=now)
        if self.partitioned:
            from paddle_tpu import stats
            from paddle_tpu.observability import flight
            self.partitioned = False
            stats.add("serve/link_heals")
            flight.record("link", "heal", gen=self.generation)
            return "healed"
        return "ok"


class ReplicaSession:
    """Shared replica-side control-plane state for the three serve
    loops (`serve_replica` + the disagg role loops): guarded store IO
    that degrades instead of raising, the reconnect recovery actions,
    a bounded flight-recorded result buffer, and (optionally) this
    replica's socket KV transport endpoint.

    Partition contract (tentpole 2): every method that touches the
    store catches `resilience.StorePartitioned`, flips the link into
    partition mode, and returns something inert — a store blip costs
    missed heartbeats and buffered results, never replica suicide, and
    in-flight decode keeps stepping.

    Reconnect contract (tentpole 1): on a new router generation the
    session re-announces membership + lifecycle state, restarts the
    mailbox cursor at zero, re-publishes every RETAINED terminal result
    (the new router answers journal-recovered ids from them —
    first-result-wins), and re-publishes fleet prefix pages via the
    engine's ``fleet_republish`` hook.
    """

    RESULT_RETAIN = 256

    def __init__(self, store, rid: str, meta: dict, transport=None,
                 endpoint_file: Optional[str] = None, engine=None,
                 fleet=None):
        self.link = store if isinstance(store, RouterLink) \
            else RouterLink(store, endpoint_file=endpoint_file)
        self.store = self.link.store
        self.rid = rid
        self.meta = dict(meta)
        self.transport = transport
        if transport is not None:
            self.meta["kv_ep"] = list(transport.locator())
        self.engine = engine
        self.fleet = fleet
        if fleet is not None:
            # route the prefix directory through the SAME guarded store
            # client: its ops degrade on partition and automatically
            # follow swap() to the next router generation's endpoint
            fleet.store = self.store
        self.directory = ReplicaDirectory(self.store)
        self.seen = 0
        self.state = "up"               # local lifecycle mirror
        self._results: "OrderedDict[str, dict]" = OrderedDict()
        self._pending: Dict[str, dict] = {}   # buffered during partition

    @property
    def partitioned(self) -> bool:
        return self.link.partitioned

    def close(self):
        if self.transport is not None:
            self.transport.close()

    # -- control-plane IO (degrading) -----------------------------------

    def announce(self):
        self.directory.announce(self.rid, self.meta)

    def heartbeat(self, load: Optional[dict] = None,
                  stats_export: Optional[dict] = None):
        if self.link.partitioned:
            return
        try:
            self.directory.heartbeat(self.rid, load=load,
                                     stats=stats_export)
        except resilience.StorePartitioned as e:
            self.link.note_partition(e)

    def lifecycle(self) -> str:
        """The directory's lifecycle state for this replica (the local
        mirror while partitioned — a partition must not un-drain)."""
        if self.link.partitioned:
            return self.state
        try:
            s = self.directory.state(self.rid)
        except resilience.StorePartitioned as e:
            self.link.note_partition(e)
            return self.state
        if s != "up" or self.state == "up":
            self.state = s
        return self.state

    def set_state(self, state: str):
        self.state = state
        if self.link.partitioned:
            return
        try:
            self.directory.set_state(self.rid, state)
        except resilience.StorePartitioned as e:
            self.link.note_partition(e)

    def shutdown_requested(self) -> bool:
        if self.link.partitioned:
            return False
        try:
            return _shutdown_requested(self.store)
        except resilience.StorePartitioned as e:
            self.link.note_partition(e)
            return False

    def pump_mailbox(self) -> List[dict]:
        """Drain new mailbox messages. Duplicates of already-finished
        requests (a journal-recovered router re-placing at-least-once)
        are answered from the retained results instead of re-serving."""
        if self.link.partitioned:
            return []
        try:
            self.seen, msgs = _mailbox_pump(self.store, self.rid,
                                            self.seen)
        except resilience.StorePartitioned as e:
            self.link.note_partition(e)
            return []
        out = []
        for msg in msgs:
            req_id = msg.get("id")
            if req_id is not None and req_id in self._results:
                from paddle_tpu import stats
                stats.add("serve/dup_replays_answered")
                self.publish(req_id, self._results[req_id])
                continue
            out.append(msg)
        return out

    def pump_transport(self, budget: int = 8):
        if self.transport is not None:
            self.transport.pump(budget)

    # -- results (buffered through partitions) --------------------------

    def publish(self, req_id: str, result: dict, terminal: bool = True):
        """Publish a result; a partition buffers it (bounded,
        flight-recorded) for the flush on heal/reconnect. ``terminal``
        results are additionally RETAINED for duplicate-replay answers
        and re-publication to a new router generation."""
        if terminal:
            self._results[req_id] = result
            self._results.move_to_end(req_id)
            while len(self._results) > self.RESULT_RETAIN:
                old, _ = self._results.popitem(last=False)
                self._pending.pop(old, None)
        if self.link.partitioned:
            self._buffer(req_id, result)
            return
        try:
            _publish(self.store, self.rid, req_id, result)
            self._pending.pop(req_id, None)
        except resilience.StorePartitioned as e:
            self.link.note_partition(e)
            self._buffer(req_id, result)

    def _buffer(self, req_id: str, result: dict):
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        self._pending[req_id] = result
        stats.add("serve/results_buffered")
        flight.record(req_id, "result-buffered", replica=self.rid,
                      pending=len(self._pending))

    # -- the per-iteration state pump -----------------------------------

    def maintain(self) -> str:
        """Advance the link state machine and run the matching recovery
        actions; returns the link status for the loop's bookkeeping."""
        st = self.link.check()
        if st == "reconnected":
            self._recover(new_generation=True)
        elif st == "healed":
            self._recover(new_generation=False)
        return st

    def _recover(self, new_generation: bool):
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        try:
            if new_generation:
                # fresh store: re-announce membership + lifecycle,
                # restart the mailbox cursor, drop stale TRANSIENT
                # buffered statuses (the new router re-places journaled
                # outstanding work from scratch anyway), and republish
                # every retained terminal result + the prefix pages
                self.seen = 0
                self.directory.announce(self.rid, self.meta)
                if self.state != "up":
                    self.directory.set_state(self.rid, self.state)
                self._pending = {q: r for q, r in self._pending.items()
                                 if q in self._results}
                republish = dict(self._results)
                republish.update(self._pending)
                if self.fleet is not None:
                    try:
                        self.fleet.reset_published()
                    except Exception:
                        pass
                if self.engine is not None and hasattr(
                        self.engine, "fleet_republish"):
                    try:
                        self.engine.fleet_republish()
                    except Exception:
                        pass
            else:
                republish = dict(self._pending)
            n = 0
            for req_id, res in republish.items():
                _publish(self.store, self.rid, req_id, res)
                self._pending.pop(req_id, None)
                n += 1
            if n:
                stats.add("serve/results_republished", n)
            flight.record(self.rid, "link-recovered",
                          new_generation=new_generation, republished=n,
                          gen=self.link.generation)
        except resilience.StorePartitioned as e:
            self.link.note_partition(e)


class Router:
    """Client-side router: owns the store, places requests, accounts
    for every request id until a result lands.

        router = Router()                  # hosts the store
        ... spawn replica processes (they connect back) ...
        rid = router.wait_replicas(2)
        req_id = router.submit(prompt, max_new_tokens=16)
        results = router.drain(timeout=60)  # req_id -> result dict
    """

    def __init__(self, store=None, host: str = "127.0.0.1",
                 port: Optional[int] = None, dead_after: float = 2.0,
                 endpoint_file: Optional[str] = None,
                 journal=None):
        if store is None:
            from paddle_tpu import native
            store = native.TCPStore(
                host, port if port is not None else router_port(),
                is_master=True)
            self._owns_store = True
        else:
            self._owns_store = False
        # every router store op rides the ONE shared deadline-guarded
        # helper (GuardedStore): transient transport errors retry with
        # backoff, a dead store surfaces as StorePartitioned instead of
        # a raw socket error deep inside poll()
        self.store = resilience.GuardedStore(store)
        # failover plumbing (docs/fleet-ha.md): the endpoint file
        # advertises THIS generation's store to reconnecting replicas;
        # the journal makes the intake reconstructible (recover())
        self.endpoint_file = endpoint_file if endpoint_file is not None \
            else (os.environ.get("PT_ROUTER_ENDPOINT_FILE") or None)
        self.generation = 1
        if self.endpoint_file and self._owns_store:
            prior = read_endpoint_file(self.endpoint_file)
            self.generation = (int(prior["gen"]) if prior else 0) + 1
            write_endpoint_file(self.endpoint_file, host=host,
                                port=self.store.port,
                                gen=self.generation)
        self.journal = None
        if journal is not None:
            from paddle_tpu.serving.scheduler import RequestJournal
            self.journal = journal if isinstance(journal, RequestJournal) \
                else RequestJournal(journal)
        self.directory = ReplicaDirectory(self.store)
        self.dead_after = float(dead_after)
        self._seq = 0
        self._payload: Dict[str, dict] = {}      # req_id -> request json
        self._assigned: Dict[str, str] = {}      # req_id -> replica id
        self._outstanding: Dict[str, int] = {}   # rid -> open requests
        self.results: Dict[str, dict] = {}       # req_id -> result json
        self._done_cursor: Dict[str, int] = {}   # rid -> done idx read
        # replicas whose current death has already been swept — NOT a
        # permanent blacklist: a false-positive death (heartbeat stalled
        # by host load, then resumed) re-earns routing eligibility the
        # moment the counter progresses again; the extra redistribution
        # is harmless (at-least-once, first result wins)
        self._swept = set()
        # disaggregated serving state: which phase each request is in
        # ('serve' = whole request on one replica; 'prefill' = awaiting
        # a prefill replica's handoff; 'decode' = handoff placed on a
        # decode replica), plus the heartbeat-refreshed load gauges
        # (one store read per replica per poll — never per request)
        self._phase: Dict[str, str] = {}
        self._loads: Dict[str, dict] = {}
        self._loads_at = 0.0
        self._t_submit: Dict[str, float] = {}    # req_id -> submit time
        # perf_counter twin of _t_submit: the serve/route span (the
        # request's client-observed window on the stitched timeline)
        # needs the tracer's clock, monotonic deadline math keeps its own
        self._t_submit_pc: Dict[str, float] = {}
        # fleet telemetry plane (observability/fleet.FleetStats):
        # enable_fleet_stats attaches it; poll() pumps it at its own
        # refresh cadence
        self.fleet_stats = None
        self._fleet_refresh_s = 1.0
        self._fleet_at = 0.0
        # requests whose RE-placement failed transiently (no capable
        # replica alive at that instant): retried on every poll —
        # a liveness blip must degrade to a delay, never crash poll()
        self._unplaced: set = set()
        self._fleet = None                       # lazy directory client
        # drain protocol (fleet controller, docs/elastic.md): lifecycle
        # states read at most every _state_ttl_s per replica — a
        # submission burst reuses the cache instead of one store read
        # per replica per placement. mark_draining() updates the cache
        # in-process, so a controller sharing this router never races
        # its own drain decision against a stale cache entry.
        self._state_cache: Dict[str, tuple] = {}  # rid -> (state, t)
        self._state_ttl_s = 0.25
        # socket-plane handoff locators: req_id -> [host, port] of the
        # replica whose outbox holds the blob (None = store plane)
        self._kv_src: Dict[str, Optional[list]] = {}

    # -- membership ---------------------------------------------------------

    def _replica_state(self, rid: str) -> str:
        """Cached lifecycle state (drain protocol): ``up`` replicas are
        routable, ``draining``/``drained`` ones never receive a NEW
        placement (their in-flight work finishes where it is, or the
        death sweep redistributes it once they exit)."""
        now = time.monotonic()
        ent = self._state_cache.get(rid)
        if ent is None or now - ent[1] > self._state_ttl_s:
            ent = (self.directory.state(rid), now)
            self._state_cache[rid] = ent
        return ent[0]

    def mark_draining(self, rid: str):
        """Start draining ``rid``: publish the state AND update the
        local cache, so the very next placement in this process already
        excludes it (the fleet controller shares the router process —
        its drain decision must not race the cache TTL)."""
        self.directory.set_state(rid, "draining")
        self._state_cache[rid] = ("draining", time.monotonic())

    def replicas(self) -> List[str]:
        """Alive ROUTABLE replicas (draining ones excluded),
        least-outstanding first."""
        alive = [rid for rid in self.directory.members()
                 if self.directory.alive(rid, self.dead_after)
                 and self._replica_state(rid) == "up"]
        return sorted(alive,
                      key=lambda r: (self._outstanding.get(r, 0), r))

    def wait_replicas(self, n: int, timeout: float = 60.0) -> List[str]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            got = self.replicas()
            if len(got) >= n:
                return got
            time.sleep(0.05)
        raise TimeoutError(
            f"only {len(self.replicas())}/{n} replicas announced "
            f"within {timeout}s")

    # -- failover recovery --------------------------------------------------

    def recover(self) -> int:
        """Rebuild this (fresh) router generation's state from the
        request journal: journaled submits without a terminal result
        re-enter placement (parked in ``_unplaced`` until replicas
        reconnect — poll() retries them every call); journaled results
        are final (first-result-wins across generations — a replica
        re-publishing the same id later is deduped exactly like a
        same-generation duplicate). Returns the number of outstanding
        requests re-queued. Deadline budgets restart at recovery time:
        the journal records no clocks, and a stricter restart would
        time out work the failover itself delayed."""
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        from paddle_tpu.serving.scheduler import RequestJournal
        if self.journal is None:
            return 0
        payloads, results = RequestJournal.replay(self.journal.path)
        now = time.monotonic()
        pc = time.perf_counter()
        n_out = 0
        for req_id, payload in payloads.items():
            self._payload.setdefault(req_id, payload)
            try:
                self._seq = max(self._seq,
                                int(req_id.rsplit("-", 1)[1]))
            except (ValueError, IndexError):
                pass
            if req_id in results or req_id in self.results:
                continue
            n_out += 1
            self._t_submit.setdefault(req_id, now)
            self._t_submit_pc.setdefault(req_id, pc)
            self._phase[req_id] = "serve"   # re-place from scratch
            self._unplaced.add(req_id)
            flight.record(req_id, "journal-recover",
                          gen=self.generation)
        for req_id, res in results.items():
            self.results.setdefault(req_id, res)
        stats.add("serve/router_recovered", n_out)
        return n_out

    # -- placement ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               priority: int = 0) -> str:
        from paddle_tpu import stats
        self._seq += 1
        req_id = f"rq-{self._seq:06d}"
        self._payload[req_id] = {
            "id": req_id, "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens), "eos_id": eos_id,
            "deadline_s": deadline_s, "priority": int(priority)}
        # router-local submit time: every (re-)placement charges the
        # time already spent (queueing, prefill, transfer, re-routes)
        # against the request's deadline budget, matching same-replica
        # semantics where the clock starts once at submission
        self._t_submit[req_id] = time.monotonic()
        self._t_submit_pc[req_id] = time.perf_counter()
        from paddle_tpu.observability import flight
        flight.record(req_id, "submit", prompt=len(prompt),
                      budget=int(max_new_tokens), deadline_s=deadline_s)
        if self.journal is not None:
            # journal BEFORE placement: an accepted id must survive a
            # router SIGKILL even if the placement write never lands
            self.journal.append_submit(self._payload[req_id])
        self._place(req_id)
        stats.add("serve/router_requests")
        return req_id

    def _remaining_deadline(self, req_id: str):
        d = self._payload[req_id].get("deadline_s")
        if d is None:
            return None
        t0 = getattr(self, "_t_submit", {}).get(req_id)
        return d if t0 is None else d - (time.monotonic() - t0)

    def _request_msg(self, req_id: str) -> dict:
        return dict(self._payload[req_id], kind="req",
                    deadline_s=self._remaining_deadline(req_id))

    def _try_place(self, req_id: str):
        """RE-placement that survives a transient no-capable-replica
        window (poll's prefill-done/handoff-failed progression and the
        death sweep land here): on failure the request parks in
        ``_unplaced`` and every subsequent poll retries, so a
        heartbeat blip delays the request instead of crashing the
        router. ``submit()`` keeps the raising ``_place`` — the API
        edge should fail loudly when there is truly no fleet."""
        try:
            self._place(req_id, wait_s=0.0)
            self._unplaced.discard(req_id)
        except RuntimeError:
            self._unplaced.add(req_id)

    # -- role-aware placement ------------------------------------------------

    def _refresh_loads(self, min_interval_s: float = 0.2):
        """One load read per known replica (the gauges ride the
        heartbeat — membership.heartbeat(load=...)), throttled to the
        replicas' own refresh cadence: a burst of submissions reuses
        the cached gauges plus this router's in-flight counts —
        requests never trigger their own store round trips."""
        now = time.monotonic()
        if now - self._loads_at < min_interval_s and self._loads:
            return
        self._loads_at = now
        for rid in self.directory.members():
            load = self.directory.load(rid)
            if load is not None:
                self._loads[rid] = load

    def _alive_meta(self) -> Dict[str, dict]:
        return {rid: m for rid, m in self.directory.members().items()
                if self.directory.alive(rid, self.dead_after)
                and self._replica_state(rid) == "up"}

    def _fleet_covered(self, prompt, page: int) -> int:
        """Pre-placement directory consult: how many leading FULL pages
        of ``prompt`` the fleet prefix directory already holds."""
        if not page:
            return 0
        from paddle_tpu.inference.prefix_cache import chain_digests
        from paddle_tpu.serving.disagg import (FleetPrefixDirectory,
                                               fleet_enabled)
        if not fleet_enabled():
            return 0
        if self._fleet is None:
            self._fleet = FleetPrefixDirectory(self.store, "router")
        chain = chain_digests(prompt, page)
        return self._fleet.covered(chain) * page

    def _pick_prefill(self, alive: Dict[str, dict], prompt_len: int):
        """Prefill placement: bucket fit first (the replica's largest
        bucket must cover the prompt), then least queue depth (the
        heartbeat gauge plus this router's own in-flight count)."""
        fits = [rid for rid, m in alive.items()
                if m.get("role") == "prefill"
                and prompt_len <= m.get("max_bucket", 0)]
        return min(fits, key=lambda r: (
            self._loads.get(r, {}).get("queued", 0)
            + self._outstanding.get(r, 0), r), default=None)

    def _pick_decode(self, alive: Dict[str, dict]):
        """Decode placement: least outstanding KV bytes, most free
        pages (the memory-bound axis), router in-flight as tiebreak.
        ``both``-role replicas qualify — a symmetric fleet must be
        able to receive a draining peer's mid-decode handoffs."""
        ds = [rid for rid, m in alive.items()
              if m.get("role", "both") in ("decode", "both")]
        return min(ds, key=lambda r: (
            self._loads.get(r, {}).get("kv_bytes", 0),
            -self._loads.get(r, {}).get("free_pages", 0),
            self._outstanding.get(r, 0), r), default=None)

    def _send(self, rid: str, req_id: str, msg: dict):
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        i = self.store.add(f"serve/mbox_n/{rid}", 1)
        self.store.set(f"serve/mbox/{rid}/{i}", json.dumps(msg))
        self._assigned[req_id] = rid
        self._outstanding[rid] = self._outstanding.get(rid, 0) + 1
        flight.record(req_id, "place", replica=rid,
                      phase=self._phase.get(req_id, "serve"),
                      kind=msg.get("kind", "req"))
        stats.set_value("serve/router_outstanding",
                        sum(self._outstanding.values()))

    def _place(self, req_id: str, wait_s: float = 2.0):
        """Phase-aware placement. A symmetric fleet (no prefill-role
        replicas) keeps PR 9's least-outstanding policy verbatim. A
        disaggregated fleet places the prefill phase on a prefill
        replica by queue depth + bucket fit, UNLESS the fleet prefix
        directory already covers the prompt's full pages — then
        prefill is skipped fleet-wide and the request goes straight to
        a decode replica (which suffix-prefills locally from fleet
        pages). Decode-phase placement (after ``prefill-done``) goes by
        outstanding KV bytes + free pages. When a needed role has no
        alive replica, the request falls back to whole-request serving
        on whatever is alive — a dead prefill tier degrades to
        symmetric serving, never to an outage."""
        deadline = time.monotonic() + wait_s
        alive = self._alive_meta()
        while not alive and time.monotonic() < deadline:
            # a transient liveness blip (or replicas still announcing)
            # must not fail a submit outright
            time.sleep(0.05)
            alive = self._alive_meta()
        if not alive:
            raise RuntimeError("no alive replicas to route to")
        payload = self._payload[req_id]
        phase = self._phase.get(req_id)
        if phase in ("prefill", "serve"):
            # re-placement (death sweep): a not-yet-handed-off request
            # restarts from scratch wherever capacity is — a dead
            # prefill replica's work re-enters the prefill pool, or
            # degrades to whole-request serving below
            phase = None
        roles = {m.get("role", "both") for m in alive.values()}
        if phase == "decode":
            rid = self._pick_decode(alive)
            if rid is not None:
                self._send(rid, req_id, {
                    "kind": "handoff", "id": req_id,
                    "deadline_s": self._remaining_deadline(req_id),
                    # socket-plane locator: where the sender's outbox
                    # holds the blob (None = chunked store fetch)
                    "kv_ep": self._kv_src.get(req_id)})
                return
            # no decode replica alive: fall through to whole-request
            # placement (the handoff blob is abandoned; at-least-once)
            phase = None
        if phase is None and "prefill" in roles and "decode" in roles:
            self._refresh_loads()
            page = max((m.get("page", 0) for m in alive.values()
                        if m.get("role") == "decode"), default=0)
            covered = self._fleet_covered(payload["prompt"], page)
            n = len(payload["prompt"])
            if covered and n - covered < (page or n):
                # every full page is fleet-warm: skip prefill entirely
                rid = self._pick_decode(alive)
                if rid is not None:
                    from paddle_tpu import stats
                    stats.add("serve/router_prefill_skipped")
                    self._phase[req_id] = "serve"
                    self._send(rid, req_id, self._request_msg(req_id))
                    return
            rid = self._pick_prefill(alive, n)
            if rid is not None:
                self._phase[req_id] = "prefill"
                self._send(rid, req_id, self._request_msg(req_id))
                return
            # no fitting/alive prefill replica: serve whole on decode
            rid = self._pick_decode(alive)
            if rid is not None:
                self._phase[req_id] = "serve"
                self._send(rid, req_id, self._request_msg(req_id))
                return
        # symmetric fleet (or role fallback): least outstanding among
        # replicas that can actually SERVE a whole request — a
        # prefill-only replica would prefill and publish another
        # handoff forever (livelock) if the decode tier is down
        servers = [r for r in alive
                   if alive[r].get("role", "both") != "prefill"]
        if not servers:
            raise RuntimeError(
                "no decode-capable replica alive (prefill-only fleet)")
        self._phase[req_id] = "serve"
        rid = min(servers,
                  key=lambda r: (self._outstanding.get(r, 0), r))
        self._send(rid, req_id, self._request_msg(req_id))

    # -- completion / fault handling ----------------------------------------

    def poll(self) -> Dict[str, dict]:
        """Collect newly landed results; returns the new ones. Cost is
        one counter read per KNOWN replica (not one blocking probe per
        outstanding request): each replica appends completions to its
        done index (see ``_publish``), and the router fetches only the
        entries beyond its per-replica cursor."""
        from paddle_tpu import native, stats
        from paddle_tpu.observability import flight, trace
        from paddle_tpu.testing import faults
        # chaos hook: PT_FAULTS="router.die:kill:after=N" drops the
        # coordinator mid-traffic — failover (endpoint file + journal
        # recovery) must preserve every request id
        faults.fire("router.die")
        # router-liveness counter: replicas' RouterLinks judge progress
        # on this (ProgressJudge) to tell a dead router from a slow one
        self.store.add(_ROUTER_HB_KEY, 1)
        fresh = {}
        for req_id in list(self._unplaced):
            if req_id not in self.results:
                self._try_place(req_id)
        for rid in self.directory.members():
            try:
                n = native.decode_counter(
                    self.store.get(f"serve/done_n/{rid}", timeout=0.02))
            except (TimeoutError, ValueError):
                continue
            cursor = self._done_cursor.get(rid, 0)
            while cursor < n:
                cursor += 1
                try:
                    req_id = self.store.get(
                        f"serve/done_idx/{rid}/{cursor}",
                        timeout=1.0).decode()
                    raw = self.store.get(f"serve/done/{req_id}",
                                         timeout=1.0)
                except TimeoutError:
                    cursor -= 1    # index mid-write; retry next poll
                    break
                if req_id in self.results or req_id not in self._payload:
                    continue       # duplicate completion / foreign key
                res = json.loads(raw)
                if res.get("status") == "handoff-failed":
                    # retryable: the decode replica could not fetch the
                    # handoff blob (prefill replica died mid-transfer).
                    # Re-place from scratch — the request re-enters the
                    # prefill pool (or whole-request serving)
                    owner = self._assigned.get(req_id)
                    if owner is not None:
                        self._outstanding[owner] = max(
                            0, self._outstanding.get(owner, 0) - 1)
                    flight.record(req_id, "handoff-failed",
                                  replica=res.get("replica"),
                                  error=res.get("error"))
                    flight.dump(req_id, "handoff-failed")
                    self._phase[req_id] = "serve"
                    self._kv_src.pop(req_id, None)   # blob unusable
                    self._try_place(req_id)
                    stats.add("serve/router_handoff_retries")
                    continue
                if res.get("status") == "migrated":
                    # NOT terminal: a draining replica handed this
                    # request off mid-flight. kv=True carries device
                    # state (place the handoff blob on a decode-capable
                    # survivor); kv=False was still queued there
                    # (re-place from scratch). Either way the id stays
                    # accounted until a real result lands.
                    owner = self._assigned.get(req_id)
                    if owner is not None:
                        self._outstanding[owner] = max(
                            0, self._outstanding.get(owner, 0) - 1)
                    flight.record(req_id, "migrate",
                                  replica=res.get("replica"),
                                  kv=bool(res.get("kv")))
                    self._phase[req_id] = (
                        "decode" if res.get("kv") else "serve")
                    if res.get("kv"):
                        self._kv_src[req_id] = res.get("kv_ep")
                    self._refresh_loads()
                    self._try_place(req_id)
                    stats.add("serve/router_migrated")
                    continue
                if res.get("status") == "prefill-done":
                    # NOT terminal: the prefill replica published the
                    # KV handoff blob — place the decode phase on a
                    # decode replica (by outstanding KV bytes + free
                    # pages). Duplicate prefill-done entries (a death
                    # sweep re-ran the prefill elsewhere) re-place the
                    # decode phase; at-least-once, first final result
                    # wins.
                    owner = self._assigned.get(req_id)
                    if owner is not None:
                        self._outstanding[owner] = max(
                            0, self._outstanding.get(owner, 0) - 1)
                    self._phase[req_id] = "decode"
                    self._kv_src[req_id] = res.get("kv_ep")
                    flight.record(req_id, "prefill-done",
                                  replica=res.get("replica"))
                    self._refresh_loads()
                    self._try_place(req_id)
                    stats.add("serve/router_prefill_handoffs")
                    continue
                self.results[req_id] = res
                fresh[req_id] = res
                self._kv_src.pop(req_id, None)
                if self.journal is not None:
                    self.journal.append_result(req_id, res)
                # close the request's client-observed window on the
                # stitched timeline (submit → result pickup)
                t0 = self._t_submit_pc.pop(req_id, None)
                if t0 is not None:
                    trace.complete("serve/route", t0, rid=req_id,
                                   status=res.get("status"),
                                   replica=res.get("replica"))
                flight.record(req_id, "result",
                              status=res.get("status"),
                              replica=res.get("replica"))
                owner = self._assigned.get(req_id)
                if owner is not None:
                    self._outstanding[owner] = max(
                        0, self._outstanding.get(owner, 0) - 1)
            self._done_cursor[rid] = cursor
        if fresh:
            stats.set_value("serve/router_outstanding",
                            sum(self._outstanding.values()))
        if self.fleet_stats is not None:
            now = time.monotonic()
            if now - self._fleet_at >= self._fleet_refresh_s:
                self._fleet_at = now
                self.fleet_stats.poll()
        return fresh

    def enable_fleet_stats(self, refresh_s: float = 1.0,
                           stall_after_s: float = 5.0,
                           jsonl_path: Optional[str] = None,
                           statsz_port: Optional[int] = None):
        """Attach the fleet telemetry plane (observability/fleet):
        :meth:`poll` then refreshes per-replica exports, runs the
        SLO/anomaly watch, and appends JSONL telemetry every
        ``refresh_s``. ``statsz_port`` additionally serves the merged
        fleet /statsz (0 = ephemeral; read ``.port`` off the returned
        FleetStats' server). Returns the FleetStats."""
        from paddle_tpu.observability.fleet import FleetStats
        self.fleet_stats = FleetStats(
            self.directory, dead_after=self.dead_after,
            stall_after_s=stall_after_s, jsonl_path=jsonl_path)
        self._fleet_refresh_s = float(refresh_s)
        if statsz_port is not None:
            self.fleet_stats.serve_statsz(statsz_port)
        return self.fleet_stats

    def check_replicas(self):
        """Death sweep: redistribute every unfinished request assigned
        to a replica whose heartbeat stalled. Each death is swept once;
        a replica whose heartbeat resumes becomes routable again."""
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        for rid in list(self.directory.members()):
            if self.directory.alive(rid, self.dead_after):
                self._swept.discard(rid)
                continue
            if rid in self._swept:
                continue
            self._swept.add(rid)
            self._outstanding.pop(rid, None)
            # a controller-churned fleet mints a fresh rid per spawn:
            # drop the dead replica's lifecycle-cache entry with the
            # other per-rid state or the cache grows forever
            self._state_cache.pop(rid, None)
            orphans = [q for q, r in self._assigned.items()
                       if r == rid and q not in self.results]
            for req_id in orphans:
                flight.record(req_id, "redistribute", dead=rid)
                self._try_place(req_id)
            if orphans:
                stats.add("serve/router_redistributed", len(orphans))

    def drain(self, timeout: float = 120.0) -> Dict[str, dict]:
        """Block until every submitted request has a result (or
        ``timeout``); death sweeps run throughout, so replicas may die
        mid-drain and the work still completes elsewhere."""
        deadline = time.monotonic() + timeout
        while len(self.results) < len(self._payload):
            if time.monotonic() > deadline:
                missing = sorted(set(self._payload) - set(self.results))
                raise TimeoutError(
                    f"{len(missing)} requests unfinished after "
                    f"{timeout}s: {missing[:8]}")
            self.poll()
            self.check_replicas()
        return dict(self.results)

    def shutdown(self):
        """Ask every replica loop to exit (they finish in-flight work
        first), then release the store if this router owns it."""
        try:
            self.store.set("serve/shutdown", "1")
        except Exception:
            pass

    def close(self):
        if self.journal is not None:
            self.journal.close()
        if self._owns_store:
            self.store.close()


def _mailbox_pump(store, rid: str, seen: int):
    """Drain new mailbox indices for ``rid`` (counter + indexed keys —
    the ONE mailbox idiom every serve loop shares, including the
    role-split loops in serving/disagg.py). Returns
    ``(new_seen, [message dicts])``."""
    from paddle_tpu import native
    try:
        n = native.decode_counter(
            store.get(f"serve/mbox_n/{rid}", timeout=0.001))
    except (TimeoutError, ValueError):
        n = seen
    out = []
    while seen < n:
        seen += 1
        out.append(json.loads(store.get(f"serve/mbox/{rid}/{seen}",
                                        timeout=5.0)))
    return seen, out


def _shutdown_requested(store) -> bool:
    try:
        store.get("serve/shutdown", timeout=0.001)
        return True
    except TimeoutError:
        return False


def _publish(store, rid: str, req_id: str, result: dict):
    """Write one terminal result AND append it to the replica's done
    index (``serve/done_n/<rid>`` counter + ``serve/done_idx/<rid>/<i>``
    -> req_id) — the same counter idiom as the mailbox, so the router
    learns of completions from one counter read per replica instead of
    one blocking probe per outstanding request."""
    store.set(f"serve/done/{req_id}", json.dumps(result))
    i = store.add(f"serve/done_n/{rid}", 1)
    store.set(f"serve/done_idx/{rid}/{i}", req_id)


def drain_migrate_enabled() -> bool:
    """``PT_DRAIN_MIGRATE`` (default on): a draining replica migrates
    its in-flight decode requests to survivors mid-decode instead of
    finishing them in place — drain latency drops from longest-request
    to migration time. 0 restores the PR 14 finish-in-place drain."""
    return os.environ.get("PT_DRAIN_MIGRATE", "1") != "0"


def _migrate_open_requests(store, rid: str, frontend, open_reqs,
                           sess: Optional[ReplicaSession] = None):
    """Drain migration, sending half (docs/elastic.md): try to move
    every open request off this draining replica. Slot-holding
    requests leave with their KV rows + token history over the fp32
    wire (the ``serve/kv/<req_id>`` blob on the configured data plane —
    the survivor continues bit-for-bit); still-queued ones leave as
    bare ids (the router re-places them from scratch). Either way the
    sender publishes a NON-terminal ``migrated`` result the router
    turns into the next placement, so no request id is ever lost.

    With a `ReplicaSession` the blob rides the socket KV plane (the
    ``migrated`` result carries the sender's ``kv_ep`` locator) and
    the result publication degrades through partitions; without one
    (in-process tests) the PR 16 store path is unchanged.

    Per-request fallback: any failure — the ``drain.migrate`` chaos
    site firing, detach refusing (mid-prefill, completed during the
    pipeline drain), blob publication dying — leaves THAT request
    finishing in place (``serve/drain_migrate_failed``) while the rest
    still migrate. Requests that could not move yet are retried every
    loop iteration until the replica is empty."""
    import time as _time
    from paddle_tpu import stats
    from paddle_tpu.observability import flight, trace
    from paddle_tpu.serving import kv_transfer
    from paddle_tpu.testing import faults
    transport = sess.transport if sess is not None else None
    for req_id, sreq in list(open_reqs.items()):
        if sreq.done:
            continue                 # the generic publisher owns it
        try:
            faults.fire("drain.migrate")
            got = frontend.detach_migrate(sreq)
        except Exception as e:
            # injected fault / detach failure: this request finishes
            # in place — loudly, never silently corrupted
            stats.add("serve/drain_migrate_failed")
            flight.record(req_id, "migrate-failed", replica=rid,
                          error=str(e))
            continue
        if got is None:
            continue                 # can't move yet; retried next loop
        kv_ep = None
        try:
            if got["kv"]:
                meta = got["meta"]
                t0 = _time.perf_counter()
                # fp32 wire: migration must be bit-identical — a lossy
                # wire would fork the stream at the migration boundary
                header, blob = kv_transfer.encode_kv_pages(
                    got["k"], got["v"], n_tokens=meta["n_tokens"],
                    wire="fp32", rid=req_id)
                header["handoff"] = dict(meta, wire=header["wire"])
                if faults.enabled():
                    # in-transit corruption point (chaos: bitflip /
                    # truncate) — the receiver's digest check must turn
                    # it into handoff-failed, never installed state
                    blob = faults.transform("drain.migrate", blob)
                kv_ep = kv_transfer.send_handoff(
                    store, transport, f"serve/kv/{req_id}", header, blob)
                trace.complete("serve/kv_publish", t0, rid=req_id,
                               bytes=len(blob))
                flight.record(req_id, "migrate-publish",
                              bytes=len(blob),
                              generated=len(meta["tokens"]),
                              plane=("socket" if kv_ep else "store"))
        except Exception as e:
            # the request is already detached; a publish failure is
            # still safe — the router's handoff-failed / re-place path
            # re-executes it from scratch once the fetch times out
            stats.add("serve/drain_migrate_failed")
            flight.record(req_id, "migrate-failed", replica=rid,
                          error=str(e))
        stats.add("serve/drain_migrated")
        flight.record("fleet", "migrate", request=req_id, replica=rid,
                      kv=bool(got["kv"]))
        result = {"id": req_id, "tokens": [], "status": "migrated",
                  "kv": bool(got["kv"]), "kv_ep": kv_ep,
                  "error": None, "replica": rid}
        if sess is not None:
            sess.publish(req_id, result, terminal=False)
        else:
            _publish(store, rid, req_id, result)
        del open_reqs[req_id]


def _install_handoff(store, rid: str, directory, frontend, msg,
                     sess: Optional[ReplicaSession] = None):
    """Receiving half of a KV handoff on a symmetric replica (the
    disagg decode loop keeps its own copy): fetch the blob from
    whichever data plane the message's ``kv_ep`` locator names (socket
    outbox / chunked store), decode the pages, admit via
    ``frontend.submit_handoff``. Publishes ``handoff-failed``
    (retryable — the router re-places from scratch) on a
    missing/corrupt blob, ``rejected-invalid`` (terminal) on an
    infeasible request. Returns the admitted request or None."""
    import time as _time
    from paddle_tpu import stats
    from paddle_tpu.observability import flight, trace
    from paddle_tpu.serving import kv_transfer
    req_id = msg["id"]
    kv_ep = msg.get("kv_ep")
    transport = sess.transport if sess is not None else None
    try:
        t0 = _time.perf_counter()
        try:
            # bounded below dead_after-scale stalls, heartbeat after
            # either way — a slow fetch must not get this healthy
            # replica death-swept
            header, blob = kv_transfer.fetch_handoff(
                store, transport, f"serve/kv/{req_id}", kv_ep=kv_ep,
                timeout=2.0)
        finally:
            if sess is not None:
                sess.heartbeat()
            else:
                directory.heartbeat(rid)
        k, v = kv_transfer.decode_kv_pages(header, blob)
        stats.observe("serve/kv_transfer_s",
                      _time.perf_counter() - t0)
        trace.complete("serve/kv_transfer", t0, rid=req_id,
                       bytes=len(blob))
        flight.record(req_id, "handoff-fetch", bytes=len(blob),
                      wire=header.get("wire"),
                      plane=("socket" if kv_ep else "store"))
        req = frontend.submit_handoff(
            header["handoff"], k, v, deadline_s=msg.get("deadline_s"),
            req_id=req_id)
        kv_transfer.delete_handoff(store, transport,
                                   f"serve/kv/{req_id}", kv_ep=kv_ep,
                                   nchunks=int(header.get("nchunks", 0)))
        return req
    except (TimeoutError, ValueError, RuntimeError,
            resilience.StorePartitioned) as e:
        # missing blob, digest mismatch (in-transit corruption), a
        # partitioned store mid-fetch, or an infeasible install:
        # RETRYABLE — the router re-places the request from scratch;
        # at-least-once keeps the id accounted
        flight.record(req_id, "handoff-failed", error=str(e))
        flight.dump(req_id, "handoff-failed")
        result = {"id": req_id, "tokens": [], "status": "handoff-failed",
                  "error": str(e), "replica": rid}
        if sess is not None:
            sess.publish(req_id, result, terminal=False)
        else:
            _publish(store, rid, req_id, result)
        return None


def serve_replica(store, rid: str, frontend, poll_s: float = 0.02,
                  max_idle_s: Optional[float] = None,
                  load_refresh_s: float = 0.25):
    """One replica's serve loop: announce, then consume the mailbox,
    pump the front-end, publish terminal results, heartbeat — until
    the shutdown key appears (or ``max_idle_s`` with nothing to do).

    ``frontend`` is a :class:`~paddle_tpu.serving.scheduler.FrontEnd`;
    all admission policy (deadline rejection, backfill, streaming)
    applies per-replica exactly as single-process serving. Every
    ``load_refresh_s`` the heartbeat also carries the load gauges AND
    a full ``stats.export()`` snapshot — the fleet telemetry plane's
    feed (observability/fleet.FleetStats) — plus the live/peak HBM
    gauges on backends that expose them.

    Drain protocol (docs/elastic.md): once the directory state flips
    to ``draining`` (the fleet controller retiring this replica), the
    router has already stopped placing new work here — this loop keeps
    consuming any mailbox entries placed BEFORE the drain, then (with
    ``PT_DRAIN_MIGRATE``, default on) MIGRATES its in-flight requests
    to survivors mid-decode (:func:`_migrate_open_requests` — KV rows
    + token history over the fp32 wire, streams byte-identical),
    finishes in place whatever could not move, publishes ``drained``,
    and exits — drain latency is bounded by migration time, not the
    longest in-flight request.
    """
    from paddle_tpu import stats
    from paddle_tpu.observability import runtime
    from paddle_tpu.serving import kv_transfer
    from paddle_tpu.serving.disagg import queue_age_s, replica_load
    from paddle_tpu.testing import faults
    sess = ReplicaSession(
        store, rid,
        meta={"pid": os.getpid(), "slots": frontend.engine.S},
        transport=kv_transfer.maybe_transport(),
        engine=frontend.engine,
        fleet=getattr(frontend.engine, "fleet", None))
    sess.announce()
    open_reqs: Dict[str, object] = {}
    idle_since = time.monotonic()
    last_load = 0.0
    draining = False
    while True:
        # chaos hook (testing/faults.py): PT_FAULTS="serve.loop:kill:
        # after=N" SIGKILL-equivalently drops this replica mid-serve —
        # the fleet controller must heal it with zero request-id loss
        faults.fire("serve.loop")
        # partition / failover state machine: probes the router's
        # liveness counter, watches the endpoint file, and on a new
        # router generation re-announces + republishes buffered results
        sess.maintain()
        sess.pump_transport()
        now = time.monotonic()
        if now - last_load >= load_refresh_s:
            runtime.hbm_gauges()
            sess.heartbeat(load=replica_load(
                frontend.engine, "both",
                queued=len(frontend._queue) + frontend.engine.queued,
                queue_age_s=queue_age_s(frontend=frontend)),
                stats_export=stats.export())
            last_load = now
            draining = draining or sess.lifecycle() == "draining"
        else:
            sess.heartbeat()
        # mailbox BEFORE the drain/shutdown exit checks: a request the
        # router placed just before the drain decision may still sit
        # unconsumed here — exiting first would strand it until the
        # death sweep, a dead_after-sized latency cliff on a request
        # the drain protocol promises to finish
        for msg in sess.pump_mailbox():
            if msg.get("id") in open_reqs:
                continue        # duplicate re-place of in-flight work
            if msg.get("kind") == "handoff":
                # a draining peer's mid-decode migration landing here
                # (the router picked this replica as the survivor)
                req = _install_handoff(sess.store, rid, sess.directory,
                                       frontend, msg, sess=sess)
                if req is not None:
                    open_reqs[msg["id"]] = req
                continue
            try:
                req = frontend.submit(
                    msg["prompt"], max_new_tokens=msg["max_new_tokens"],
                    eos_id=msg["eos_id"], deadline_s=msg["deadline_s"],
                    priority=msg["priority"], req_id=msg["id"])
            except ValueError as e:
                # an infeasible request (too long for this engine's
                # cache, empty prompt) must fail AS A RESULT, never
                # kill the replica: an uncaught raise here would die,
                # the router would redistribute the same poison payload
                # to the next replica, and one bad client request would
                # cascade through the whole fleet
                sess.publish(msg["id"], {
                    "id": msg["id"], "tokens": [],
                    "status": "rejected-invalid", "error": str(e),
                    "replica": rid})
                continue
            open_reqs[msg["id"]] = req
        if draining and open_reqs and drain_migrate_enabled():
            # migrate in-flight work to survivors instead of finishing
            # it here: drain latency becomes migration time, not
            # longest-request time (per-request fallback inside)
            _migrate_open_requests(sess.store, rid, frontend, open_reqs,
                                   sess=sess)
        if draining and not open_reqs and not frontend.busy:
            sess.set_state("drained")
            sess.close()
            return
        if sess.shutdown_requested() and not open_reqs \
                and not frontend.busy:
            sess.close()
            return
        if frontend.busy:
            # in-flight decode continues straight through a partition —
            # the whole point of degrading instead of dying
            frontend.step()
            idle_since = time.monotonic()
        else:
            if sess.partitioned:
                # never idle-exit into a partition: the router may be
                # mid-failover and about to re-place work here
                idle_since = time.monotonic()
            if (max_idle_s is not None
                    and time.monotonic() - idle_since > max_idle_s):
                sess.close()
                return
            time.sleep(poll_s)
        for req_id, req in list(open_reqs.items()):
            if req.done:
                sess.publish(req_id, {
                    "id": req_id, "tokens": list(req.tokens),
                    "status": req.status, "error": req.error,
                    "replica": rid})
                del open_reqs[req_id]
