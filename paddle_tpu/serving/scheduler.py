"""Continuous-batching serving front-end: the service layer over the
decode engines (ROADMAP open item 2, ISSUE 10).

The engines (``inference/decode_engine.py``, ``paged_engine.py``) are
fast *mechanisms*: slot-based continuous batching, pipelined dispatch,
deadline/poison eviction. They have no *policy* — their admission queue
is an unbounded FIFO, so chip occupancy under live traffic is whatever
order callers happen to ``submit()`` in. ``FrontEnd`` adds the policy
half a real serving deployment needs:

- **Admission control.** A bounded queue (``PT_SERVE_QUEUE_DEPTH``)
  with a pluggable ordering policy (``PT_SERVE_ADMISSION``:
  ``fifo`` / ``priority`` / ``edf`` earliest-deadline-first). Queue
  wait counts against the request's ``deadline_s``; a request that
  expires while queued — or whose remaining headroom is already below
  the engine's observed time-to-first-token — is REJECTED at admission
  (``rejected-deadline`` status, ``serve/queue_deadline_rejects`` /
  ``serve/queue_hopeless_rejects``) instead of occupying a slot it can
  only be evicted from mid-decode. Rejection costs zero device work;
  eviction abandons a prefill.
- **Slot backfill.** The engine's ``on_retire`` hook fires from inside
  the harvest the moment a slot frees; the front-end immediately moves
  the best queued request into the engine (``serve/queue_backfill``),
  so the next dispatch is never under-occupied while work waits.
- **Dynamic bucket selection.** Under load the front-end overrides the
  engine's prefill bucket choice with :func:`dynamic_bucket`: the
  bucket minimizing the admission's *projected TTFT* given current
  occupancy (idle engine → cost is padded prefill work + per-dispatch
  overhead; busy engine → every extra prefill chunk also puts one more
  interleaved decode dispatch in the TTFT path, shifting the optimum
  toward fewer, larger chunks).
- **Streaming.** ``submit(...).stream()`` iterates tokens as harvests
  apply them (fed from the engine's ``on_token`` hook). Greedy streams
  are byte-identical to a direct ``engine.submit()`` + ``run()`` — the
  front-end only reorders admissions, never per-slot math.

Single-threaded by design, like the engines: callers pump ``step()``
(or ``run()``, or iterate a stream, which pumps internally). The
multi-replica layer lives in ``serving/router.py``.
"""

import math
import os
import time
from typing import Iterator, List, Optional

__all__ = ["FrontEnd", "ServeRequest", "dynamic_bucket",
           "projected_ttft", "RequestJournal"]

# terminal statuses a ServeRequest can reach
_TERMINAL = ("done", "failed", "rejected-queue-full",
             "rejected-deadline", "migrated")


class ServeRequest:
    """One request's front-end lifecycle. Status transitions::

        queued -> admitted -> done | failed
        queued -> rejected-queue-full | rejected-deadline
        queued | admitted -> migrated          (drain migration)

    ``rejected-*`` means the request never reached a prefill (no device
    work); ``failed`` means the engine evicted it after admission
    (deadline mid-decode, non-finite logits) — ``error`` says which.
    ``migrated`` is terminal only LOCALLY: a draining replica handed
    the request to a survivor (``detach_migrate``), which owns the
    client-visible completion from then on.
    """

    __slots__ = ("id", "prompt", "max_new_tokens", "eos_id", "priority",
                 "deadline", "t_submit", "seq", "status", "error",
                 "engine_req", "_buf", "_fe")

    def __init__(self, req_id, prompt, max_new_tokens, eos_id, priority,
                 deadline, seq, fe):
        self.id = req_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.priority = priority
        self.deadline = deadline          # absolute time.monotonic()
        self.t_submit = time.perf_counter()
        self.seq = seq
        self.status = "queued"
        self.error: Optional[str] = None
        self.engine_req = None            # inference Request once admitted
        self._buf: List[int] = []         # stream buffer (harvest order)
        self._fe = fe

    @property
    def tokens(self) -> List[int]:
        return self._buf

    @property
    def done(self) -> bool:
        return self.status in _TERMINAL

    @property
    def failed(self) -> bool:
        return self.done and self.status != "done"

    @property
    def ttft_s(self) -> Optional[float]:
        r = self.engine_req
        return None if r is None else r.ttft_s

    def stream(self) -> Iterator[int]:
        """Iterate generated tokens in harvest order, pumping the
        front-end while waiting. Ends when the request completes (or is
        rejected/evicted — check ``failed``/``error`` afterwards)."""
        i = 0
        while True:
            while i < len(self._buf):
                yield self._buf[i]
                i += 1
            if self.done:
                # a terminal request's tokens are fully applied (retire
                # happens at harvest, after the replay loop) — but the
                # buffer may have grown between the check and now
                while i < len(self._buf):
                    yield self._buf[i]
                    i += 1
                return
            self._fe.step()


def projected_ttft(engine, remaining: int, bucket: int,
                   alpha: float = 2e-3, beta: float = 2e-5) -> float:
    """Analytic TTFT projection for prefilling ``remaining`` prompt
    tokens in ``bucket``-sized chunks on ``engine`` at its CURRENT
    occupancy. ``alpha`` is the per-dispatch overhead, ``beta`` the
    per-token compute cost; only their ratio shapes the argmin, so the
    defaults need no per-hardware calibration.

    cost = chunks * (alpha + bucket * beta)            # padded prefill
         + steps  * (alpha + live * chunk * beta)      # interleaved
                                                       # decode (if any)

    where ``steps`` is how many engine steps the chunks spread over
    under the per-step prefill token budget — each step a live engine
    also dispatches one decode chunk into the TTFT path.
    """
    chunks = max(1, math.ceil(remaining / bucket))
    # the paged engine has no chunked-prefill budget (its prefill is
    # one dispatch and it never consults bucket_policy) — treat it as
    # one-bucket-per-step if projected directly
    budget = getattr(engine, "_prefill_budget", engine.buckets[-1])
    per_step = max(1, budget // bucket)
    steps = math.ceil(chunks / per_step)
    live = engine.S - engine.free_slots
    cost = chunks * (alpha + bucket * beta)
    if live > 0:
        cost += steps * (alpha + live * engine.chunk * beta)
    return cost


def dynamic_bucket(engine, remaining: int) -> int:
    """``engine.bucket_policy`` minimizing :func:`projected_ttft` over
    the engine's bucket set (ties to the smaller bucket — less padding,
    shorter peer stall)."""
    return min(engine.buckets,
               key=lambda b: (projected_ttft(engine, remaining, b), b))


def _queue_depth_default() -> int:
    return int(os.environ.get("PT_SERVE_QUEUE_DEPTH", "256"))


def _admission_default() -> str:
    return os.environ.get("PT_SERVE_ADMISSION", "priority")


class FrontEnd:
    """Admission control + backfill + streaming over ONE engine.

        fe = FrontEnd(DecodeEngine(model, ...))
        r = fe.submit(prompt, max_new_tokens=64, deadline_s=2.0)
        for tok in r.stream():
            ...

    ``admission``: ``fifo`` (arrival order), ``priority`` (higher
    ``priority=`` first, then arrival), ``edf`` (earliest absolute
    deadline first, deadline-less last). ``hopeless_factor`` scales the
    observed-TTFT bar a deadline must clear at admission (0 disables
    hopeless rejection; expiry rejection always applies).
    ``admit_ahead`` extra requests are staged into the engine's own
    queue beyond visible free slots so admission never waits a step.
    """

    def __init__(self, engine, queue_depth: Optional[int] = None,
                 admission: Optional[str] = None,
                 hopeless_factor: float = 1.0, admit_ahead: int = 1,
                 dynamic_buckets: bool = True):
        self.engine = engine
        self.queue_depth = (queue_depth if queue_depth is not None
                            else _queue_depth_default())
        self.admission = admission or _admission_default()
        if self.admission not in ("fifo", "priority", "edf"):
            raise ValueError(
                f"admission policy must be fifo|priority|edf, "
                f"got {self.admission!r}")
        self.hopeless_factor = float(hopeless_factor)
        self.admit_ahead = int(admit_ahead)
        self._queue: List[ServeRequest] = []
        self._all: List[ServeRequest] = []
        self._by_engine_req = {}        # id(engine Request) -> ServeRequest
        self._seq = 0
        self._ttft_ema: Optional[float] = None
        engine.on_token = self._on_token
        engine.on_retire = self._on_retire
        if dynamic_buckets and engine.bucket_policy is None:
            engine.bucket_policy = dynamic_bucket

    # -- submission ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None, priority: int = 0,
               req_id: Optional[str] = None) -> ServeRequest:
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        prompt = [int(t) for t in prompt]
        # infeasible requests fail HERE, not from a later pump
        self.engine.check_request(len(prompt), int(max_new_tokens))
        self._seq += 1
        req = ServeRequest(
            req_id or f"req-{self._seq:06d}", prompt,
            int(max_new_tokens), eos_id, int(priority),
            (None if deadline_s is None
             else time.monotonic() + float(deadline_s)),
            self._seq, self)
        self._all.append(req)
        if len(self._queue) >= self.queue_depth:
            req.status = "rejected-queue-full"
            req.error = (f"admission queue full "
                         f"({self.queue_depth} waiting)")
            stats.add("serve/queue_rejects")
            flight.record(req.id, "reject", reason="queue-full",
                          depth=self.queue_depth)
            return req
        self._queue.append(req)
        flight.record(req.id, "submit", prompt=len(prompt),
                      budget=int(max_new_tokens), priority=int(priority),
                      deadline_s=deadline_s)
        stats.set_value("serve/queue_len", len(self._queue))
        return req

    def submit_handoff(self, meta: dict, k, v,
                       deadline_s: Optional[float] = None,
                       req_id: Optional[str] = None,
                       t_submit: Optional[float] = None) -> ServeRequest:
        """Admit a request whose KV state was built on another replica
        — right after prefill (disaggregated serving, serving/disagg.py)
        or mid-decode (a drain migration): the engine installs the
        transferred KV pages when a slot frees and decode continues
        bit-for-bit from the handed-off state. Bypasses the admission
        queue — admission control already ran where the request first
        entered the fleet; streaming/on_token/retire hooks apply
        exactly as for local requests. A mid-decode handoff's already-
        final tokens (``meta["tokens"][:-1]``) pre-fill the stream
        buffer, so the migrated request's token stream is byte-
        identical to the unmigrated run."""
        eng = self.engine
        if not hasattr(eng, "submit_handoff"):
            raise ValueError("engine has no KV-handoff support")
        ereq = eng.submit_handoff(meta, k, v, deadline_s=deadline_s)
        self._seq += 1
        sreq = ServeRequest(
            req_id or f"req-{self._seq:06d}", list(meta["prompt"]),
            int(meta["max_new_tokens"]), meta["eos_id"], 0,
            (None if deadline_s is None
             else time.monotonic() + float(deadline_s)),
            self._seq, self)
        sreq.status = "admitted"
        sreq.engine_req = ereq
        # sender-side history: tokens[:-1] are already final (the
        # engine re-emits tokens[-1] through the harvest, landing in
        # _buf via _on_token like any locally generated token) — the
        # post-prefill disagg case is the [first]-singleton instance,
        # where this prepopulates nothing
        sreq._buf = [int(t) for t in
                     meta.get("tokens", [meta["first"]])[:-1]]
        if ereq.rid is None:
            ereq.rid = sreq.id     # local handoff (bench): no meta rid
        from paddle_tpu.observability import flight
        flight.record(sreq.id, "handoff-admitted",
                      n_tokens=int(meta["n_tokens"]),
                      generated=len(sreq._buf) + 1)
        if t_submit is not None:
            # same-process disaggregation (bench): TTFT counts from the
            # ORIGINAL arrival, not the handoff install — perf_counter
            # is only comparable within one process, so cross-process
            # callers leave this unset
            sreq.t_submit = t_submit
        ereq.t_submit = sreq.t_submit
        self._all.append(sreq)
        self._by_engine_req[id(ereq)] = sreq
        return sreq

    def detach_migrate(self, sreq: ServeRequest):
        """Extract one open request for a drain migration (the sending
        half; serving/router.py drives this for every open request on
        a draining replica). Returns

        - ``None`` — the request can't move right now (mid-prefill, no
          token yet, or it completed while the pipeline drained):
          finish it in place and retry/publish next loop iteration;
        - ``{"kv": False}`` — it was still queued (front-end queue or
          the engine's own staging deque), no device state to carry:
          the router re-places it from scratch;
        - ``{"kv": True, "meta":, "k":, "v":}`` — it held a slot
          mid-decode: the engine detached its KV rows + token history
          (``engine.detach_handoff``) for a survivor to continue
          bit-for-bit.

        On the non-None paths the request is locally terminal
        (status ``migrated``) and already off every queue/slot."""
        from paddle_tpu import stats
        if sreq.done:
            return None
        if sreq.status == "queued":
            try:
                self._queue.remove(sreq)
            except ValueError:
                return None
            sreq.status = "migrated"
            stats.set_value("serve/queue_len", len(self._queue))
            return {"kv": False}
        ereq = sreq.engine_req
        eng = self.engine
        if ereq is None or not hasattr(eng, "detach_handoff"):
            return None
        if ereq in eng._waiting:
            # staged ahead into the engine's queue: prefill never ran
            eng._waiting.remove(ereq)
            self._by_engine_req.pop(id(ereq), None)
            sreq.status = "migrated"
            return {"kv": False}
        # harvest the pipeline FIRST, while the retire/token hooks are
        # still wired: tokens landing here must reach sreq._buf, and a
        # request that completes during the drain must retire normally
        eng._drain()
        if ereq.done or not ereq.tokens:
            return None
        # detach fires the retire hook path (_obs_request_end) — unhook
        # first so the migrating request is not marked done
        self._by_engine_req.pop(id(ereq), None)
        try:
            meta, k, v = eng.detach_handoff(ereq)
        except ValueError:
            self._by_engine_req[id(ereq)] = sreq
            return None
        sreq.status = "migrated"
        return {"kv": True, "meta": meta, "k": k, "v": v}

    # -- engine hooks -------------------------------------------------------

    def _on_token(self, ereq, token: int):
        sreq = self._by_engine_req.get(id(ereq))
        if sreq is not None:
            sreq._buf.append(token)

    def _on_retire(self, ereq):
        """Engine-side request end (fires from inside the harvest):
        finalize the front-end record, fold its TTFT into the hopeless-
        rejection estimate, and BACKFILL the freed slot from the queue
        at once — the next dispatch must not run under-occupied while
        admissible work waits."""
        from paddle_tpu import stats
        sreq = self._by_engine_req.pop(id(ereq), None)
        if sreq is not None:
            if ereq.error is None:
                sreq.status = "done"
            else:
                # a request staged ahead into the engine's own queue
                # that expired THERE is still a queue reject (the
                # engine counted it on the queue-reject counter)
                sreq.status = ("rejected-deadline"
                               if "while queued" in ereq.error
                               else "failed")
                sreq.error = ereq.error
            if ereq.ttft_s is not None:
                self._ttft_ema = (
                    ereq.ttft_s if self._ttft_ema is None
                    else 0.8 * self._ttft_ema + 0.2 * ereq.ttft_s)
        if self._queue and self._feed() > 0:
            stats.add("serve/queue_backfill")

    # -- admission ----------------------------------------------------------

    def _order_key(self, r: ServeRequest):
        if self.admission == "priority":
            return (-r.priority, r.seq)
        if self.admission == "edf":
            return (r.deadline if r.deadline is not None else math.inf,
                    r.seq)
        return (r.seq,)

    def _reject(self, req: ServeRequest, reason: str, stat: str):
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        req.status = "rejected-deadline"
        req.error = reason
        stats.add(stat)
        flight.record(req.id, "reject", reason=reason, stat=stat)

    def _ttft_estimate(self, req: ServeRequest) -> float:
        """The TTFT bar the hopeless screen judges ``req`` against.
        Before ANY observation lands, the EMA seeds from
        :func:`projected_ttft` of the smallest covering bucket — the
        same analytic model the bucket policy trusts — instead of an
        empty/zero estimate. Cold start therefore neither waves every
        request through (the old ``ema is None`` bypass let a 1ms-
        deadline request reach prefill and be evicted mid-flight, paid
        device work) nor rejects reasonable deadlines spuriously (the
        projection is a per-request lower-ish bound, not a loaded-
        system percentile)."""
        if self._ttft_ema is not None:
            return self._ttft_ema
        eng = self.engine
        n = len(req.prompt)
        bucket = next((b for b in eng.buckets if b >= n),
                      eng.buckets[-1])
        return projected_ttft(eng, n, bucket)

    def _admissible(self, req: ServeRequest) -> bool:
        """Deadline screen at the queue->engine boundary: queue wait
        already spent counts against the budget, and a budget below the
        engine's observed TTFT (EMA; cold start seeds from the
        analytic projection — see ``_ttft_estimate``) is hopeless —
        reject it here, for free, instead of letting the engine evict
        it mid-decode."""
        if req.deadline is None:
            return True
        headroom = req.deadline - time.monotonic()
        if headroom <= 0:
            self._reject(req, "deadline exceeded while queued",
                         "serve/queue_deadline_rejects")
            return False
        est = self._ttft_estimate(req)
        if (self.hopeless_factor > 0
                and headroom < self.hopeless_factor * est):
            self._reject(
                req, f"deadline hopeless at admission: "
                     f"{headroom * 1e3:.0f}ms budget vs "
                     f"~{est * 1e3:.0f}ms "
                     f"{'observed' if self._ttft_ema is not None else 'projected'} TTFT",
                "serve/queue_hopeless_rejects")
            return False
        return True

    def _feed(self, capacity: Optional[int] = None) -> int:
        """Move the best queued requests into the engine while it has
        room (free slots plus ``admit_ahead`` staged). Returns how many
        were admitted."""
        from paddle_tpu import stats
        eng = self.engine
        if capacity is None:
            capacity = (eng.free_slots + self.admit_ahead - eng.queued)
        admitted = 0
        # one sort per feed: the ordering keys (priority / absolute
        # deadline / arrival seq) are immutable while queued
        self._queue.sort(key=self._order_key)
        while capacity > 0 and self._queue:
            req = self._queue.pop(0)
            if not self._admissible(req):
                continue
            ereq = eng.submit(
                req.prompt, max_new_tokens=req.max_new_tokens,
                eos_id=req.eos_id,
                deadline_s=(None if req.deadline is None
                            else req.deadline - time.monotonic()),
                req_id=req.id)
            # TTFT must count the front-end queue wait: re-anchor the
            # engine request's clock to the front-end submission
            ereq.t_submit = req.t_submit
            req.engine_req = ereq
            req.status = "admitted"
            self._by_engine_req[id(ereq)] = req
            stats.observe("serve/queue_wait_s",
                          time.perf_counter() - req.t_submit)
            capacity -= 1
            admitted += 1
        stats.set_value("serve/queue_len", len(self._queue))
        return admitted

    def _sweep_expired(self):
        """Reject queued requests whose deadline passed — they must
        never reach a prefill."""
        now = time.monotonic()
        expired = [r for r in self._queue
                   if r.deadline is not None and now > r.deadline]
        for req in expired:
            self._queue.remove(req)
            self._reject(req, "deadline exceeded while queued",
                         "serve/queue_deadline_rejects")

    # -- pump ---------------------------------------------------------------

    def step(self) -> int:
        """One service iteration: reject expired queue entries, feed
        free capacity, advance the engine one step (which may backfill
        more via ``on_retire``). Returns tokens applied this call.

        ``serve/fed_occupancy`` samples batch occupancy only on steps
        whose demand exceeded the free slots — exactly the steps where
        a scheduler that trickles singletons (no backfill, serial
        admission) diverges from one that keeps the pipeline fed
        (stays near 1.0 minus the lag-one backfill step)."""
        from paddle_tpu import stats
        self._sweep_expired()
        self._feed()
        eng = self.engine
        backlogged = (len(self._queue) + eng.queued) > eng.free_slots
        n = eng.step()
        if backlogged:
            stats.observe("serve/fed_occupancy",
                          (eng.S - eng.free_slots) / eng.S)
        return n

    @property
    def busy(self) -> bool:
        eng = self.engine
        return bool(self._queue or eng.queued
                    or eng.free_slots < eng.S or eng._pending)

    def run(self) -> None:
        """Serve until every submitted request is terminal."""
        while self.busy:
            self.step()
        self.engine.drain()

    def results(self) -> List[ServeRequest]:
        return list(self._all)


class RequestJournal:
    """FrontEnd-side request journal: the durable half of router
    failover (docs/fleet-ha.md).

    The router's in-memory placement state is disposable — replicas
    hold the real work — but the *intake* is not: a request accepted
    from a client must survive the router process. The journal is an
    append-only JSONL file the submitting side writes before placement
    and after every terminal result::

        {"kind": "submit", "id": "rq-000007", "prompt": [...], ...}
        {"kind": "result", "id": "rq-000007", "result": {...}}

    A restarted router replays it (:meth:`replay`): payloads without a
    terminal result are re-placed (at-least-once — the PR 9
    redistribution idiom across router generations; first result wins),
    payloads with one are already answered. ``flush()`` after every
    append puts records in the OS page cache, which survives a router
    SIGKILL (the failure this protects against); host crashes are the
    checkpoint layer's problem, not the serving plane's.
    """

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", encoding="utf-8")

    def append_submit(self, payload: dict) -> None:
        import json
        rec = {"kind": "submit"}
        rec.update(payload)
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def append_result(self, req_id: str, result: dict) -> None:
        import json
        self._f.write(json.dumps(
            {"kind": "result", "id": req_id, "result": result}) + "\n")
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass

    @staticmethod
    def replay(path: str):
        """Parse a journal → ``(payloads, results)``: ``payloads`` maps
        req_id → the original submit payload (journal bookkeeping keys
        stripped), ``results`` maps req_id → its terminal result. A
        torn final line (SIGKILL mid-append) is skipped — every
        *complete* record before it is intact."""
        import json
        payloads, results = {}, {}
        try:
            f = open(path, "r", encoding="utf-8")
        except OSError:
            return payloads, results
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue        # torn tail record
                if rec.get("kind") == "submit" and "id" in rec:
                    p = {k: v for k, v in rec.items() if k != "kind"}
                    payloads[rec["id"]] = p
                elif rec.get("kind") == "result" and "id" in rec:
                    results[rec["id"]] = rec.get("result") or {}
        return payloads, results
